"""Benchmark: Atari env-steps/sec/chip (BASELINE.json metric).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline denominator: the north-star is "matching the original 64-node CPU
cluster's env-steps/sec on one host" (BASELINE.json). The reference published
no throughput number we could verify (mount empty, BASELINE.json `published`
== {}); BASELINE.md records the recalled-UNVERIFIED cluster figure of
~80k agent-steps/sec across 64 nodes for the 21-minute Atari runs. We use
that 80_000 as the vs_baseline denominator until a verified figure exists.

What is measured: sustained learner train-step throughput on the real chip —
transitions consumed per second per chip (one transition == one agent-level
env step: an 84x84x4 uint8 state + action + n-step return, exactly what the
reference's FIFOQueue feeds per sample). Host->device transfer of fresh uint8
batches is included so the number reflects the full feed path, not just the
matmul time. When the fused on-device env path lands, this script switches to
measuring true emulator-steps/sec.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

BASELINE_ENV_STEPS_PER_SEC = 80_000.0  # recalled 64-node cluster rate, UNVERIFIED


def bench_learner(batch_size: int = 1024, steps: int = 30) -> dict:
    import optax

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.parallel.train_step import (
        create_train_state,
        make_train_step,
    )

    n_chips = len(jax.devices())
    cfg = BA3CConfig(batch_size=batch_size * n_chips)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    optimizer = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adam(cfg.learning_rate, eps=cfg.adam_epsilon),
    )
    mesh = make_mesh(num_data=n_chips, num_model=1)
    state = create_train_state(jax.random.PRNGKey(0), model, cfg, optimizer)
    step = make_train_step(model, optimizer, cfg, mesh)
    state = jax.device_put(state, step.state_sharding)

    rng = np.random.default_rng(0)
    # Pre-generate host batches (double-buffer style: alternate two buffers so
    # the device never waits on host RNG, but transfer cost stays measured).
    host_batches = []
    for _ in range(2):
        host_batches.append(
            {
                "state": rng.integers(
                    0, 255, (cfg.batch_size, *cfg.state_shape), dtype=np.uint8
                ),
                "action": rng.integers(
                    0, cfg.num_actions, (cfg.batch_size,), dtype=np.int32
                ),
                "return": rng.normal(size=(cfg.batch_size,)).astype(np.float32),
            }
        )

    def put(b):
        return {k: jax.device_put(v, step.batch_sharding) for k, v in b.items()}

    # warmup / compile
    state, metrics = step(state, put(host_batches[0]), cfg.entropy_beta)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, put(host_batches[i % 2]), cfg.entropy_beta)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    sps = steps * cfg.batch_size / dt
    per_chip = sps / n_chips
    return {
        "metric": "learner_train_samples_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_ENV_STEPS_PER_SEC, 3),
    }


def main():
    result = bench_learner()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
