"""Benchmark: Atari env-steps/sec/chip (BASELINE.json metric).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

What is measured: the fused on-device actor+learner loop (envs, rendering,
policy forward, sampling, n-step returns, loss, grads, Adam — one jitted
program, distributed_ba3c_tpu/fused/) on pure-JAX Pong, counting AGENT steps
(each = 4 physics substeps, ALE frameskip parity). This is the path that
replaces the reference's 64-node CPU cluster: its whole pipeline (ALE procs →
ZMQ → predictor → FIFOQueue → PS updates, SURVEY.md §3) collapses into this
one computation.

Baseline denominator: BASELINE.json's north-star is "matching the original
64-node CPU cluster's env-steps/sec on one host". The reference published no
verifiable throughput number (mount empty; BASELINE.json `published` == {});
BASELINE.md records the recalled-UNVERIFIED figure of ~80k agent-steps/sec
across the 64-node cluster for the 21-minute runs. vs_baseline uses that
80_000 until a verified figure exists. (The secondary metric — wall-clock to
Pong >= 18 — is tracked separately in full training runs' stat.json, not in
this number.)
"""

from __future__ import annotations

import json
import time

import jax

BASELINE_ENV_STEPS_PER_SEC = 80_000.0  # recalled 64-node cluster rate, UNVERIFIED


def bench_fused(n_envs: int = 1024, rollout_len: int = 20, iters: int = 20) -> dict:
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    n_chips = len(jax.devices())
    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    step = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=rollout_len)
    state = create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong,
        n_envs * n_chips, n_shards=n_chips,
    )
    state = step.put(state)

    # warmup / compile; fetch a VALUE (block_until_ready alone does not
    # drain the async queue through the tunneled-TPU PJRT client)
    state, metrics = step(state, cfg.entropy_beta)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, cfg.entropy_beta)
    float(metrics["loss"])  # full sync: last iter depends on all prior state
    dt = time.perf_counter() - t0

    env_steps = iters * n_envs * n_chips * rollout_len
    host_rate = env_steps / dt
    per_chip = host_rate / n_chips
    return {
        "metric": "fused_pong_env_steps_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "env-steps/sec/chip",
        # north-star compares the HOST-aggregate rate to the 64-node cluster
        "vs_baseline": round(host_rate / BASELINE_ENV_STEPS_PER_SEC, 3),
    }


def main():
    print(json.dumps(bench_fused()))


if __name__ == "__main__":
    main()
