"""Benchmark: Atari env-steps/sec/chip (BASELINE.json metric).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

What is measured: the fused on-device actor+learner loop (envs, rendering,
policy forward, sampling, n-step returns, loss, grads, Adam — one jitted
program, distributed_ba3c_tpu/fused/) on pure-JAX Pong, counting AGENT steps
(each = 4 physics substeps, ALE frameskip parity). This is the path that
replaces the reference's 64-node CPU cluster: its whole pipeline (ALE procs →
ZMQ → predictor → FIFOQueue → PS updates, SURVEY.md §3) collapses into this
one computation.

Baseline denominator: BASELINE.json's north-star is "matching the original
64-node CPU cluster's env-steps/sec on one host". The reference published no
verifiable throughput number (mount empty; BASELINE.json `published` == {});
BASELINE.md records the recalled-UNVERIFIED figure of ~80k agent-steps/sec
across the 64-node cluster for the 21-minute runs. vs_baseline uses that
80_000 until a verified figure exists. (The secondary metric — wall-clock to
Pong >= 18 — is tracked separately in full training runs' stat.json, not in
this number.)
"""

from __future__ import annotations

import json
import os
import time

import jax

BASELINE_ENV_STEPS_PER_SEC = 80_000.0  # recalled 64-node cluster rate, UNVERIFIED

# bf16 peak FLOP/s by device kind — the MFU denominator. Only kinds this
# project has actually run on; unknown kinds report mfu=null rather than a
# made-up denominator.
_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e datasheet bf16
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
}


def _mfu(per_chip_rate: float, entries: tuple = ("fused.step",)) -> dict:
    """Model FLOPs utilization of the measured program(s) at the given rate.

    Numerator: the audit manifest's PINNED per-sample FLOPs for the given
    entry point(s) (tools/ba3caudit T5 — canonical shape 4 envs x 4 rollout
    = 16 samples/step; conv/matmul cost scales linearly in samples, and the
    per-update fixed terms (Adam, bookkeeping) are <0.01 us/sample at real
    shapes, PERF.md round 3). Keeping the numerator manifest-pinned means
    MFU moves only when the measured RATE moves — a program change that
    alters FLOPs shows up as a T5 audit finding first.

    Overlap mode passes BOTH registered programs — ``("fused.actor",
    "fused.learner")`` — and their FLOPs are SUMMED: a single-manifest
    lookup would undercount the actor program's rollout forwards, inflating
    the reported MFU exactly when the split is being judged.
    """
    try:
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "audit_manifest.json")
        ) as fh:
            manifest = json.load(fh)
        flops = sum(float(manifest[e]["flops"]) for e in entries)
        # inside the try: an un-importable audit module (jax drift the
        # shims don't cover) must degrade to mfu=null, not kill the bench
        from distributed_ba3c_tpu.audit import CANONICAL_MESH_DEVICES
    except (OSError, KeyError, ValueError, ImportError):
        return {"mfu": None}

    canonical_samples = (2 * CANONICAL_MESH_DEVICES) * 4  # n_envs x rollout
    per_sample = flops / canonical_samples
    kind = jax.devices()[0].device_kind
    peak = _PEAK_FLOPS.get(kind)
    out = {
        "flops_per_sample": round(per_sample, 1),
        "device_kind": kind,
    }
    if peak is None:
        out["mfu"] = None  # unknown silicon: no honest denominator
    else:
        out["mfu"] = round(per_chip_rate * per_sample / peak, 4)
    return out


def bench_fused(
    n_envs: int = 128,
    rollout_len: int = 20,
    iters: int = 200,
    steps_per_dispatch: int | None = None,
) -> dict:
    """Measures the FLAGSHIP TRAINING SHAPE (128 envs x 20 rollout — the
    batch the round-3 sample-efficiency ladder settled on; RESULTS.md).

    Round 4: by default each window is ONE scanned program of `iters`
    updates (--steps_per_dispatch mechanics), so the measured rate is pure
    device throughput — no dependence on host dispatch pipelining racing
    the tunnel (VERDICT r3 weak #1; scan-vs-sequential parity is tested,
    and the scanned rate matched pipelined-K=1 within 0.5% when measured
    clean, PERF.md round 4). Passing steps_per_dispatch=K < iters instead
    runs iters/K pipelined host dispatches of a K-step program per window
    (the K-sweep, scripts/ksweep_bench.py) — at K=1 that is deliberately
    the round-3 pipelined methodology, host dispatch and all. Best-of-3
    windows remains as a tunnel-health filter either way: a wedged window
    still reads slow through the final sync.
    The round-1/2 bench shape (4096x40, 10 iters) measured 62.9k; the
    round-3 pipelined measurement at this shape was 65.9k; the shape grid
    lives in scripts/profile_fused.py."""
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    n_chips = len(jax.devices())
    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    # default: ONE dispatch per window (iters updates in a single scanned
    # program). steps_per_dispatch=K overrides for the K-sweep
    # (scripts/ksweep_bench.py): iters/K dispatches per window, same sync
    # and best-of-N policy either way.
    K = iters if steps_per_dispatch is None else steps_per_dispatch
    if K < 1 or iters % K != 0:
        raise ValueError(
            f"steps_per_dispatch={K} must be >= 1 and divide iters={iters}"
        )
    step = make_fused_step(
        model, opt, cfg, mesh, pong, rollout_len=rollout_len,
        steps_per_dispatch=K,
    )
    state = create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong,
        n_envs * n_chips, n_shards=n_chips,
    )
    state = step.put(state)

    # warmup / compile; fetch a VALUE (block_until_ready alone does not
    # drain the async queue through the tunneled-TPU PJRT client)
    state, metrics = step(state, cfg.entropy_beta)
    float(metrics["loss"])

    # best of 3 windows: the dev tunnel intermittently degrades (PERF.md) —
    # a stalled window reads 10-20x slow; the chip's sustained rate is the
    # best clean window (each window fully syncs via the loss fetch)
    window_dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters // K):
            state, metrics = step(state, cfg.entropy_beta)
        float(metrics["loss"])  # full sync on the whole scanned window
        window_dts.append(time.perf_counter() - t0)
    best_dt = min(window_dts)

    env_steps = iters * n_envs * n_chips * rollout_len
    host_rate = env_steps / best_dt
    per_chip = host_rate / n_chips
    # account the measured work in the learner registry so the embedded
    # telemetry snapshot below reflects this run (docs/observability.md)
    from distributed_ba3c_tpu import telemetry

    # 1 warmup step + 3 timed windows of `iters` updates
    telemetry.registry("learner").counter("train_steps_total").inc(
        3 * iters + 1
    )
    telemetry.registry("learner").counter("train_samples_total").inc(
        (3 * iters + 1) * n_envs * n_chips * rollout_len
    )
    return {
        "metric": "fused_pong_env_steps_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "env-steps/sec/chip",
        # north-star compares the HOST-aggregate rate to the 64-node cluster
        "vs_baseline": round(host_rate / BASELINE_ENV_STEPS_PER_SEC, 3),
        # MFU pins the 0.8x plateau to silicon utilization (VERDICT r5 #3):
        # manifest-pinned FLOPs/sample x measured rate / bf16 peak
        **_mfu(per_chip),
        # methodology (ADVICE r3): shape + best-of-N policy are part of the
        # number — without them BENCH_r{N}.json files are not comparable
        "n_envs": n_envs,
        "rollout_len": rollout_len,
        "iters": iters,
        "steps_per_dispatch": K,
        "policy": f"best_of_3_windows, {iters // K} scanned dispatch(es) per window",
        "window_rates": [round(env_steps / dt, 1) for dt in window_dts],
        "telemetry": _tele_snapshot(),
    }


def bench_overlap(
    n_envs: int = 128,
    rollout_len: int = 20,
    iters: int = 200,
    rollout_dtype: str = "float32",
    probe_reps: int = 5,
) -> dict:
    """Overlapped two-program mode (--overlap): rollout k+1 dispatched
    concurrently with learner k, lag-1 V-trace (fused/overlap.py,
    docs/overlap.md). Same flagship shape, window policy and sync contract
    as ``bench_fused``; each window is ``iters`` async actor/learner
    dispatch pairs with one metrics fetch at the end.

    Extra first-class fields vs the fused row (ISSUE 8 satellite):

    - ``mfu`` sums the manifest FLOPs of BOTH registered programs
      (``fused.actor`` + ``fused.learner``) — the actor's rollout forwards
      are real work the chip does; a fused.step-only lookup would
      undercount it.
    - ``program_latency``: per-program wall-time MEDIANS from the overlap
      probe (the same numbers published as tele/learner/actor_program_ms,
      learner_program_ms, overlap_pair_ms gauges), plus
      ``overlap_efficiency`` — the measured learner-hidden fraction of the
      actor program, (t_actor + t_learner - t_pair) / t_actor — and
      ``learner_window_coverage`` — min(1, t_learner/t_actor), the
      device-free proxy gate quantity (how much of the actor's wall time
      the learner window is LONG enough to hide; realized hiding requires
      an execution backend with concurrent queues, PERF.md round 9).
    """
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    n_chips = len(jax.devices())
    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    step = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=rollout_len,
        steps_per_dispatch=iters, rollout_dtype=rollout_dtype,
    )
    state = step.put(create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong,
        n_envs * n_chips, n_shards=n_chips,
    ))

    # warmup / compile all programs; fetch a VALUE (same contract as
    # bench_fused — block_until_ready alone does not drain the queue
    # through the tunneled-TPU PJRT client). One facade call = `iters`
    # pairs; acceptable as warmup since the windows below re-measure.
    state, metrics = step(state, cfg.entropy_beta)
    float(metrics["loss"])

    # per-program latencies + overlap efficiency: the ONE sanctioned
    # sync-between-dispatches site (fused/overlap.py probe_overlap) —
    # medians over probe_reps, published as telemetry gauges too
    state, probe = step.probe_overlap(
        state, cfg.entropy_beta, reps=probe_reps
    )

    window_dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        state, metrics = step(state, cfg.entropy_beta)
        float(metrics["loss"])  # full sync on the whole window
        window_dts.append(time.perf_counter() - t0)
    best_dt = min(window_dts)

    env_steps = iters * n_envs * n_chips * rollout_len
    host_rate = env_steps / best_dt
    per_chip = host_rate / n_chips
    from distributed_ba3c_tpu import telemetry

    telemetry.registry("learner").counter("train_steps_total").inc(4 * iters)
    telemetry.registry("learner").counter("train_samples_total").inc(
        4 * iters * n_envs * n_chips * rollout_len
    )
    return {
        "metric": "overlap_pong_env_steps_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(host_rate / BASELINE_ENV_STEPS_PER_SEC, 3),
        # BOTH programs' manifest FLOPs — see docstring
        **_mfu(per_chip, entries=("fused.actor", "fused.learner")),
        "program_latency": probe,
        # computed by probe_overlap itself so every consumer reports the
        # same gate number (fused/overlap.py)
        "learner_window_coverage": probe["learner_window_coverage"],
        "rollout_dtype": rollout_dtype,
        "lag": step.lag,
        "n_envs": n_envs,
        "rollout_len": rollout_len,
        "iters": iters,
        "policy": "best_of_3_windows, "
                  f"{iters} async actor/learner pairs per window",
        "window_rates": [round(env_steps / dt, 1) for dt in window_dts],
        "telemetry": _tele_snapshot(),
    }


def _tele_snapshot() -> dict:
    """Compact final telemetry snapshot embedded in every bench JSON:
    counters/gauges as scalars per role (histograms as _count/_sum)."""
    from distributed_ba3c_tpu import telemetry

    return {
        role: reg.scalars()
        for role, reg in sorted(telemetry.all_registries().items())
        if reg.scalars()
    }


def make_null_predictor(model, params, n_actions: int, service_s: float = 0.0,
                        **kw):
    """A BatchedPredictor whose 'device' is host numpy: identical queueing,
    continuous-batching scheduler, deadline/shed machinery and callbacks —
    only the dispatch/fetch pair is replaced by thread-safe host-side
    random actions. The plane's own ceiling measurement (PERF.md;
    scripts/plane_bench.py) uses this to take the device (and, on this rig,
    the tunnel RTT) out of the loop.

    ``service_s`` > 0 simulates a device that takes that long PER CALL
    (slept at fetch time, like a real serialized device queue) — the knob
    ``scripts/serving_bench.py`` uses to give the latency frontier a real
    service-time axis on a device-free host."""
    import threading
    import time as _time

    import numpy as np

    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    class _NullDevicePredictor(BatchedPredictor):
        """Identical scheduler machinery; the 'device' is host numpy."""

        def __init__(self, *a, **kws):
            super().__init__(*a, **kws)
            self._null_rng = np.random.default_rng(0)
            # numpy Generators are not thread-safe and the sync
            # predict_batch path can race the scheduler thread here (the
            # real predictor guards its PRNG key with a lock — keep the
            # invariant)
            self._null_lock = threading.Lock()

        def _dispatch(self, params, batch):
            # 'dispatch' computes eagerly on host; 'fetch' pays the
            # simulated device time, so the depth-2 pipeline sees the
            # same serialized-device timing a real backend gives it
            k = np.asarray(batch).shape[0]
            with self._null_lock:
                acts = self._null_rng.integers(0, n_actions, k).astype(
                    np.int32
                )
            vals = np.zeros(k, np.float32)
            logp = np.full(k, -np.log(n_actions), np.float32)
            return k, (acts, vals, logp, acts)

        def _collect(self, handle):
            if service_s > 0:
                _time.sleep(service_s)
            return handle[1]

    return _NullDevicePredictor(model, params, **kw)


def _role_scalars(base: str) -> dict:
    """Summed counters/gauges over ``base`` AND its dotted sub-roles
    (``master`` + ``master.f0``/``master.f1``/... — telemetry.fleet_role;
    ``pod`` + ``pod.host0``/``pod.host1``/... — pod/wire.py pod_role):
    the bench's progress/attribution reads must see the WHOLE plane, not
    one fleet (or one actor host) of it."""
    from distributed_ba3c_tpu import telemetry

    out: dict = {}
    for role, reg in telemetry.all_registries().items():
        if role != base and not role.startswith(f"{base}."):
            continue
        for name, v in reg.scalars().items():
            out[name] = out.get(name, 0.0) + v
    return out


def _master_progress() -> tuple:
    """(wire messages, datapoints) from the master registries — the plane's
    provable forward motion, read lock-free off the live counters."""
    s = _role_scalars("master")
    msgs = (
        s.get("per_env_msgs_total", 0)
        + s.get("block_msgs_total", 0)
        + s.get("block_shm_msgs_total", 0)
    )
    return msgs, s.get("datapoints_total", 0)


def stall_attribution() -> str:
    """Name the dead stage from the real counters (the bare time threshold
    used to be the whole diagnosis; now it only opens the case). Public:
    scripts/chaos_bench.py attributes its own warmup failures with it."""
    from distributed_ba3c_tpu import telemetry

    m = _role_scalars("master")
    p = _role_scalars("predictor")
    msgs, dps = _master_progress()
    depth = m.get("train_queue_depth", 0)
    parts = (
        f"wire_msgs={msgs:.0f} datapoints={dps:.0f} "
        f"train_queue_depth={depth:.0f} "
        f"predictor_batches={p.get('batches_total', 0):.0f} "
        f"blocked_puts={m.get('queue_blocked_puts_total', 0):.0f}"
    )
    if not telemetry.enabled():
        return f"telemetry disabled, no attribution ({parts})"
    if msgs == 0:
        return f"no wire traffic: env servers never connected or died ({parts})"
    if p.get("batches_total", 0) == 0:
        return f"wire traffic but predictor never served ({parts})"
    if dps == 0:
        return f"predictor serving but no datapoints: flush path stalled ({parts})"
    return f"plane went quiet after progress ({parts})"


#: private alias kept so staged callers keep working (same
#: convention as devicelock.stderr_print)
_stall_attribution = stall_attribution


def bench_zmq_plane(
    game: str = "pong", n_envs: int = 256, seconds: float = 20.0,
    null_device: bool = False, wire: str = "per-env",
    envs_per_proc: int = 32, warmup_datapoints: int = 512,
    windows: int = 1, telemetry_on: bool = True, fleets: int = 1,
    trace_sample: int = 0,
) -> dict:
    """Actor-plane throughput (BASELINE configs #1/#2): C++ batched env
    servers -> ZMQ -> master -> batched TPU predictor, counting n-step
    datapoints entering the train queue. Run via `python bench.py --plane zmq`
    (the driver's default invocation stays the fused line); the dedicated
    plane instrument with both wires and both predictors in one JSON is
    ``scripts/plane_bench.py``.

    ``null_device=True`` (``--plane zmq-null``) swaps the device forward for
    host-side random actions while keeping EVERY other stage — C++ envs,
    serialization, ZMQ transport, master routing, batching/coalesce,
    n-step assembly. That measures the plane's own ceiling with no device
    (and, on this rig, no tunnel RTT) in the loop: the number that separates
    "the plane is slow" from "the tunneled device is slow" (PERF.md).

    ``wire`` selects the env-server protocol: ``per-env`` (the reference's
    B-messages-per-step shape, the historical 2,128/s ceiling) or ``block``
    (one zero-copy multipart message per server per step,
    docs/actor_plane.md).

    ``fleets`` > 1 stands up K INDEPENDENT planes at the SAME per-fleet
    shape — per-fleet pipes/masters/predictors/telemetry roles, fleet-
    tagged idents (actors/fleet.py addressing) — and counts the AGGREGATE
    datapoint rate across their train queues: the device-free proof of the
    multi-fleet macro-batching scaling claim (``plane_bench --fleets``;
    ``n_envs``/``envs_per_proc`` stay per-fleet quantities)."""
    import queue
    import tempfile

    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.actors.fleet import fleet_pipes
    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs import native
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    # per-run telemetry accounting: fresh registries, and the A/B switch
    # for the overhead gate (scripts/plane_bench.py --telemetry both).
    # Children inherit the env var through spawn.
    telemetry.reset_all()
    telemetry.set_enabled(telemetry_on)
    os.environ["BA3C_TELEMETRY"] = "1" if telemetry_on else "0"
    # the trace plane's A/B lever rides the same pattern (plane_bench
    # --trace both): sampling armed here for the master/predictor side,
    # via the env var for the spawned env servers
    trace_n = trace_sample if telemetry_on else 0
    telemetry.tracing.set_sampling(trace_n)
    os.environ["BA3C_TRACE"] = str(trace_n)

    n_actions = native.CppBatchedEnv(game, 1).num_actions
    cfg = BA3CConfig(num_actions=n_actions, predict_batch_size=256)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    # 2 worker threads (measured best on the tunneled dev chip: more threads
    # fragment batches without overlapping the serialized link). Coalescing
    # exists to multiply TINY per-env tasks per device call; a block already
    # IS a full batch, so block wires serve greedily (waiting would only
    # add latency to the lockstep round trip).
    coalesce_ms = 5.0 if wire == "per-env" else 0.0
    predict_bs = max(cfg.predict_batch_size, envs_per_proc)
    tmp = tempfile.mkdtemp(prefix="ba3c-bench-")
    base_c2s, base_s2c = f"ipc://{tmp}/c2s", f"ipc://{tmp}/s2c"
    per = envs_per_proc
    predictors, masters, procs = [], [], []
    for k in range(max(1, fleets)):
        tag = k if fleets > 1 else None
        c2s, s2c = fleet_pipes(base_c2s, base_s2c, k)
        if null_device:
            predictor = make_null_predictor(
                model, params, n_actions,
                batch_size=predict_bs, num_threads=2,
                coalesce_ms=coalesce_ms,
                tele_role=telemetry.fleet_role("predictor", tag),
            )
        else:
            predictor = BatchedPredictor(  # ba3clint: disable=A14 — the RAW single plane is the measurand here (the routed plane has its own instrument, serving_bench --replicas)
                model, params, batch_size=predict_bs, num_threads=2,
                coalesce_ms=coalesce_ms,
                tele_role=telemetry.fleet_role("predictor", tag),
            )
            predictor.warmup(cfg.state_shape)
        master = BA3CSimulatorMaster(
            c2s, s2c, predictor,
            gamma=cfg.gamma, local_time_max=cfg.local_time_max,
            score_queue=queue.Queue(maxsize=100_000),
            tele_role=telemetry.fleet_role("master", tag),
        )
        predictors.append(predictor)
        masters.append(master)
        procs += [
            # the RAW unsupervised plane is the measurand here (no respawn
            # machinery in the loop); the supervised path has its own
            # instrument, scripts/chaos_bench.py
            native.CppEnvServerProcess(  # ba3clint: disable=A8
                i, c2s, s2c, game=game, n_envs=min(per, n_envs - i * per),
                wire=wire,
                ident_prefix=(
                    f"f{k}-cppsim-{i}" if fleets > 1 else None
                ),
            )
            for i in range((n_envs + per - 1) // per)
        ]
    for predictor in predictors:
        predictor.start()
    for master in masters:
        master.start()
    for p in procs:
        p.start()
    try:
        # warmup until the pipeline flows, then count datapoints over
        # best-of-N windows (the sandbox scheduler intermittently starves
        # a window the way the TPU tunnel does for bench_fused — a slow
        # window is scheduler noise, not plane rate). First-datapoint
        # timeout is generous: spawning the server fleet re-imports
        # numpy/zmq per process and takes minutes under load
        # (tests/test_native_env.py saw the same)
        try:
            # EVERY fleet must produce before the clock starts (an
            # aggregate-only warmup would let a dead fleet hide behind a
            # healthy one and publish a fake per-fleet scaling number)
            for master in masters:
                master.queue.get(timeout=300)
            for _ in range(warmup_datapoints - len(masters)):
                masters[_ % len(masters)].queue.get(timeout=60)
        except queue.Empty:
            # a bare Empty says "timeout"; the counters say WHICH stage
            # never moved (fleet spawn, predictor serve, flush) — the
            # difference between a mystery and a diagnosis when a fleet
            # shape fails to come up (docs/observability.md)
            raise RuntimeError(
                f"plane produced no warmup data — {stall_attribution()}"
            ) from None
        window_rates = []
        qs = [m.queue for m in masters]
        for _ in range(max(1, windows)):
            t0 = time.perf_counter()
            deadline = t0 + seconds
            n = 0
            empty_since = None
            # drain in BURSTS (get_nowait + short sleeps) rather than
            # blocking get() per item: a consumer parked in the queue's
            # condition variable makes every producer put() pay a futex
            # wake — tens of us of syscall on sandboxed kernels, which at
            # 40k datapoints/s would dominate the measurement. A real
            # learner feed drains in batch-sized gulps for the same reason.
            while True:
                now = time.perf_counter()
                if now >= deadline:
                    break
                drained = 0
                for q in qs:
                    # round-robin burst drain across fleets, same fairness
                    # shape as the FleetMergeFeed collator
                    try:
                        while True:
                            q.get_nowait()
                            drained += 1
                    except queue.Empty:
                        pass
                if drained:
                    n += drained
                    empty_since = None
                else:
                    if empty_since is None:
                        empty_since = now
                        stall_mark = _master_progress()[1]
                    elif now - empty_since > min(5.0, seconds / 2):
                        # the quiet threshold only OPENS the investigation
                        # (it must be reachable inside one window, else the
                        # deadline expires first and a wedged wire silently
                        # publishes a near-zero rate); the VERDICT comes
                        # from the real counters — a master that provably
                        # emitted DATAPOINTS during the quiet spell is
                        # draining elsewhere, not stalled. Datapoints ONLY:
                        # wire messages still ticking while the flush path
                        # is dead is the "flush path stalled" wedge itself
                        # and must keep counting toward the raise
                        if _master_progress()[1] != stall_mark:
                            empty_since = None
                            continue
                        raise RuntimeError(
                            "plane stalled: "
                            f"{min(5.0, seconds / 2):.1f}s without data "
                            f"post-warmup — {stall_attribution()}"
                        )
                    time.sleep(0.002)
            window_rates.append(n / (time.perf_counter() - t0))
    finally:
        for p in procs:
            p.terminate()
        for master in masters:
            master.close()
        for predictor in predictors:
            predictor.stop()
        for predictor in predictors:
            predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)
    rate = max(window_rates)
    kind = "nodevice" if null_device else "tpu"
    return {
        "telemetry_enabled": telemetry_on,
        "telemetry": _tele_snapshot(),
        # the null-predictor ceiling must be UNMISTAKABLE from a real plane
        # measurement: distinct metric name + an explicit predictor field
        "metric": f"zmq_plane_{kind}_{game}_env_steps_per_sec_per_host",
        "value": round(rate, 1),
        "unit": "env-steps/sec/host",
        "vs_baseline": round(rate / BASELINE_ENV_STEPS_PER_SEC, 3),
        "predictor": "null-host-random" if null_device else "batched-tpu",
        "wire": wire,
        "fleets": max(1, fleets),
        # per-fleet shape (the unit the --fleets scaling gate compares at)
        "n_envs": n_envs,
        "envs_per_proc": per,
        "seconds": seconds,
        "window_rates": [round(r, 1) for r in window_rates],
    }


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--plane",
        choices=["fused", "zmq", "zmq-null"],
        default="fused",
        help="fused = on-device actor+learner (the driver metric); "
        "zmq = host actor plane via C++ env servers; "
        "zmq-null = same plane with a no-device null predictor (the "
        "serialization+transport+batching ceiling, PERF.md)",
    )
    ap.add_argument(
        "--wire",
        default="auto",
        choices=["auto", "block-shm", "block", "per-env"],
        help="env-server wire protocol for the zmq planes (the fused plane "
        "has no wire): block-shm = control over zmq + obs through a "
        "/dev/shm ring (the README headline wire), block = all-zmq "
        "zero-copy multipart, per-env = the pre-block compat baseline; "
        "auto = block-shm when /dev/shm is available, else block (same "
        "resolution as cli.py --wire)",
    )
    ap.add_argument(
        "--tpu_lock",
        default="wait",
        choices=["wait", "fail", "off"],
        help="host-local TPU-claim mutex (utils/devicelock.py). Default "
        "wait: a bench launched while training holds the chip QUEUES "
        "instead of wedging the pool (the round-4 outage class).",
    )
    ap.add_argument(
        "--overlap", action="store_true",
        help="fused plane only: measure the overlapped two-program mode "
        "(rollout k+1 concurrent with learner k, lag-1 V-trace — "
        "docs/overlap.md) instead of the single fused program; MFU sums "
        "the manifest FLOPs of both registered programs",
    )
    ap.add_argument(
        "--n_envs", type=int, default=128,
        help="fused/overlap planes: envs per chip (the flagship bench "
        "shape; shrink for device-free proxy captures)",
    )
    ap.add_argument(
        "--rollout_len", type=int, default=20,
        help="fused/overlap planes: rollout length per update",
    )
    ap.add_argument(
        "--iters", type=int, default=200,
        help="fused/overlap planes: updates per timed window",
    )
    ap.add_argument(
        "--rollout_dtype", default="float32",
        choices=["float32", "bfloat16"],
        help="--overlap only: actor-side params-snapshot dtype",
    )
    args = ap.parse_args()

    import os

    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    # bounded wait: the driver invokes bench.py unattended at round end —
    # queueing briefly behind a finishing run is right, hanging forever
    # behind a wedged one is not (exit nonzero with the holder identity)
    _lock = guard_tpu(  # noqa: F841 — held for process lifetime
        "bench.py",
        mode=args.tpu_lock,
        timeout_s=float(os.environ.get("BA3C_TPU_LOCK_TIMEOUT", "1800")),
    )
    if args.wire == "auto":
        from distributed_ba3c_tpu.utils import shm

        args.wire = "block-shm" if shm.available() else "block"
    if args.overlap and args.plane != "fused":
        # same convention as cli.py: contradictory flags are a usage
        # error, never a silently-ignored modifier
        raise SystemExit(
            f"--overlap measures the fused plane's two-program schedule; "
            f"it does not combine with --plane {args.plane}"
        )
    if args.plane == "zmq":
        print(json.dumps(bench_zmq_plane(wire=args.wire)))
    elif args.plane == "zmq-null":
        print(json.dumps(bench_zmq_plane(null_device=True, wire=args.wire)))
    elif args.overlap:
        print(json.dumps(bench_overlap(
            n_envs=args.n_envs, rollout_len=args.rollout_len,
            iters=args.iters, rollout_dtype=args.rollout_dtype,
        )))
    else:
        print(json.dumps(bench_fused(
            n_envs=args.n_envs, rollout_len=args.rollout_len,
            iters=args.iters,
        )))


if __name__ == "__main__":
    main()
