#!/usr/bin/env bash
# Stall-tolerant training launcher: runs train.py, watches the run's log for
# progress, and on a stall (no log writes for STALL_SECS — e.g. the tunneled
# PJRT client losing its terminal mid-run) kills the process and resumes from
# the run's checkpoints with --load. Training survives infrastructure flakes
# without operator attention (the reference had no crash-resume beyond manual
# --load either — SURVEY.md §5 checkpoint/resume).
#
# Usage: scripts/run_with_resume.sh LOGDIR MAX_RESTARTS STALL_SECS -- <train.py args...>
# The train args must include --logdir LOGDIR and NOT --load (the launcher
# adds --load LOGDIR/checkpoints whenever that directory exists, so re-running
# the same command over a prior run's logdir RESUMES it, never restarts it).
set -u
LOGDIR=$1; MAX_RESTARTS=$2; STALL_SECS=$3; shift 3
[ "$1" = "--" ] && shift
HERE=$(cd "$(dirname "$0")/.." && pwd)

attempt=0
while :; do
  args=("$@")
  # resume whenever a FINALIZED checkpoint exists — including a FRESH
  # launcher invocation over a prior run's logdir (restarting from step 0
  # would clobber the existing checkpoints). Gate on checkpoint.json's
  # non-null "latest" (written only after wait_until_finished), NOT on the
  # dir: CheckpointManager creates the dir at startup, so a stall-kill
  # before the first save would otherwise make every subsequent attempt
  # --load an empty dir, crash with exit 1, and burn MAX_RESTARTS on a
  # run that never trained (same gate as launch_multihost.sh).
  if [ -f "$LOGDIR/checkpoints/checkpoint.json" ] && \
     python3 -c 'import json,sys; sys.exit(0 if json.load(open(sys.argv[1])).get("latest") is not None else 1)' \
       "$LOGDIR/checkpoints/checkpoint.json" 2>/dev/null; then
    args+=(--load "$LOGDIR/checkpoints")
  fi
  echo "[run_with_resume] attempt $attempt: python train.py ${args[*]}" >&2
  # setsid: own process group, so the stall kill reaps the trainer AND its
  # spawned children without touching unrelated processes on the machine
  setsid python "$HERE/train.py" "${args[@]}" &
  pid=$!
  start=$(date +%s)
  # watchdog: poll the log mtime; kill on stall. Progress is measured
  # against max(attempt start, log mtime) so a stale log from a PREVIOUS
  # attempt can't kill this one, and until THIS attempt's first log write
  # (startup + XLA compile can exceed STALL_SECS) the threshold gets an
  # extra 600s of grace.
  while kill -0 $pid 2>/dev/null; do
    sleep 30
    log="$LOGDIR/log.log"
    last=$start
    thresh=$(( STALL_SECS + 600 ))
    if [ -f "$log" ]; then
      m=$(stat -c %Y "$log")
      if [ "$m" -gt "$last" ]; then
        last=$m
        thresh=$STALL_SECS
      fi
    fi
    age=$(( $(date +%s) - last ))
    if [ $age -gt $thresh ]; then
      echo "[run_with_resume] stall: no progress for ${age}s — killing group $pid" >&2
      kill -- -$pid 2>/dev/null; sleep 5; kill -9 -- -$pid 2>/dev/null
      break
    fi
  done
  wait $pid; rc=$?
  if [ $rc -eq 0 ]; then
    echo "[run_with_resume] finished cleanly" >&2
    exit 0
  fi
  attempt=$((attempt + 1))
  if [ $attempt -gt $MAX_RESTARTS ]; then
    echo "[run_with_resume] giving up after $MAX_RESTARTS restarts (rc=$rc)" >&2
    exit $rc
  fi
done
