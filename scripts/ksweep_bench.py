"""K-sweep at the BENCH shape: env-steps/s/chip vs --steps_per_dispatch K.

Replaces round 4's contaminated sweep (PERF.md) with a committed
methodology: delegates to ``bench.bench_fused`` so the measurement policy
(state creation, warmup-and-drain, 3 fully-synced windows, best window
wins) lives in exactly one place — for each K the window's ``iters``
updates run as ``iters/K`` dispatches of one K-step scanned program.
Run on an idle chip — the TPU-claim mutex queues (bounded) or refuses if
another local process holds it.

``--n_envs`` takes a comma list to capture SHARD SHAPES (VERDICT r5 Next
#1): the RESULTS.md v4-8 wall-clock conversion shards the solving batch
(32 envs x 20) across 4 chips, so each chip actually runs an 8-env shard
— a shape whose rate was never measured (the e8 ladder row saw 16-env
batches drop to ~38k). ``--n_envs 8,16`` measures those shard rates so
the headline conversion can be restated from data instead of assuming
the 32-env single-chip rate survives the shard split:

  python scripts/ksweep_bench.py --n_envs 8,16 --ks 1,20 --total 200

Prints per-(shape,K) diagnostics on stderr and ONE JSON line on stdout
(the repo's bench-tooling contract, utils/devicelock.py). Single-shape
runs keep the legacy top-level ``per_chip_by_K``/``windows_by_K`` keys
(runs/ksweep_r5.json schema); every run also emits the shape-keyed
``rows``.

Usage: python scripts/ksweep_bench.py [--ks 1,20,200] [--n_envs 128]
       [--tpu_lock wait|fail|off]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_ba3c_tpu.utils.devicelock import guard_tpu, stderr_print  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n_envs", default="128",
                    help="comma list of per-chip env counts; multiple "
                    "values capture shard-shape rows (e.g. 8,16 = the "
                    "4-way / 2-way shards of the solving batch)")
    ap.add_argument("--rollout_len", type=int, default=20)
    ap.add_argument("--total", type=int, default=200,
                    help="updates per timed window (must be divisible by each K)")
    ap.add_argument("--ks", default="1,20,200")
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    args = ap.parse_args()

    _lock = guard_tpu(  # noqa: F841 — held for process lifetime
        "ksweep_bench",
        mode=args.tpu_lock,
        timeout_s=float(os.environ.get("BA3C_TPU_LOCK_TIMEOUT", "1800")),
    )

    from bench import bench_fused

    shapes = [int(n) for n in args.n_envs.split(",")]
    ks = [int(k) for k in args.ks.split(",")]
    rows: dict[str, dict] = {}
    for n_envs in shapes:
        out: dict[int, float] = {}
        windows: dict[int, list[float]] = {}
        for K in ks:
            r = bench_fused(
                n_envs=n_envs, rollout_len=args.rollout_len,
                iters=args.total, steps_per_dispatch=K,
            )
            out[K] = r["value"]
            windows[K] = r["window_rates"]
            stderr_print(
                f"{n_envs}x{args.rollout_len} K={K}: {r['value']} "
                f"env-steps/s/chip  windows={r['window_rates']}"
            )
        rows[f"{n_envs}x{args.rollout_len}"] = {
            "per_chip_by_K": out, "windows_by_K": windows,
        }

    payload = {
        "metric": "fused_pong_ksweep_env_steps_per_sec_per_chip",
        "shape": ",".join(rows),
        "total_updates_per_window": args.total,
        "rows": rows,
    }
    if len(shapes) == 1:
        # legacy single-shape schema (runs/ksweep_r5.json, test_bench.py)
        payload.update(next(iter(rows.values())))
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
