"""K-sweep at the BENCH shape: env-steps/s/chip vs --steps_per_dispatch K.

Replaces round 4's contaminated sweep (PERF.md) with a committed
methodology: delegates to ``bench.bench_fused`` so the measurement policy
(state creation, warmup-and-drain, 3 fully-synced windows, best window
wins) lives in exactly one place — for each K the window's ``iters``
updates run as ``iters/K`` dispatches of one K-step scanned program.
Run on an idle chip — the TPU-claim mutex queues (bounded) or refuses if
another local process holds it.

Prints per-K diagnostics on stderr and ONE JSON line on stdout
(the repo's bench-tooling contract, utils/devicelock.py).

Usage: python scripts/ksweep_bench.py [--ks 1,20,200] [--tpu_lock wait|fail|off]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_ba3c_tpu.utils.devicelock import guard_tpu, stderr_print  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n_envs", type=int, default=128)
    ap.add_argument("--rollout_len", type=int, default=20)
    ap.add_argument("--total", type=int, default=200,
                    help="updates per timed window (must be divisible by each K)")
    ap.add_argument("--ks", default="1,20,200")
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    args = ap.parse_args()

    _lock = guard_tpu(  # noqa: F841 — held for process lifetime
        "ksweep_bench",
        mode=args.tpu_lock,
        timeout_s=float(os.environ.get("BA3C_TPU_LOCK_TIMEOUT", "1800")),
    )

    from bench import bench_fused

    out: dict[int, float] = {}
    windows: dict[int, list[float]] = {}
    for K in (int(k) for k in args.ks.split(",")):
        r = bench_fused(
            n_envs=args.n_envs, rollout_len=args.rollout_len,
            iters=args.total, steps_per_dispatch=K,
        )
        out[K] = r["value"]
        windows[K] = r["window_rates"]
        stderr_print(
            f"K={K}: {r['value']} env-steps/s/chip  windows={r['window_rates']}"
        )
    print(json.dumps({
        "metric": "fused_pong_ksweep_env_steps_per_sec_per_chip",
        "shape": f"{args.n_envs}x{args.rollout_len}",
        "total_updates_per_window": args.total,
        "per_chip_by_K": out,
        "windows_by_K": windows,
    }))


if __name__ == "__main__":
    main()
