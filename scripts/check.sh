#!/usr/bin/env bash
# Pre-commit entry point: the repo's static gates, fast enough to run on
# every commit (no tests, no accelerator — gates 1-2 are pure host-side
# analysis; gate 3 traces/compiles the registered jit programs on a pinned
# 2-device CPU platform, ~25 s, and never touches the TPU pool).
#
#   ./scripts/check.sh
#
# Gate 1: ba3clint — the repo-specific AST lint suite (rule catalog in
#         docs/static_analysis.md). Exit 1 on any unsuppressed finding.
# Gate 1b: ba3cflow — the interprocedural concurrency & lifecycle
#         analyzer (F1-F6, same doc): whole-repo call-graph analysis of
#         the actor/serving planes. Exit 1 on any unsuppressed finding.
# Gate 1c: ba3cwire — the wire-protocol & failure-path conformance
#         analyzer (W1-W6, same doc): codec-pair symmetry, header
#         versioning, receive-loop resilience, typed-reject accounting,
#         the metrics contract vs docs/observability.md, CRC coverage.
#         Then the stale-suppression audit for ALL THREE tools: a
#         disable= comment that masks nothing is itself a finding (S001).
# Gate 2: compileall — every shipped .py must at least byte-compile.
# Gate 3: ba3caudit — trace-level (jaxpr/HLO) invariants of the hot-path
#         entry points against the committed audit_manifest.json (same
#         doc). Exit 1 on any T-rule violation or manifest drift.
#
# CI runs exactly this script (.github/workflows/ci.yml `lint` job runs
# gates 1-2, the `flow` and `wire` jobs run gates 1b-1c with SARIF
# upload; the `audit` job runs gate 3), so a clean local run means clean
# CI static gates.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ba3clint =="
python -m tools.ba3clint distributed_ba3c_tpu tools scripts train.py bench.py

echo "== ba3cflow =="
python -m tools.ba3cflow

echo "== ba3cwire =="
python -m tools.ba3cwire

echo "== suppression hygiene =="
python -m tools.ba3clint --check-suppressions distributed_ba3c_tpu tools scripts train.py bench.py
python -m tools.ba3cflow --check-suppressions
python -m tools.ba3cwire --check-suppressions

echo "== compileall =="
python -m compileall -q distributed_ba3c_tpu tools scripts tests train.py bench.py

if [[ "${BA3C_CHECK_NO_AUDIT:-0}" != 1 ]]; then
  echo "== ba3caudit =="
  python -m tools.ba3caudit
else
  # CI's lint job installs no jax; the dedicated `audit` job owns gate 3
  # there. Locally, never set this — the full pre-commit is all 3 gates.
  echo "== ba3caudit skipped (BA3C_CHECK_NO_AUDIT=1) =="
fi

echo "check.sh: all gates passed"
