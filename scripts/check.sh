#!/usr/bin/env bash
# Pre-commit entry point: the repo's static gates, fast enough to run on
# every commit (no tests, no device — pure host-side analysis).
#
#   ./scripts/check.sh
#
# Gate 1: ba3clint — the repo-specific AST lint suite (rule catalog in
#         docs/static_analysis.md). Exit 1 on any unsuppressed finding.
# Gate 2: compileall — every shipped .py must at least byte-compile.
#
# CI runs exactly this script (.github/workflows/ci.yml `lint` job), so a
# clean local run means a clean CI lint job.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ba3clint =="
python -m tools.ba3clint distributed_ba3c_tpu scripts train.py bench.py

echo "== compileall =="
python -m compileall -q distributed_ba3c_tpu tools scripts tests train.py bench.py

echo "check.sh: all gates passed"
