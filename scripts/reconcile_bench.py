#!/usr/bin/env python
"""Reconcile-loop chaos certification: every resource class killed, healed.

The acceptance contract for the declarative control plane
(docs/topology.md): ONE seeded run SIGKILLs a member of EVERY resource
class the :class:`Reconciler` drives, and gates on the loop healing each
back to spec with zero manual intervention:

1. **fleet**: a supervised fake-env simulator fleet (per-env wire ->
   master -> null predictor) with the reconciler owning the tick (the
   supervisor thread is never started); one env-server slot is SIGKILLed
   mid-stream and must respawn through a flight-recorded
   ``reconcile_action``, the plane producing datapoints again afterwards.
   The env flavor is irrelevant here — the C++ fleet's own chaos story is
   scripts/chaos_bench.py; the measurand is the LOOP.
2. **pod**: a 2-host fake-env pod against a real :class:`PodLearnerPlane`,
   the hosts under :class:`PodSupervisor` ridden as a ``kind="pod"``
   resource; one WHOLE host process group is SIGKILLed and must rejoin,
   the learner taking updates again post-heal with zero learner restarts.
3. **netchaos partition**: the pod links under a timed full partition
   (10 s at the committed shape) from the seeded netchaos plane — heal
   restart-free, typed counters only, and the rep must replay from its
   seed (docs/netchaos.md: spec'd chaos is part of the document).
4. **learner**: a real ``train.py`` fake-env run driven through
   :class:`LearnerResource` (the reconciler's re-arm path, NOT
   ``LearnerSupervisor.run``); SIGKILLed after its first FINALIZED
   checkpoint, it must resume from that checkpoint to rc 0 — zero
   state loss proven by step continuity (final step > kill step).
5. **serving**: two null-predictor replicas behind the REAL
   ServingRouter in a :class:`ReplicaSet` whose sweeper thread is OFF
   (the reconciler owns the sweep); one replica's scheduler is killed
   mid-traffic (the in-process SIGKILL analogue, serving_bench
   precedent) and the set must heal back to target with a fresh
   incarnation, every submitted task resolving.

Prints ONE JSON line (the repo's bench-tooling contract) embedding the
flight-recorded decision trail (``reconcile_action`` and friends) — the
committed artifact is ``runs/reconcile_bench_r17.json``. Exit 1 if any
gate fails. ``--short`` is the CI schedule (same gates, smaller shapes
— the ``reconcile`` job). Device-free: forces ``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import queue
import random
import signal
import sys
import tempfile
import time
from pathlib import Path
from types import SimpleNamespace

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: flight-event kinds that belong to the reconcile story — each phase
#: embeds exactly these (captured per phase: the netchaos rig resets
#: telemetry, so the trail is accumulated, not re-read at the end)
_TRAIL_KINDS = (
    "reconcile_action", "reconcile_act_error", "reconcile_circuit_open",
    "reconcile_circuit_close", "server_spawn", "server_respawn",
    "server_death", "learner_failover", "learner_giveup",
    "serving_replica_spawn", "serving_replica_replace", "replica_dead",
)


def _policy(poll_s: float = 0.1):
    from distributed_ba3c_tpu.orchestrate.topology import ReconcilePolicy

    return ReconcilePolicy(
        poll_interval_s=poll_s, backoff_base_s=0.25, backoff_max_s=5.0,
        restart_budget=32, budget_window_s=120.0,
    )


def _heal_count(kind: str) -> float:
    from distributed_ba3c_tpu import telemetry

    return telemetry.registry("reconciler").counter(
        f"reconcile_heal_{kind}_total"
    ).value()


def _trail(since_t: float, cap: int = 80) -> list:
    from distributed_ba3c_tpu import telemetry

    return [
        {"kind": k, **f}
        for _, k, f in telemetry.flight_recorder().events_since(since_t)
        if k in _TRAIL_KINDS
    ][-cap:]


def _drain(master, n: int, first_timeout: float = 240.0) -> int:
    """Pull ``n`` datapoints off the master's train queue (liveness
    proof: the plane is actually streaming, not just process-alive)."""
    got = 0
    try:
        master.queue.get(timeout=first_timeout)
        got += 1
        while got < n:
            master.queue.get(timeout=60)
            got += 1
    except queue.Empty:
        pass
    return got


# ---------------------------------------------------------------------------
# phase 1: env-server slot
# ---------------------------------------------------------------------------

def _phase_fleet(args, rng: random.Random) -> dict:
    """SIGKILL one supervised fake-env simulator slot; the reconciler's
    FleetResource must respawn it and the plane must stream again."""
    from bench import make_null_predictor
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.actors.simulator import SimulatorProcess
    from distributed_ba3c_tpu.envs.fake import build_fake_player
    from distributed_ba3c_tpu.orchestrate import FleetSpec, FleetSupervisor
    from distributed_ba3c_tpu.orchestrate.reconcile import (
        FleetResource,
        Reconciler,
    )

    t0 = time.monotonic()
    model = SimpleNamespace(num_actions=4, apply=None)
    predictor = make_null_predictor(
        model, {}, 4, batch_size=64, num_threads=2, coalesce_ms=0.0
    )
    tmp = tempfile.mkdtemp(prefix="ba3c-reconcile-fleet-")
    c2s, s2c = f"ipc://{tmp}/c2s", f"ipc://{tmp}/s2c"
    master = BA3CSimulatorMaster(
        c2s, s2c, predictor, gamma=0.99, local_time_max=5,
        score_queue=queue.Queue(maxsize=100_000),
    )
    build_player = functools.partial(
        build_fake_player, image_size=(16, 16), frame_history=4,
        num_actions=4,
    )
    sup = FleetSupervisor(
        FleetSpec(
            pipe_c2s=c2s, pipe_s2c=s2c, envs_per_server=1, wire="per-env",
            fleet_size=args.fleet_sims, fleet_min=args.fleet_sims,
            fleet_max=args.fleet_sims, backoff_base_s=0.25,
            backoff_max_s=5.0, stable_after_s=5.0,
        ),
        # construction only parameterizes the slot — the reconciler-driven
        # supervisor this factory is handed to owns the spawn
        factory=lambda i: SimulatorProcess(  # ba3clint: disable=A8
            i, c2s, s2c, build_player
        ),
        ident_prefix=lambda i: f"simulator-{i}",
    )
    rec = Reconciler(policy=_policy())  # ba3cflow: disable=F5 — the finally's rec.close() stops AND joins the loop thread (Reconciler.close)
    rec.add(FleetResource("fleet0", sup))
    heal_before = _heal_count("fleet")
    out: dict = {"ok": False, "fleet_size": args.fleet_sims}
    try:
        predictor.start()
        master.start()
        rec.start()  # prepare() spawns the initial fleet; the loop ticks
        out["warmup_datapoints"] = _drain(master, args.warmup_datapoints)
        if out["warmup_datapoints"] < args.warmup_datapoints:
            out["error"] = "plane produced no warmup stream"
            return out
        victim = rng.choice([idx for idx, _ in sup.live_slots()])
        out["killed_slot"] = victim
        sup.sigkill_slot(victim)
        deadline = time.monotonic() + args.settle_timeout
        while time.monotonic() < deadline:
            if (
                sup.live_count() >= sup.target
                and _heal_count("fleet") > heal_before
            ):
                break
            time.sleep(0.1)
        out["settled"] = sup.live_count() >= sup.target
        out["heal_actions"] = _heal_count("fleet") - heal_before
        # the respawned slot must STREAM, not just sit in the process
        # table — drain fresh datapoints through the healed fleet
        out["post_heal_datapoints"] = _drain(
            master, args.post_heal_datapoints, first_timeout=60.0
        )
        reg = telemetry.registry("orchestrator")
        out["respawns"] = reg.counter("server_respawns_total").value()
        out["ok"] = bool(
            out["settled"]
            and out["heal_actions"] >= 1
            and out["respawns"] >= 1
            and out["post_heal_datapoints"] >= args.post_heal_datapoints
        )
        return out
    finally:
        out["decisions"] = _trail(t0)
        rec.close()  # retires the resource -> supervisor.close()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)


# ---------------------------------------------------------------------------
# phase 2: whole pod host group
# ---------------------------------------------------------------------------

def _phase_pod(args, rng: random.Random) -> dict:
    """SIGKILL one WHOLE pod host process group mid-training; the
    reconciler must respawn it and the learner must take updates again
    — with zero learner restarts (host loss is not a learner event)."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.orchestrate.pod import (
        PodLearnerPlane,
        PodSupervisor,
        host_argv,
    )
    from distributed_ba3c_tpu.orchestrate.reconcile import (
        FleetResource,
        Reconciler,
    )

    t0 = time.monotonic()
    cfg = BA3CConfig(
        image_size=(16, 16), frame_history=4, num_actions=4, fc_units=16,
        local_time_max=5, predict_batch_size=16,
    )
    tmp = tempfile.mkdtemp(prefix="ba3c-reconcile-pod-")
    c2s, s2c = f"ipc://{tmp}/c2s", f"ipc://{tmp}/s2c"
    plane = PodLearnerPlane(cfg, c2s, s2c, max_staleness=8)
    sup = PodSupervisor(
        2,
        lambda i: host_argv(
            i, c2s, s2c, env="fake", n_sims=2, unroll_len=5,
            segments_per_block=4, max_staleness=8, image_size=16,
            frame_history=4, num_actions=4, fc_units=16,
            predict_batch_size=16,
        ),
        backoff_base_s=0.25,
    )
    rec = Reconciler(policy=_policy())  # ba3cflow: disable=F5 — the finally's rec.close() stops AND joins the loop thread (Reconciler.close)
    rec.add(FleetResource("pod-hosts", sup, kind="pod"))
    heal_before = _heal_count("pod")
    # delta, not absolute: the fleet phase's respawn counter carries over
    respawns_before = telemetry.registry("orchestrator").counter(
        "server_respawns_total"
    ).value()
    out: dict = {"ok": False, "hosts": 2}
    try:
        plane.start()
        rec.start()
        updates = 0
        deadline = time.monotonic() + args.warmup_timeout_net
        while updates < args.pod_warmup_updates:
            if time.monotonic() > deadline:
                out["error"] = "pod produced no warmup updates"
                return out
            if plane.step_once(timeout=1.0) is not None:
                updates += 1
        out["warmup_updates"] = updates
        victim = rng.choice([idx for idx, _ in sup.live_slots()])
        out["killed_host"] = victim
        sup.sigkill_slot(victim)  # the whole host process group
        post_kill_updates = 0
        deadline = time.monotonic() + max(120.0, args.settle_timeout)
        while time.monotonic() < deadline:
            if plane.step_once(timeout=0.2) is not None:
                post_kill_updates += 1
            if (
                sup.live_count() >= sup.target
                and _heal_count("pod") > heal_before
                and post_kill_updates >= args.pod_heal_updates
            ):
                break
        out["settled"] = sup.live_count() >= sup.target
        out["heal_actions"] = _heal_count("pod") - heal_before
        out["post_kill_updates"] = post_kill_updates
        orch = telemetry.registry("orchestrator").scalars()
        out["host_respawns"] = int(
            orch.get("server_respawns_total", 0) - respawns_before
        )
        out["learner_restarts"] = int(orch.get("learner_restarts_total", 0))
        out["ok"] = bool(
            out["settled"]
            and out["heal_actions"] >= 1
            and out["host_respawns"] >= 1
            and post_kill_updates >= args.pod_heal_updates
            and out["learner_restarts"] == 0
        )
        return out
    finally:
        out["decisions"] = _trail(t0)
        rec.close()  # retires the resource -> supervisor.close()
        plane.close()


# ---------------------------------------------------------------------------
# phase 3: netchaos partition across the pod links
# ---------------------------------------------------------------------------

def _phase_partition(args) -> dict:
    """A timed FULL partition of every pod link from the seeded netchaos
    plane; heal must be restart-free and the rep must replay."""
    from distributed_ba3c_tpu.netchaos.bench import NetShape, run_partition_rep

    shape = NetShape(
        hosts=1, sims_per_host=args.net_sims, segments_per_block=8,
        warmup_timeout=args.warmup_timeout_net,
    )
    part = run_partition_rep(shape, args.seed, partition_s=args.partition_s)
    return {
        "partition_s": args.partition_s,
        "recovered": part.get("recovered", False),
        "replay_ok": bool(part.get("replay", {}).get("match")),
        "detail": part,
        "ok": bool(
            part.get("recovered") and part.get("replay", {}).get("match")
        ),
    }


# ---------------------------------------------------------------------------
# phase 4: learner, post-checkpoint
# ---------------------------------------------------------------------------

def _phase_learner(args) -> dict:
    """SIGKILL a real train.py run's whole process group after its first
    FINALIZED checkpoint; the reconciler's re-arm path must resume it
    from that checkpoint to rc 0 (step continuity = zero state loss)."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate import LearnerSupervisor, finalized_step
    from distributed_ba3c_tpu.orchestrate.reconcile import (
        LearnerResource,
        Reconciler,
    )
    from distributed_ba3c_tpu.utils.concurrency import StoppableThread

    t0 = time.monotonic()
    logdir = os.path.join(
        tempfile.mkdtemp(prefix="ba3c-reconcile-learner-"), "run"
    )
    ckpt_dir = os.path.join(logdir, "checkpoints")
    train_args = [
        "--env", "fake",
        "--simulator_procs", "2",
        "--batch_size", "16",
        "--image_size", "16",
        "--fc_units", "16",
        "--steps_per_epoch", str(args.failover_steps_per_epoch),
        "--max_epoch", "3",
        "--nr_eval", "0",
        "--logdir", logdir,
    ]
    sup = LearnerSupervisor(logdir, train_args, max_restarts=3, poll_s=0.2)
    rec = Reconciler(policy=_policy(poll_s=0.2))  # ba3cflow: disable=F5 — the finally's rec.close() stops AND joins the loop thread (Reconciler.close)
    lres = rec.add(LearnerResource("learner", sup))
    heal_before = _heal_count("learner")
    killed = {}

    def killer():
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            step = finalized_step(ckpt_dir)
            pid = sup.child_pid
            if step is not None and pid is not None:
                killed["at_step"] = step
                try:
                    os.killpg(pid, signal.SIGKILL)  # the whole group
                except (OSError, ProcessLookupError):
                    pass
                return
            time.sleep(0.3)

    kt = StoppableThread(target=killer, daemon=True)
    out: dict = {"ok": False}
    try:
        rec.start()  # the first tick re-arms: start from scratch
        kt.start()
        deadline = time.monotonic() + 900
        while lres.final_rc is None and time.monotonic() < deadline:
            time.sleep(0.2)
        kt.join(timeout=5)
        reg = telemetry.registry("orchestrator")
        final = finalized_step(ckpt_dir)
        out.update({
            "rc": lres.final_rc,
            "killed_at_step": killed.get("at_step"),
            "resumes": reg.counter("learner_resumes_total").value(),
            "restarts": reg.counter("learner_restarts_total").value(),
            "final_step": final,
            "heal_actions": _heal_count("learner") - heal_before,
        })
        # resume proof is STEP CONTINUITY (the chaos_bench lesson: epoch
        # counts cannot distinguish resume from restart; steps can)
        out["ok"] = bool(
            lres.final_rc == 0
            and killed.get("at_step") is not None
            and out["resumes"] >= 1
            and final is not None
            and final > killed.get("at_step", 0)
            # >= 2 re-arms: the scratch start AND the post-kill resume
            # both went through the reconciler, not a side channel
            and out["heal_actions"] >= 2
        )
        return out
    finally:
        out["decisions"] = _trail(t0)
        kt.stop()
        rec.close()


# ---------------------------------------------------------------------------
# phase 5: serving replica
# ---------------------------------------------------------------------------

def _phase_serving(args, rng: random.Random) -> dict:
    """Kill one routed replica's scheduler mid-traffic (the in-process
    SIGKILL analogue); the reconciler's ServingResource must sweep the
    corpse and heal the set back to target with a fresh incarnation."""
    import numpy as np

    from bench import make_null_predictor
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate.reconcile import (
        Reconciler,
        ServingResource,
    )
    from distributed_ba3c_tpu.orchestrate.serving import ReplicaSet
    from distributed_ba3c_tpu.predict.router import ServingRouter, replica_role

    t0 = time.monotonic()
    model = SimpleNamespace(num_actions=4, apply=None)
    spawned: dict = {}

    def factory(idx: int):
        pred = make_null_predictor(
            model, {}, 4, service_s=0.002, batch_size=16, coalesce_ms=0.0,
            tele_role=replica_role("predictor", idx),
        )
        spawned[idx] = pred
        return pred

    router = ServingRouter(health_interval_s=0.1)
    rs = ReplicaSet(
        router, factory, min_replicas=2, max_replicas=4, retire_grace_s=1.0
    )
    rec = Reconciler(policy=_policy())  # ba3cflow: disable=F5 — the finally's rec.close() stops AND joins the loop thread (Reconciler.close)
    rec.add(ServingResource("serving", rs))
    heal_before = _heal_count("serving")
    served: list = []
    sheds: list = []
    out: dict = {"ok": False, "replicas": 2}
    try:
        router.start()
        rs.start(2, reconcile_thread=False)  # the reconciler owns the sweep
        rec.start()
        victim = rng.choice(rs.replica_ids())
        out["killed_replica"] = victim
        vpred = spawned[int(victim[1:])]

        def _die(params, batch):
            raise RuntimeError("chaos: replica killed")

        # the kill: the victim's next dispatch raises and its scheduler
        # thread dies with the queue intact — what a SIGKILL leaves behind
        vpred._dispatch = _die

        def saw_dead() -> bool:
            # the router's OWN verdict, read from its flight record: the
            # reconciler sweeps the corpse out of replica_states() within
            # one tick, so polling the live table races the heal
            return any(
                e["kind"] == "replica_dead" and e.get("replica") == victim
                for e in _trail(t0)
            )

        state = np.zeros((16, 1), np.uint8)
        submitted = 0
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not saw_dead():
            for _ in range(8):  # keep both replicas fed until the verdict
                router.put_block_task(
                    state,
                    lambda *a: served.append(1),
                    shed_callback=lambda rej: sheds.append(
                        getattr(rej, "reason", "?")
                    ),
                )
                submitted += 1
            time.sleep(0.2)
        out["replica_dead_verdict"] = saw_dead()
        healed = False
        deadline = time.monotonic() + args.settle_timeout
        while time.monotonic() < deadline:
            ids = rs.replica_ids()
            states = router.replica_states()
            if (
                victim not in ids
                and len(ids) >= 2
                and all(states.get(r) == "up" for r in ids)
            ):
                healed = True
                break
            time.sleep(0.1)
        # drain: every submitted task must RESOLVE (served or typed shed)
        deadline = time.monotonic() + 10.0
        while (
            len(served) + len(sheds) < submitted
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        out.update({
            "healed_to_target": healed,
            "final_replicas": rs.replica_ids(),
            "heal_actions": _heal_count("serving") - heal_before,
            "submitted_tasks": submitted,
            "served_tasks": len(served),
            "shed_tasks": len(sheds),
            "unresolved_tasks": submitted - len(served) - len(sheds),
            "sheds_by_reason": {
                r: sheds.count(r) for r in sorted(set(sheds))
            },
        })
        out["ok"] = bool(
            out["replica_dead_verdict"]
            and healed
            and out["heal_actions"] >= 1
            and out["unresolved_tasks"] == 0
        )
        return out
    finally:
        out["decisions"] = _trail(t0)
        rec.close()
        rs.close()  # the bench owns the set (ServingResource.retire defers)
        router.stop()
        router.join(timeout=5)


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument(
        "--short", action="store_true",
        help="CI schedule: identical gates, smaller shapes (fewer warmup "
        "datapoints, 4 s partition, shorter learner epochs)",
    )
    ap.add_argument("--fleet_sims", type=int, default=4)
    ap.add_argument("--warmup_datapoints", type=int, default=128)
    ap.add_argument("--post_heal_datapoints", type=int, default=64)
    ap.add_argument("--pod_warmup_updates", type=int, default=3)
    ap.add_argument("--pod_heal_updates", type=int, default=2)
    ap.add_argument(
        "--partition_s", type=float, default=10.0,
        help="netchaos full-partition length (the committed capture's 10 s)",
    )
    ap.add_argument("--net_sims", type=int, default=2)
    ap.add_argument("--warmup_timeout_net", type=float, default=240.0)
    ap.add_argument("--failover_steps_per_epoch", type=int, default=60)
    ap.add_argument("--settle_timeout", type=float, default=90.0)
    args = ap.parse_args()
    if args.short:
        args.fleet_sims = 3
        args.warmup_datapoints = 48
        args.post_heal_datapoints = 24
        args.partition_s = 4.0
        args.failover_steps_per_epoch = 40

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    telemetry.reset_all()
    rng = random.Random(args.seed)
    failures: list = []

    fleet = _phase_fleet(args, rng)
    stderr_print(
        f"fleet:     killed slot {fleet.get('killed_slot')}, settled="
        f"{fleet.get('settled')}, {fleet.get('heal_actions', 0):.0f} heal "
        f"actions, {fleet.get('post_heal_datapoints', 0)} post-heal "
        f"datapoints"
    )
    if not fleet["ok"]:
        failures.append(f"fleet phase FAILED: {json.dumps(fleet)[:500]}")

    pod = _phase_pod(args, rng)
    stderr_print(
        f"pod:       killed host {pod.get('killed_host')} (whole group), "
        f"settled={pod.get('settled')}, {pod.get('host_respawns', 0)} host "
        f"respawns, {pod.get('post_kill_updates', 0)} post-kill updates, "
        f"{pod.get('learner_restarts', 0)} learner restarts"
    )
    if not pod["ok"]:
        failures.append(f"pod phase FAILED: {json.dumps(pod)[:500]}")

    partition = _phase_partition(args)
    stderr_print(
        f"partition: {args.partition_s:.0f}s full partition, recovered="
        f"{partition['recovered']}, replay={partition['replay_ok']}"
    )
    if not partition["ok"]:
        failures.append(
            "netchaos partition phase FAILED: "
            f"{json.dumps(partition['detail'])[:500]}"
        )

    learner = _phase_learner(args)
    stderr_print(
        f"learner:   killed at step {learner.get('killed_at_step')}, "
        f"resumes {learner.get('resumes', 0):.0f}, rc {learner.get('rc')}, "
        f"final step {learner.get('final_step')}"
    )
    if not learner["ok"]:
        failures.append(f"learner phase FAILED: {json.dumps(learner)[:800]}")

    serving = _phase_serving(args, rng)
    stderr_print(
        f"serving:   killed {serving.get('killed_replica')}, dead verdict="
        f"{serving.get('replica_dead_verdict')}, healed="
        f"{serving.get('healed_to_target')}, unresolved "
        f"{serving.get('unresolved_tasks')}"
    )
    if not serving["ok"]:
        failures.append(f"serving phase FAILED: {json.dumps(serving)[:500]}")

    flight = telemetry.flight_recorder()
    dump_path = flight.dump("reconcile bench complete")
    # the accumulated per-phase trails ARE the decision record (the
    # netchaos rig resets telemetry mid-run, so a single events_since(0)
    # at the end would only cover the tail phases)
    trail = (
        fleet.get("decisions", []) + pod.get("decisions", [])
        + learner.get("decisions", []) + serving.get("decisions", [])
    )
    healed_classes = sum(
        1 for p in (fleet, pod, learner, serving) if p["ok"]
    )
    out = {
        "metric": "reconcile_chaos_classes_healed",
        "value": healed_classes,
        "unit": "resource classes SIGKILLed and healed to spec (of 4)",
        "seed": args.seed,
        "short": bool(args.short),
        "partition_recovered": partition["recovered"],
        "partition_replay_ok": partition["replay_ok"],
        "fleet": fleet,
        "pod": pod,
        "partition": partition,
        "learner": learner,
        "serving": serving,
        "reconciler_series": telemetry.registry("reconciler").scalars(),
        "flight_dump": dump_path,
        "flight_event_kinds": sorted({e["kind"] for e in trail}),
        "decision_trail": trail[-200:],
    }
    # evidence prints BEFORE the verdict (the repo's bench contract): the
    # per-phase detail and the decision trail matter most on a failure
    print(json.dumps(out))
    if failures:
        for msg in failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
