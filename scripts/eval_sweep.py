"""Independent re-evaluation sweep over a run's kept checkpoints.

The north-star verification protocol (VERDICT r3 Missing #1): in-training
evals are noisy (64-ep reads sit +-0.5 around fresh-seed 128-ep re-evals),
so the claimed crossing must come from INDEPENDENT re-evals of kept
checkpoints — fresh seeds, >=128 episodes, a horizon covering full episodes.

Usage (ONE process, one TPU claim — serialize around training runs, see
.claude/skills/verify/SKILL.md):
    python scripts/eval_sweep.py --env jax:pong \
        --load runs/ns_r4_a/checkpoints [--steps 40000,44800,...] \
        --nr_eval 128 --max_steps 10000 --threshold 18 \
        --out runs/ns_r4_a/eval_sweep.json

Walks every kept step (ascending) unless --steps narrows it, evaluates each
with the on-device greedy Evaluator on a seed stream DISJOINT from
training's (integer seeds 777000+step vs training's 1000+epoch), and writes
one JSON with per-step means plus the earliest step clearing --threshold.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from distributed_ba3c_tpu.train.eval_tools import make_checkpoint_evaluator


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--env", default="jax:pong")
    ap.add_argument("--load", required=True)
    ap.add_argument("--steps", default=None,
                    help="comma-separated step subset (default: all kept)")
    ap.add_argument("--nr_eval", type=int, default=128)
    ap.add_argument("--max_steps", type=int, default=10000)
    ap.add_argument("--threshold", type=float, default=None)
    ap.add_argument("--fc_units", type=int, default=512)
    ap.add_argument("--out", default=None)
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    args = ap.parse_args()

    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    _lock = guard_tpu("eval_sweep", mode=args.tpu_lock)  # noqa: F841

    mgr, target, evaluate, n_eval = make_checkpoint_evaluator(
        args.env, args.load, args.nr_eval, args.max_steps, args.fc_units
    )
    steps = (
        [int(s) for s in args.steps.split(",")]
        if args.steps
        else mgr.all_steps
    )
    if not steps:
        raise SystemExit(f"no checkpoints recorded under {args.load}")

    out = args.out or f"{args.load}/../eval_sweep.json"
    results = []
    earliest = None

    def write_summary(complete):
        summary = {
            "load": args.load,
            "nr_eval_requested": args.nr_eval,
            "n_eval_envs": n_eval,
            "max_steps": args.max_steps,
            "threshold": args.threshold,
            "seed_stream": "777000+step, disjoint from training's 1000+epoch",
            "results": results,
            "earliest_at_threshold": earliest,
            "sweep_complete": complete,
        }
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=1)
        os.replace(tmp, out)

    for step in steps:
        try:
            state = mgr.restore(target, step)
            # integer seed stream provably disjoint from training's
            # 1000+epoch
            mean, mx, n = evaluate(state.params, 777000 + step)
        except Exception as e:
            # one bad checkpoint (or a tunnel wedge surfacing as a device
            # error) must not discard the evals already done — the sweep
            # IS the verification artifact; record the failure and go on
            rec = {"step": step, "error": f"{type(e).__name__}: {e}"}
            results.append(rec)
            print(json.dumps(rec), flush=True)
            write_summary(complete=False)
            continue
        # n==0 => mean/max are fill values (-inf is not even valid JSON)
        rec = {"step": step,
               "eval_mean": round(mean, 3) if n > 0 else None,
               "eval_max": round(mx, 2) if n > 0 else None,
               "episodes": n}
        results.append(rec)
        print(json.dumps(rec), flush=True)
        # long rallies can leave a few envs unfinished at the horizon
        # (round 3's final ckpt re-eval completed 127/128); demand near-full
        # completion and report the exact count in the record
        if (
            args.threshold is not None
            and earliest is None
            and n >= max(1, int(0.95 * n_eval))
            and mean >= args.threshold
        ):
            earliest = rec
        # incremental write: a crash at checkpoint k keeps evals 1..k
        # (26 x ~1 min on a flaky tunnel is a real loss surface)
        write_summary(complete=False)
    write_summary(complete=not any("error" in r for r in results))
    print(f"wrote {out}", flush=True)
    if args.threshold is not None:
        print(
            "earliest independently-verified >= %.4g: %s"
            % (args.threshold, earliest or "NONE in sweep"),
            flush=True,
        )


if __name__ == "__main__":
    main()
