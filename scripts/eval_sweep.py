"""Independent re-evaluation sweep over a run's kept checkpoints.

The north-star verification protocol (VERDICT r3 Missing #1): in-training
evals are noisy (64-ep reads sit +-0.5 around fresh-seed 128-ep re-evals),
so the claimed crossing must come from INDEPENDENT re-evals of kept
checkpoints — fresh seeds, >=128 episodes, a horizon covering full episodes.

Usage:
    python scripts/eval_sweep.py --env jax:pong \
        --load runs/ns_r4_a/checkpoints [--steps 40000,44800,...] \
        --nr_eval 128 --max_steps 10000 --threshold 18 \
        --out runs/ns_r4_a/eval_sweep.json

Walks every kept step (checkpoint.json "all" list) in ascending order unless
--steps narrows it, evaluates each with the on-device greedy Evaluator on a
seed stream DISJOINT from training's (train uses fold_in(1000+epoch); this
uses fold_in(777000+step)), and writes one JSON with per-step means plus the
earliest step clearing --threshold. ONE process, one TPU claim: do not run
while a training run holds the chip (see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs import jaxenv
from distributed_ba3c_tpu.fused.loop import make_greedy_eval
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh
from distributed_ba3c_tpu.parallel.train_step import create_train_state
from distributed_ba3c_tpu.train.checkpoint import CheckpointManager


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--env", default="jax:pong")
    ap.add_argument("--load", required=True)
    ap.add_argument("--steps", default=None,
                    help="comma-separated step subset (default: all kept)")
    ap.add_argument("--nr_eval", type=int, default=128)
    ap.add_argument("--max_steps", type=int, default=10000)
    ap.add_argument("--threshold", type=float, default=None)
    ap.add_argument("--fc_units", type=int, default=512)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    env = jaxenv.get_env(args.env.split(":", 1)[1])
    cfg = BA3CConfig(num_actions=env.num_actions, fc_units=args.fc_units)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    target = jax.device_get(
        create_train_state(jax.random.PRNGKey(0), model, cfg, opt)
    )

    mgr = CheckpointManager(args.load)
    steps = (
        [int(s) for s in args.steps.split(",")]
        if args.steps
        else sorted(mgr._meta.get("all", []))
    )
    if not steps:
        raise SystemExit(f"no checkpoints recorded under {args.load}")

    mesh = make_mesh()
    evaluate = make_greedy_eval(
        model, cfg, mesh, env, n_envs=args.nr_eval, max_steps=args.max_steps
    )

    results = []
    earliest = None
    for step in steps:
        state = mgr.restore(target, step)
        # integer seed stream provably disjoint from training's 1000+epoch
        mean, mx, n = evaluate(state.params, 777000 + step)
        rec = {"step": step, "eval_mean": round(mean, 3),
               "eval_max": round(mx, 2), "episodes": n}
        results.append(rec)
        print(json.dumps(rec), flush=True)
        if (
            args.threshold is not None
            and earliest is None
            and n >= args.nr_eval
            and mean >= args.threshold
        ):
            earliest = rec
    summary = {
        "load": args.load,
        "nr_eval": args.nr_eval,
        "max_steps": args.max_steps,
        "threshold": args.threshold,
        "seed_stream": "777000+step, disjoint from training's 1000+epoch",
        "results": results,
        "earliest_at_threshold": earliest,
    }
    out = args.out or f"{args.load}/../eval_sweep.json"
    with open(out, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"wrote {out}", flush=True)
    if args.threshold is not None:
        print(
            "earliest independently-verified >= %.4g: %s"
            % (args.threshold, earliest or "NONE in sweep"),
            flush=True,
        )


if __name__ == "__main__":
    main()
