#!/usr/bin/env python
"""Actor-plane throughput instrument: the plane finally gets a PINNED number.

Measures the full ZMQ experience plane — C++ batched env servers → ZMQ →
master routing → batched predictor → n-step assembly → train queue — in two
predictor modes and (by default) both wire protocols:

- **device-free** (null predictor, host-side random actions): the plane's
  OWN ceiling, no device and no tunnel RTT in the loop. This is the number
  that pinned the per-env wire at 2,128 env-steps/s/host (PERF.md round 4)
  and the one the block wire's ≥40k acceptance bar is defined on.
- **device-in-loop** (``--device``): the same plane serving through the real
  batched predictor on whatever device jax finds. On the dev tunnel this is
  RTT-bound (~135 ms per fetch, PERF.md) — measured so the gap between the
  two modes stays attributed, not asserted.

Prints ONE JSON line on stdout (the repo's bench-tooling contract); per-mode
diagnostics go to stderr. Device-free runs force ``JAX_PLATFORMS=cpu`` and
never take the TPU-claim mutex — a plane bench must not queue behind (or
wedge) a training run when no device is in its loop.

Usage:
  python scripts/plane_bench.py                        # device-free, both wires
  python scripts/plane_bench.py --wires block          # device-free, block only
  python scripts/plane_bench.py --device --tpu_lock wait   # add device-in-loop
  python scripts/plane_bench.py --telemetry both       # telemetry overhead gate
                                                       # (same-session alternating
                                                       # off/on reps; fails if the
                                                       # median on-rate drops >2%
                                                       # below the median off-rate)
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
# sibling-script import surface (serving_bench rides along under --serving)
sys.path.insert(0, str(Path(__file__).resolve().parent))


def run_trace_capture(
    game: str = "pong",
    sample: int = 32,
    n_envs: int = 64,
    unroll_len: int = 5,
    feed_batch: int = 4,
    min_traces: int = 3,
    timeout_s: float = 120.0,
):
    """One traced block-shm plane through a REAL (CPU) V-trace learner.

    C++ env server (block-shm, trace contexts stamped 1-in-``sample``) →
    master → null predictor → unroll flush → RolloutFeed → device staging
    → the actual jitted ``parallel.vtrace_step`` — the full causal chain
    the trace plane exists to attribute, run until ``min_traces``
    complete env-step→learner-step traces are buffered. Returns
    ``(capture_dict, gate_failures)``; the capture embeds the raw
    ``/trace`` document plus a per-hop summary of one complete trace.
    """
    import queue
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.telemetry import tracing
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.data.dataflow import RolloutFeed
    from distributed_ba3c_tpu.envs import native
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.parallel.train_step import create_train_state
    from distributed_ba3c_tpu.parallel.vtrace_step import make_vtrace_train_step

    from bench import make_null_predictor
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    telemetry.reset_all()
    telemetry.set_enabled(True)
    os.environ["BA3C_TELEMETRY"] = "1"
    tracing.set_sampling(sample)
    os.environ["BA3C_TRACE"] = str(sample)

    n_actions = native.CppBatchedEnv(game, 1).num_actions
    cfg = BA3CConfig(num_actions=n_actions, predict_batch_size=max(64, n_envs))
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    mesh = make_mesh(num_model=1)
    step_fn = make_vtrace_train_step(
        model, make_optimizer(cfg.learning_rate, cfg.adam_epsilon,
                              cfg.grad_clip_norm), cfg, mesh,
    )
    state = jax.device_put(
        create_train_state(
            jax.random.PRNGKey(0), model, cfg,
            make_optimizer(cfg.learning_rate, cfg.adam_epsilon,
                           cfg.grad_clip_norm),
        ),
        step_fn.state_sharding,
    )

    tmp = tempfile.mkdtemp(prefix="ba3c-trace-cap-")
    c2s, s2c = f"ipc://{tmp}/c2s", f"ipc://{tmp}/s2c"
    predictor = make_null_predictor(
        model, params, n_actions, batch_size=max(64, n_envs), coalesce_ms=0.0,
    )
    master = VTraceSimulatorMaster(
        c2s, s2c, predictor, unroll_len=unroll_len,
        train_queue=queue.Queue(maxsize=256),
    )
    master.feed_batch = feed_batch
    feed = RolloutFeed(master.queue, batch_size=feed_batch)
    proc = native.CppEnvServerProcess(  # ba3clint: disable=A8 — raw plane is the measurand, like bench_zmq_plane
        0, c2s, s2c, game=game, n_envs=n_envs, wire="block-shm",
    )
    completed = 0
    steps = 0
    failures = []
    try:
        predictor.start()
        master.start()
        feed.start()
        proc.start()
        deadline = _time.monotonic() + timeout_s
        while completed < min_traces and _time.monotonic() < deadline:
            try:
                batch = feed.next_batch(timeout=10)
            except queue.Empty:
                continue
            ref = batch.pop("_trace", None)
            staged = {
                k: jax.device_put(v, step_fn.batch_sharding[k])
                for k, v in batch.items()
            }
            if ref is not None:
                ref = ref.hop("ingest", "learner")
            state, _metrics = step_fn(
                state, staged, cfg.entropy_beta, cfg.learning_rate
            )
            steps += 1
            if ref is not None:
                ref.hop("learner_step", "learner")
                completed += 1
    finally:
        proc.terminate()
        feed.stop()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        feed.join(timeout=2)

    doc = tracing.tracer().document()
    # pick ONE complete trace (env_step AND learner_step present) and
    # summarize its named hops in causal order
    by_trace = {}
    for s in doc["spans"]:
        by_trace.setdefault(s["trace_id"], []).append(s)
    chain = None
    for spans in by_trace.values():
        names = {s["name"] for s in spans}
        if "env_step" in names and "learner_step" in names:
            chain = sorted(spans, key=lambda s: s["ts_us"])
            break
    hop_hists = {
        f"{role}/{name}": m
        for role, series in telemetry.all_snapshots().items()
        for name, m in series.items()
        if name.startswith("hop_")
    }
    capture = {
        "game": game, "n_envs": n_envs, "wire": "block-shm",
        "sample_n": sample, "learner_steps": steps,
        "completed_traces": completed,
        "one_block_chain": [
            {"name": s["name"], "role": s["role"], "dur_us": s["dur_us"]}
            for s in (chain or [])
        ],
        "hop_histograms": hop_hists,
        "document": doc,
    }
    if chain is None:
        failures.append(
            "trace capture FAILED: no complete env-step->learner-step "
            f"trace after {steps} learner steps (completed={completed})"
        )
    elif len({s["name"] for s in chain}) < 6:
        failures.append(
            "trace capture FAILED: complete trace has fewer than 6 named "
            f"hops: {[s['name'] for s in chain]}"
        )
    else:
        stderr_print(
            "trace capture: one block-shm chain = "
            + " -> ".join(
                f"{s['name']}({s['dur_us']}us)" for s in chain
            )
        )
    return capture, failures


def run_ingest_phase(
    game: str = "pong",
    n_envs: int = 64,
    unroll_len: int = 5,
    feed_batch: int = 4,
    steps_per_arm: int = 40,
    sample: int = 4,
    timeout_s: float = 240.0,
):
    """The ingest before/after: legacy materialize→collate→device_put vs
    the staged pipeline (data/staging.py), SAME SESSION, device-free.

    Both arms run the full block-shm plane (C++ env server → master →
    null predictor → unroll flush → RolloutFeed) into the REAL jitted
    CPU V-trace learner; what differs is ONLY the ingest chain:

    - ``legacy``: plain RolloutFeed (compat collate: coerce + stack +
      time-major copy = 3 obs passes/batch) + per-key ``device_put`` at
      the head of the step — the measured ingest hop is that put chain.
    - ``staged``: RolloutFeed writing into a HostStagingRing (ONE obs
      pass/batch) wrapped in DeviceIngest — the H2D for batch k+1 is
      dispatched right after step k (prefetch), so the measured ingest
      hop is just the claim of already-dispatched device arrays.

    Gates (ISSUE 14 acceptance): staged copies-per-block == exactly 1.0
    (``ingest_copies_total / ingest_blocks_total``), and the staged
    median ingest hop ≥ 20% below the legacy median. Returns
    ``(row, gate_failures)``; the row embeds both arms' per-hop
    histograms and the master's e2e series as evidence.
    """
    import queue
    import statistics as _stats
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.telemetry import tracing
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.data.dataflow import RolloutFeed
    from distributed_ba3c_tpu.data.staging import DeviceIngest, HostStagingRing
    from distributed_ba3c_tpu.envs import native
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.parallel.train_step import create_train_state
    from distributed_ba3c_tpu.parallel.vtrace_step import make_vtrace_train_step

    from bench import make_null_predictor
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    n_actions = native.CppBatchedEnv(game, 1).num_actions
    cfg = BA3CConfig(num_actions=n_actions, predict_batch_size=max(64, n_envs))
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    mesh = make_mesh(num_model=1)
    opt = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    step_fn = make_vtrace_train_step(model, opt, cfg, mesh)

    def scalars():
        return telemetry.registry("learner").scalars()

    def arm(staged: bool) -> dict:
        telemetry.reset_all()
        telemetry.set_enabled(True)
        tracing.set_sampling(sample)
        os.environ["BA3C_TRACE"] = str(sample)
        state = jax.device_put(
            create_train_state(jax.random.PRNGKey(0), model, cfg, opt),
            step_fn.state_sharding,
        )
        tmp = tempfile.mkdtemp(prefix="ba3c-ingest-")
        c2s, s2c = f"ipc://{tmp}/c2s", f"ipc://{tmp}/s2c"
        predictor = make_null_predictor(
            model, params, n_actions,
            batch_size=max(64, n_envs), coalesce_ms=0.0,
        )
        master = VTraceSimulatorMaster(
            c2s, s2c, predictor, unroll_len=unroll_len,
            train_queue=queue.Queue(maxsize=256),
        )
        master.feed_batch = feed_batch
        ring = HostStagingRing() if staged else None
        feed = RolloutFeed(master.queue, batch_size=feed_batch, staging=ring)
        ingest = (
            DeviceIngest(feed, step_fn.batch_sharding) if staged else None
        )
        proc = native.CppEnvServerProcess(  # ba3clint: disable=A8 — raw plane is the measurand, like run_trace_capture
            0, c2s, s2c, game=game, n_envs=n_envs, wire="block-shm",
        )
        ingest_s = []
        steps = 0
        try:
            predictor.start()
            master.start()
            feed.start()
            proc.start()
            deadline = _time.monotonic() + timeout_s
            while steps < steps_per_arm and _time.monotonic() < deadline:
                if staged:
                    # wait for work WITHOUT timing the actor plane: the
                    # measurand is the step-path ingest hop, not feed wait
                    while (
                        not ingest.prefetch()
                        and _time.monotonic() < deadline
                    ):
                        _time.sleep(0.002)
                    t0 = _time.perf_counter()
                    try:
                        batch = ingest.next_batch(timeout=10)
                    except queue.Empty:
                        continue  # starved: the steps gate reports it
                    ingest_s.append(_time.perf_counter() - t0)
                    ref = batch.pop("_trace", None)
                else:
                    try:
                        batch = feed.next_batch(timeout=10)
                    except queue.Empty:
                        continue
                    ref = batch.pop("_trace", None)
                    t0 = _time.perf_counter()
                    batch = {
                        k: jax.device_put(v, step_fn.batch_sharding[k])
                        for k, v in batch.items()
                    }
                    ingest_s.append(_time.perf_counter() - t0)
                    if ref is not None:
                        ref = ref.hop("ingest", "learner")
                state, _m = step_fn(
                    state, batch, cfg.entropy_beta, cfg.learning_rate
                )
                steps += 1
                if ref is not None:
                    ref.hop("learner_step", "learner")
        finally:
            proc.terminate()
            if ingest is not None:
                ingest.stop()
            else:
                feed.stop()
            master.close()
            predictor.stop()
            predictor.join(timeout=5)
            feed.join(timeout=2)
        learner = scalars()
        hop_hists = {
            f"{role}/{name}": m
            for role, series in telemetry.all_snapshots().items()
            for name, m in series.items()
            if name.startswith(("hop_", "e2e_ingest", "staging_wait"))
        }
        copies = learner.get("ingest_copies_total", 0.0)
        blocks = learner.get("ingest_blocks_total", 0.0)
        row = {
            "staged": staged,
            "learner_steps": steps,
            "median_ingest_s": (
                _stats.median(ingest_s) if ingest_s else None
            ),
            "p90_ingest_s": (
                sorted(ingest_s)[int(0.9 * (len(ingest_s) - 1))]
                if ingest_s else None
            ),
            "ingest_copies_total": copies,
            "ingest_blocks_total": blocks,
            "copies_per_block": (
                round(copies / blocks, 4) if blocks else None
            ),
            "prefetched": learner.get("ingest_prefetched_total", 0.0),
            "dispatch_now": learner.get("ingest_dispatch_now_total", 0.0),
            "staging_waits": learner.get("staging_waits_total", 0.0),
            "hop_histograms": hop_hists,
        }
        stderr_print(
            f"ingest arm {'staged' if staged else 'legacy'}: "
            f"{steps} steps, median ingest "
            f"{(row['median_ingest_s'] or 0) * 1e6:.0f} us, "
            f"copies/block {row['copies_per_block']}"
        )
        return row

    failures = []
    legacy = arm(staged=False)
    staged = arm(staged=True)
    telemetry.reset_all()
    row = {
        "game": game, "n_envs": n_envs, "unroll_len": unroll_len,
        "feed_batch": feed_batch, "wire": "block-shm",
        "trace_sample": sample,
        # this container has no reachable accelerator: the H2D here is
        # the CPU PJRT transfer (de-aliased, data/staging.py) — the
        # on-chip re-capture stays on ROADMAP item 1's list
        "device_free_proxy": True,
        "legacy": legacy,
        "staged": staged,
    }
    if staged["learner_steps"] < steps_per_arm // 2 or legacy[
        "learner_steps"
    ] < steps_per_arm // 2:
        failures.append(
            "ingest phase FAILED: an arm starved before half its steps "
            f"(legacy {legacy['learner_steps']}, staged "
            f"{staged['learner_steps']} of {steps_per_arm})"
        )
        return row, failures
    if staged["copies_per_block"] != 1.0:
        failures.append(
            "ingest copy gate FAILED: staged copies-per-block = "
            f"{staged['copies_per_block']} (must be exactly 1.0 — "
            "shm bytes -> staging write, nothing else)"
        )
    if legacy["copies_per_block"] is None or legacy["copies_per_block"] <= 1.0:
        failures.append(
            "ingest foil broken: legacy copies-per-block = "
            f"{legacy['copies_per_block']} (expected > 1 — the before "
            "arm no longer measures the chain the staging replaced)"
        )
    ratio = (
        staged["median_ingest_s"] / legacy["median_ingest_s"]
        if legacy["median_ingest_s"] else None
    )
    row["staged_over_legacy_ingest"] = (
        round(ratio, 4) if ratio is not None else None
    )
    if ratio is None or ratio > 0.8:
        failures.append(
            "ingest latency gate FAILED: staged median ingest is "
            f"{ratio if ratio is None else round(ratio, 3)}x the legacy "
            "median (gate: <= 0.8x, i.e. >= 20% improvement same-session)"
        )
    return row, failures


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--game", default="pong")
    ap.add_argument("--n_envs", type=int, default=512)
    ap.add_argument(
        "--envs_per_proc", type=int, default=512,
        help="block size B: envs per server process (= envs per wire "
        "message). Fewer, bigger blocks win on few-core hosts: the "
        "committed capture's 1x512 beat 2x256 by ~40%% (scheduler "
        "contention; see docs/actor_plane.md)",
    )
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument(
        "--wires", default="block-shm,block,per-env",
        help="comma list of wire modes to measure "
        "(block-shm | block | per-env)",
    )
    ap.add_argument(
        "--windows", type=int, default=3,
        help="timed windows per mode; best window wins (scheduler-noise "
        "filter, same policy as bench_fused)",
    )
    ap.add_argument(
        "--device", action="store_true",
        help="ALSO measure device-in-loop (real predictor on whatever "
        "device jax finds; takes the TPU-claim mutex)",
    )
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    ap.add_argument(
        "--foil_shape", default="256/32",
        help="per-env foil fleet shape as N_ENVS/ENVS_PER_PROC. The "
        "historical 256/32 (the shape PERF.md's 2,128 baseline was pinned "
        "at) no longer comes up on this container (PERF.md round 7) — "
        "pass a feasible shape (e.g. 64/16) to re-measure the foil; the "
        "shape is recorded in the JSON row either way",
    )
    ap.add_argument(
        "--fleets", type=int, default=1,
        help="ALSO measure N independent fleets at the SAME per-fleet "
        "shape (per-fleet pipes/masters/predictors/telemetry roles, "
        "fleet-tagged idents) and gate the aggregate device-free rate at "
        ">= --fleet_gate x the single-fleet rate — the multi-fleet "
        "macro-batching scaling proof (docs/actor_plane.md)",
    )
    ap.add_argument(
        "--fleet_gate", type=float, default=1.6,
        help="minimum aggregate/single-fleet ratio for the --fleets gate",
    )
    ap.add_argument(
        "--serving", action="store_true",
        help="ALSO run the SLO-serving latency-vs-throughput frontier "
        "(scripts/serving_bench.py default sweep) and embed it under "
        "'serving' in the JSON; its SLO gate failures fail this run",
    )
    ap.add_argument(
        "--telemetry", default="on", choices=["on", "off", "both"],
        help="telemetry plane A/B: on = production default (instrumented "
        "masters/servers, fleet piggyback), off = BA3C_TELEMETRY=0 "
        "everywhere (pre-telemetry wire format), both = alternate off/on "
        "runs per wire in one session and FAIL unless the MEDIAN "
        "telemetry-on rate stays within 2%% of the median off rate (the "
        "overhead gate — runs/plane_bench_r7.json, PERF.md)",
    )
    ap.add_argument(
        "--pair_reps", type=int, default=3,
        help="(--telemetry both) off/on run pairs per wire, order "
        "alternating between reps; the gate compares medians — one pair "
        "is a coin flip against this container's run-to-run scheduler "
        "variance (PERF.md round 7)",
    )
    ap.add_argument(
        "--trace", default="off", choices=["on", "off", "both"],
        help="distributed trace plane A/B (telemetry/tracing.py): on = "
        "run with 1-in---trace_sample block sampling armed, off = "
        "tracing disarmed (the default), both = alternate off/on reps "
        "per wire in one session and FAIL unless the MEDIAN traced rate "
        "stays within 2%% of the median untraced rate (same methodology "
        "as --telemetry both; telemetry stays ON in both arms so the "
        "gate measures tracing's own marginal cost). on/both also run a "
        "block-shm capture through a REAL CPU V-trace learner and embed "
        "one complete env-step->learner-step trace under 'trace' in the "
        "JSON (runs/trace_bench_r13.json)",
    )
    ap.add_argument(
        "--trace_sample", type=int, default=64,
        help="1-in-N block sampling rate for the --trace arms",
    )
    ap.add_argument(
        "--ingest", action="store_true",
        help="ALSO run the staged-ingest before/after (data/staging.py): "
        "legacy materialize->collate->device_put vs the pinned staging "
        "ring + async H2D pipeline, same session through a REAL CPU "
        "V-trace learner. Gates: staged host copies-per-block == 1 "
        "exactly (ingest_copies_total) and staged median ingest hop "
        ">= 20%% below legacy (docs/ingest.md)",
    )
    ap.add_argument(
        "--ingest_steps", type=int, default=40,
        help="learner steps per --ingest arm",
    )
    args = ap.parse_args()

    wires = [w.strip() for w in args.wires.split(",") if w.strip()]
    for w in wires:
        if w not in ("block-shm", "block", "per-env"):
            raise SystemExit(f"unknown wire mode {w!r}")
    try:
        foil_envs, foil_per = (
            int(x) for x in args.foil_shape.replace("x", "/").split("/")
        )
        if foil_envs <= 0 or foil_per <= 0:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"--foil_shape {args.foil_shape!r} must be N_ENVS/ENVS_PER_PROC "
            "with both positive (e.g. 256/32)"
        )

    if not args.device:
        # device-free: no accelerator in the loop, so no TPU claim and no
        # tunnel — pin the platform BEFORE jax imports (bench_zmq_plane
        # builds params; on cpu that is milliseconds)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    else:
        from distributed_ba3c_tpu.utils.devicelock import guard_tpu

        _lock = guard_tpu(  # noqa: F841 — held for process lifetime
            "plane_bench",
            mode=args.tpu_lock,
            timeout_s=float(os.environ.get("BA3C_TPU_LOCK_TIMEOUT", "1800")),
        )

    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    from bench import bench_zmq_plane

    runs = {}
    overhead = {}
    trace_overhead = {}
    fleet_scaling = {}
    gate_failures = []
    for wire in wires:
        if wire == "per-env":
            # the compat foil is measured at ITS OWN fleet shape —
            # historically 256/32 (the shape PERF.md's 2,128 baseline was
            # pinned at), --foil_shape when that doesn't come up on the
            # host (PERF.md round 7); hundreds of DEALER sockets per
            # process is not a shape the per-env wire ever ran at
            n_envs, per = min(foil_envs, args.n_envs), foil_per
        else:
            n_envs, per = args.n_envs, args.envs_per_proc
        if args.telemetry == "both":
            # SAME-SESSION, ALTERNATING off/on reps: this container's
            # run-to-run variance is enormous (observed back-to-back
            # block-shm pairs at 0.90x AND 1.68x with zero code change —
            # the 1-core scheduler, not the plane), so one pair is a coin
            # flip against a 2% budget. Alternation + median-of-reps is
            # the honest comparison: slow host drift hits both arms
            # equally, and the median drops the starved-run outliers the
            # same way best-of-windows drops starved windows.
            off_vals, on_vals = [], []
            for rep in range(max(1, args.pair_reps)):
                for tele_on in (False, True) if rep % 2 == 0 else (True, False):
                    r = bench_zmq_plane(
                        game=args.game, n_envs=n_envs, seconds=args.seconds,
                        null_device=True, wire=wire, envs_per_proc=per,
                        windows=args.windows, telemetry_on=tele_on,
                    )
                    tag = "on" if tele_on else "off"
                    if wire == "per-env":
                        r["foil_shape"] = f"{n_envs}/{per}"
                    (on_vals if tele_on else off_vals).append(r["value"])
                    runs[f"nodevice_{wire}_telemetry_{tag}_rep{rep}"] = r
                    if tele_on:
                        runs[f"nodevice_{wire}"] = max(
                            runs.get(f"nodevice_{wire}", r), r,
                            key=lambda x: x["value"],
                        )
                    stderr_print(
                        f"device-free {wire:8s} (tele {tag:3s}, rep {rep}): "
                        f"{r['value']:>10.1f} env-steps/s/host"
                    )
            med_off = statistics.median(off_vals)
            med_on = statistics.median(on_vals)
            ratio = med_on / max(med_off, 1e-9)
            overhead[wire] = {
                "median_off": med_off, "median_on": med_on,
                "on_over_off": round(ratio, 4),
                "off_reps": off_vals, "on_reps": on_vals,
            }
            stderr_print(
                f"telemetry overhead {wire}: median on/off = "
                f"{med_on:.1f}/{med_off:.1f} = {ratio:.4f}"
            )
            if ratio < 0.98:
                # verdict is deferred to AFTER the JSON prints: the
                # per-rep evidence is most valuable exactly when the
                # gate fails
                gate_failures.append(
                    f"telemetry overhead gate FAILED on {wire}: median "
                    f"on-rate {med_on:.1f} is {100 * (1 - ratio):.1f}% "
                    f"below the median off-rate {med_off:.1f} (budget: 2%)"
                )
        else:
            r = bench_zmq_plane(
                game=args.game, n_envs=n_envs, seconds=args.seconds,
                null_device=True, wire=wire, envs_per_proc=per,
                windows=args.windows, telemetry_on=args.telemetry != "off",
                trace_sample=(
                    args.trace_sample if args.trace == "on" else 0
                ),
            )
            if wire == "per-env":
                # the foil's fleet shape is part of the number — rows are
                # not comparable across shapes (PERF.md rounds 4/7)
                r["foil_shape"] = f"{n_envs}/{per}"
            runs[f"nodevice_{wire}"] = r
            stderr_print(
                f"device-free {wire:8s}: {r['value']:>10.1f} env-steps/s/host"
            )
        if args.trace == "both":
            # tracing overhead gate: SAME alternating-medians methodology
            # as the telemetry gate above (and the same honest reason —
            # this container's scheduler variance dwarfs a 2% budget on
            # any single pair). Telemetry stays ON in both arms: the gate
            # measures the TRACE plane's marginal cost over the already-
            # gated telemetry baseline, not the sum of both planes.
            off_vals, on_vals = [], []
            for rep in range(max(1, args.pair_reps)):
                for tr_on in (False, True) if rep % 2 == 0 else (True, False):
                    r = bench_zmq_plane(
                        game=args.game, n_envs=n_envs, seconds=args.seconds,
                        null_device=True, wire=wire, envs_per_proc=per,
                        windows=args.windows, telemetry_on=True,
                        trace_sample=args.trace_sample if tr_on else 0,
                    )
                    tag = "on" if tr_on else "off"
                    (on_vals if tr_on else off_vals).append(r["value"])
                    runs[f"nodevice_{wire}_trace_{tag}_rep{rep}"] = r
                    stderr_print(
                        f"device-free {wire:8s} (trace {tag:3s}, rep {rep}): "
                        f"{r['value']:>10.1f} env-steps/s/host"
                    )
            med_off = statistics.median(off_vals)
            med_on = statistics.median(on_vals)
            ratio = med_on / max(med_off, 1e-9)
            trace_overhead[wire] = {
                "sample_n": args.trace_sample,
                "median_off": med_off, "median_on": med_on,
                "on_over_off": round(ratio, 4),
                "off_reps": off_vals, "on_reps": on_vals,
            }
            stderr_print(
                f"trace overhead {wire}: median on/off = "
                f"{med_on:.1f}/{med_off:.1f} = {ratio:.4f}"
            )
            if ratio < 0.98:
                gate_failures.append(
                    f"trace overhead gate FAILED on {wire}: median "
                    f"traced rate {med_on:.1f} is {100 * (1 - ratio):.1f}% "
                    f"below the median untraced rate {med_off:.1f} "
                    "(budget: 2%)"
                )
        if args.fleets > 1:
            # the multi-fleet arm at the SAME per-fleet shape, same
            # session (this container's run-to-run scheduler drift makes
            # cross-session ratios dishonest — PERF.md round 7); the
            # single-fleet arm is the nodevice_{wire} row just measured
            rf = bench_zmq_plane(
                game=args.game, n_envs=n_envs, seconds=args.seconds,
                null_device=True, wire=wire, envs_per_proc=per,
                windows=args.windows,
                telemetry_on=args.telemetry != "off",
                fleets=args.fleets,
            )
            runs[f"nodevice_{wire}_fleets{args.fleets}"] = rf
            single = runs[f"nodevice_{wire}"]["value"]
            ratio = rf["value"] / max(single, 1e-9)
            fleet_scaling[wire] = {
                "fleets": args.fleets,
                "single_fleet": single,
                "aggregate": rf["value"],
                "aggregate_over_single": round(ratio, 4),
                "gate": args.fleet_gate,
            }
            stderr_print(
                f"device-free {wire:8s} x{args.fleets} fleets: "
                f"{rf['value']:>10.1f} aggregate = {ratio:.2f}x single"
            )
            if ratio < args.fleet_gate:
                # verdict deferred to AFTER the JSON prints (evidence
                # first), per the plane_bench convention
                gate_failures.append(
                    f"fleet scaling gate FAILED on {wire}: "
                    f"{args.fleets}-fleet aggregate {rf['value']:.1f} is "
                    f"{ratio:.2f}x the single-fleet {single:.1f} "
                    f"(gate: >= {args.fleet_gate}x at equal per-fleet "
                    "shape)"
                )
        if args.device:
            r = bench_zmq_plane(
                game=args.game, n_envs=n_envs, seconds=args.seconds,
                null_device=False, wire=wire,
                envs_per_proc=per, windows=args.windows,
                telemetry_on=args.telemetry != "off",
            )
            runs[f"device_{wire}"] = r
            stderr_print(
                f"device     {wire:8s}: {r['value']:>10.1f} env-steps/s/host"
            )

    headline = (runs.get("nodevice_block-shm")
        or runs.get("nodevice_block") or next(iter(runs.values())))
    out = {
        "metric": "zmq_plane_env_steps_per_sec_per_host",
        # the headline is the best same-host block wire's device-free
        # rate: the plane's own ceiling here (the ISSUE-4 acceptance
        # number)
        "value": headline["value"],
        "unit": "env-steps/sec/host",
        "game": args.game,
        "n_envs": args.n_envs,
        "envs_per_proc": args.envs_per_proc,
        "seconds": args.seconds,
        "telemetry": args.telemetry,
        # the plane instrument drives f32 masters end to end — stamped so
        # every bench row names its rung of the rollout-precision ladder
        # (serving_bench --dtype covers the quantized rungs)
        "rollout_dtype": "float32",
        "runs": runs,
    }
    if overhead:
        # the overhead gate's evidence: per-rep off/on rates + median
        # ratio per wire, all measured alternating in THIS session
        # (PERF.md round 7 cites it)
        out["telemetry_overhead_on_over_off"] = overhead
    if trace_overhead:
        out["trace_overhead_on_over_off"] = trace_overhead
    if args.trace in ("on", "both"):
        # one REAL traced block-shm run through a CPU V-trace learner:
        # the committed evidence that a sampled block's causal chain is
        # complete env-step -> learner-step (runs/trace_bench_r13.json)
        capture, cap_failures = run_trace_capture(
            game=args.game, sample=args.trace_sample,
        )
        out["trace"] = capture.pop("document")
        out["trace_capture"] = capture
        gate_failures.extend(cap_failures)
    if args.ingest:
        # the staged-ingest before/after: copies-per-block + the ingest
        # hop collapse, measured same-session (ISSUE-14 acceptance;
        # committed as runs/plane_bench_r15.json)
        ingest_row, ingest_failures = run_ingest_phase(
            game=args.game,
            # one env server drives the rig: its block B is the smaller
            # of the fleet flags (the same flags every other phase obeys)
            n_envs=min(args.n_envs, args.envs_per_proc),
            steps_per_arm=args.ingest_steps,
        )
        out["ingest"] = ingest_row
        gate_failures.extend(ingest_failures)
    if fleet_scaling:
        # the multi-fleet scaling gate's evidence: single vs aggregate at
        # equal per-fleet shape, same session (ISSUE-10 acceptance)
        out["fleet_scaling"] = fleet_scaling
    if args.serving:
        # the SLO-serving frontier rides along (scripts/serving_bench.py
        # owns the sweep + gate; its default shape is device-free)
        import serving_bench

        serving_row, serving_failures = serving_bench.run_frontier(
            serving_bench.parse_opts([])
        )
        out["serving"] = serving_row
        gate_failures.extend(serving_failures)
    print(json.dumps(out))
    if gate_failures:
        for msg in gate_failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
