"""Split-jit experiment: rollout jit + learner jit vs the monolithic fused step.

Hypothesis (from profile_fused.py numbers): the learner runs at ~80% MFU as a
standalone jit on big flat batches but the monolithic rollout+learner program
schedules far worse (memory pressure → remat/spills near OOM). If
t(rollout_jit) + t(learner_jit) << t(monolith), restructure fused/loop.py
into two device calls per step.

``--overlap`` (ISSUE 8): measure the REAL two-program overlap schedule
(fused/overlap.py) instead of the round-1 ad-hoc split — per-program wall
times (medians over ``--reps`` probe reps), the measured learner-hidden
fraction of the actor, and ``learner_window_coverage`` (min(1,
t_learner/t_actor)) — the device-free proxy gate quantity: how much of the
actor's wall time the learner window is long enough to hide. Prints ONE
JSON line on stdout (the repo's bench-tooling contract); diagnostics go to
stderr. PERF.md round 9 records why realized concurrency is additionally
backend-dependent (this jax's CPU client multiplexes every execution onto
one shared intra-op pool).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.jaxenv import pong
from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import inject_learning_rate
from distributed_ba3c_tpu.ops.loss import a3c_loss
from distributed_ba3c_tpu.ops.returns import n_step_returns
from distributed_ba3c_tpu.parallel.mesh import make_mesh

N_ENVS = 1024
T = 20


def profile_overlap(n_envs: int, rollout_len: int, fc_units: int,
                    reps: int) -> dict:
    """Probe the real overlap programs: solo/pair wall times + hiding."""
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step

    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=fc_units)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer

    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon,
                         cfg.grad_clip_norm)
    mesh = make_mesh()
    n_chips = len(jax.devices())
    step = make_overlap_step(model, opt, cfg, mesh, pong,
                             rollout_len=rollout_len)
    state = step.put(create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong,
        n_envs * n_chips, n_shards=n_chips,
    ))
    t0 = time.perf_counter()
    state, m = step(state, cfg.entropy_beta)
    float(m["loss"])  # compile + warmup fence
    print(f"warmup (compile all programs): {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    state, probe = step.probe_overlap(state, cfg.entropy_beta, reps=reps)
    return {
        "metric": "overlap_split_profile",
        # probe carries the device-free proxy gate quantity
        # (learner_window_coverage: the learner window is long enough to
        # hide this fraction of the actor's wall time; realized hiding
        # additionally needs concurrent execution queues — on-chip
        # BENCH_r06 territory; overlap_efficiency is what THIS backend
        # realizes)
        **probe,
        "n_envs": n_envs * n_chips,
        "rollout_len": rollout_len,
        "fc_units": fc_units,
        "n_chips": n_chips,
        "device_kind": jax.devices()[0].device_kind,
    }


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    ap.add_argument("--overlap", action="store_true",
                    help="probe the real two-program overlap schedule "
                    "(fused/overlap.py) and print one JSON line")
    ap.add_argument("--n_envs", type=int, default=None,
                    help="--overlap: envs per chip (default 128, the "
                    "flagship shape; shrink for CPU proxy captures)")
    ap.add_argument("--rollout_len", type=int, default=20)
    ap.add_argument("--fc_units", type=int, default=None,
                    help="--overlap: net width (default the real 512; "
                    "shrink for CPU proxy captures)")
    ap.add_argument("--reps", type=int, default=5,
                    help="--overlap: probe repetitions (medians reported)")
    args = ap.parse_args()

    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    _lock = guard_tpu("profile_split", mode=args.tpu_lock)  # noqa: F841

    if args.overlap:
        row = profile_overlap(
            n_envs=args.n_envs or 128,
            rollout_len=args.rollout_len,
            fc_units=args.fc_units or 512,
            reps=args.reps,
        )
        print(json.dumps(row))
        return

    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer

    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    state = create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong, N_ENVS, n_shards=1
    )

    # ---------------- rollout jit ----------------
    @jax.jit
    def rollout(params, env_state, stack, key, ep_ret):
        def body(carry, _):
            es, st, k, er = carry
            out = model.apply({"params": params}, st)
            k, ka, ke = jax.random.split(k, 3)
            a = jax.random.categorical(ka, out.logits, -1).astype(jnp.int32)
            es, obs, r, d = jax.vmap(pong.step)(es, a, jax.random.split(ke, N_ENVS))
            keep = (~d).astype(st.dtype)[:, None, None, None]
            st2 = jnp.concatenate([st[..., 1:] * keep, obs[..., None]], axis=-1)
            er = er + r
            return (es, st2, k, er * (1.0 - d.astype(jnp.float32))), (st, a, r, d)

        (es, st, k, er), traj = jax.lax.scan(
            body, (env_state, stack, key, ep_ret), None, length=T
        )
        bootstrap = model.apply({"params": params}, st).value
        states_t, actions_t, rewards_t, dones_t = traj
        returns_t = n_step_returns(
            rewards_t, dones_t.astype(jnp.float32),
            jax.lax.stop_gradient(bootstrap), cfg.gamma,
        )
        return es, st, k, er, states_t, actions_t, returns_t

    # ---------------- learner jit (flat, donates traj) -------------------
    def make_learner(n_chunks):
        def learner(train, states_t, actions_t, returns_t, beta, lr):
            params = train.params
            sf = states_t.reshape(T * N_ENVS, 84, 84, cfg.frame_history)
            af = actions_t.reshape(-1)
            rf = returns_t.reshape(-1)

            def chunk_grad(p, chunk):
                sc, ac, rc = chunk

                def loss_fn(pp):
                    out = model.apply({"params": pp}, sc)
                    l = a3c_loss(out.logits, out.value, ac, rc,
                                 entropy_beta=beta,
                                 value_loss_coef=cfg.value_loss_coef)
                    return l.total, l

                return jax.value_and_grad(loss_fn, has_aux=True)(p)

            if n_chunks == 1:
                (_, aux), grads = chunk_grad(params, (sf, af, rf))
            else:
                C = (T * N_ENVS) // n_chunks
                ch = lambda x: x.reshape(n_chunks, C, *x.shape[1:])  # noqa: E731

                def acc(carry, chunk):
                    g_acc, aux_acc = carry
                    (_, aux), g = chunk_grad(params, chunk)
                    return (
                        jax.tree_util.tree_map(jnp.add, g_acc, g),
                        jax.tree_util.tree_map(jnp.add, aux_acc, aux),
                    ), None

                (_, aux0), g0 = chunk_grad(
                    params, (ch(sf)[0], ch(af)[0], ch(rf)[0])
                )
                (grads, aux), _ = jax.lax.scan(
                    acc, (g0, aux0), (ch(sf)[1:], ch(af)[1:], ch(rf)[1:])
                )
                grads = jax.tree_util.tree_map(lambda g: g / n_chunks, grads)

            import optax

            opt_state = inject_learning_rate(train.opt_state, lr)
            updates, new_opt = opt.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return train.replace(
                step=train.step + 1, params=new_params, opt_state=new_opt
            )

        return jax.jit(learner, donate_argnums=(0, 1, 2, 3))

    env_state, stack, key, ep_ret = (
        state.env_state, state.obs_stack, state.key[0], state.ep_return,
    )
    params = state.train.params
    train = state.train

    for n_chunks in (1, 2, 4):
        try:
            learner = make_learner(n_chunks)
            # warm both
            es, st, k, er, S, A, R = rollout(params, env_state, stack, key, ep_ret)
            train2 = learner(train, S, A, R, cfg.entropy_beta, cfg.learning_rate)
            # warmup sync: a profiler must force the compile before timing
            jax.block_until_ready(train2)  # ba3clint: disable=J1

            iters = 10
            t0 = time.perf_counter()
            es, st, k, er = env_state, stack, key, ep_ret
            tr = train2
            for _ in range(iters):
                es, st, k, er, S, A, R = rollout(tr.params, es, st, k, er)
                tr = learner(tr, S, A, R, cfg.entropy_beta, cfg.learning_rate)
            # measurement fence: the timed region must include execution
            jax.block_until_ready(tr)  # ba3clint: disable=J1
            dt = (time.perf_counter() - t0) / iters
            print(
                f"split n_chunks={n_chunks}: {dt*1e3:7.2f}ms/step "
                f"({N_ENVS*T/dt:9.0f} sps)",
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            print(f"split n_chunks={n_chunks}: FAILED {type(e).__name__}", flush=True)

    # monolith reference
    step = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=T,
                           grad_chunk_samples=2048)
    fstate = step.put(
        create_fused_state(jax.random.PRNGKey(0), model, cfg, opt, pong,
                           N_ENVS, n_shards=1)
    )
    s, m = step(fstate, cfg.entropy_beta)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(10):
        s, m = step(s, cfg.entropy_beta)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / 10
    print(f"monolith chunk=2048: {dt*1e3:7.2f}ms/step ({N_ENVS*T/dt:9.0f} sps)")


if __name__ == "__main__":
    main()
