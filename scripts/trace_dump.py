#!/usr/bin/env python
"""Render /trace documents as Chrome trace-event / Perfetto JSON.

Inputs are one or more trace documents — live scrapes
(``http://host:9100/trace``), saved scrape files, or bench JSONs that
embed one under a ``"trace"`` key (``plane_bench --trace``). Every
process's spans are merged onto ONE timeline and written in the Chrome
trace-event format, which ``chrome://tracing`` and https://ui.perfetto.dev
both load directly:

    python scripts/trace_dump.py http://localhost:9100/trace -o trace.json
    python scripts/trace_dump.py learner.json host0.json host1.json \\
        -o pod_trace.json
    python scripts/trace_dump.py runs/trace_bench_r13.json --validate

Timeline merge: the FIRST input is the root. Each document carries a
``(anchor_monotonic_us, anchor_wall)`` pair (the flight recorder's anchor
idiom), so another process's monotonic timeline maps onto the root's
through wall time. That is NTP-quality alignment between machines; the
finer signal — the min-filtered monotonic-offset handshake each receiver
measured against its wire peers (telemetry/tracing.py) — ships verbatim
under ``clock_offsets_us`` in each document and in the output's
``metadata`` for exact per-peer analysis. Spans a receiver synthesized
for a remote sender (env_step, wire, pod_wire) were ALREADY aligned
through that handshake at receive time, so single-scrape dumps need no
merge step at all.

``--validate`` checks the emitted JSON against the trace-event schema the
CI ``tracing`` job gates on: required keys per event, complete (``ph: X``)
events with non-negative ``dur``, and monotone ``ts`` within each
``(pid, tid)`` track.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import List, Tuple


def load_document(source: str) -> dict:
    """One trace document from a URL, a scrape file, or a bench JSON
    embedding it under ``"trace"``."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=10) as resp:
            doc = json.load(resp)
    else:
        with open(source) as fh:
            doc = json.load(fh)
    if "spans" not in doc and isinstance(doc.get("trace"), dict):
        doc = doc["trace"]
    if "spans" not in doc:
        raise ValueError(
            f"{source}: not a trace document (no 'spans'; scrape "
            "/trace — not /json — or pass a plane_bench --trace JSON)"
        )
    return doc


def merge_events(docs: List[dict]) -> Tuple[List[dict], dict]:
    """Chrome trace events + metadata from one or more documents.

    The first document's monotonic timeline is the root; later documents
    shift onto it through the wall anchors (see module docstring)."""
    root = docs[0]
    root_mono = float(root.get("anchor_monotonic_us", 0))
    root_wall = float(root.get("anchor_wall", 0.0))
    events: List[dict] = []
    offsets = {}
    for idx, doc in enumerate(docs):
        real_pid = int(doc.get("pid", 0))
        # the Chrome pid is the DOCUMENT index, not the OS pid: two hosts'
        # containers commonly share a pid (often both 1), and a bare-pid
        # key would merge their tracks and overwrite the first host's
        # alignment metadata; the real pid survives in the track name
        pid = idx
        shift = 0.0
        if doc is not root and root_wall and doc.get("anchor_wall"):
            # doc-local mono -> wall -> root-local mono
            shift = (
                (float(doc["anchor_wall"]) - root_wall) * 1e6
                + root_mono
                - float(doc.get("anchor_monotonic_us", 0))
            )
        offsets[f"doc{idx}"] = {
            "source_pid": real_pid,
            "shift_us": shift,
            "clock_offsets_us": doc.get("clock_offsets_us", {}),
            "dropped_spans": doc.get("dropped_spans", 0),
        }
        roles = set()
        for s in doc["spans"]:
            role = s.get("role", "?")
            roles.add(role)
            args = {
                "trace_id": f"{int(s['trace_id']):016x}",
                "span_id": f"{int(s['span_id']):016x}",
                "parent_id": f"{int(s.get('parent_id', 0)):016x}",
            }
            if s.get("tags"):
                args.update(s["tags"])
            events.append({
                "name": s["name"],
                "cat": role,
                "ph": "X",
                "ts": float(s["ts_us"]) + shift,
                "dur": max(0.0, float(s.get("dur_us", 0))),
                "pid": pid,
                "tid": role,  # one track per role within the process
                "args": args,
            })
        # metadata events name the process tracks in the Perfetto UI
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"ba3c doc{idx} (pid {real_pid})"},
        })
    events.sort(key=lambda e: (e["ph"] == "M", e.get("ts", 0)))
    return events, offsets


def validate(events: List[dict]) -> List[str]:
    """Schema check the CI smoke gates on; returns problem strings."""
    problems = []
    last_ts: dict = {}
    for i, ev in enumerate(events):
        if ev.get("ph") == "M":
            if "name" not in ev or "args" not in ev:
                problems.append(f"event {i}: metadata event missing name/args")
            continue
        missing = [k for k in ("name", "ph", "ts", "dur", "pid", "tid")
                   if k not in ev]
        if missing:
            problems.append(f"event {i}: missing keys {missing}")
            continue
        if ev["ph"] != "X":
            problems.append(f"event {i}: unexpected phase {ev['ph']!r}")
        if ev["dur"] < 0:
            problems.append(f"event {i}: negative dur {ev['dur']}")
        track = (ev["pid"], ev["tid"])
        if ev["ts"] < last_ts.get(track, float("-inf")):
            problems.append(
                f"event {i}: ts {ev['ts']} not monotone within track {track}"
            )
        last_ts[track] = ev["ts"]
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "inputs", nargs="+",
        help="trace documents: /trace URLs, scrape files, or bench JSONs "
        "with an embedded 'trace' key; the FIRST is the root timeline",
    )
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: stdout)")
    ap.add_argument(
        "--validate", action="store_true",
        help="check the emitted events against the trace-event schema "
        "(required keys, monotone ts per track) and exit non-zero on "
        "any problem — the CI tracing job's smoke",
    )
    args = ap.parse_args(argv)

    docs = [load_document(s) for s in args.inputs]
    events, offsets = merge_events(docs)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "tool": "distributed_ba3c_tpu scripts/trace_dump.py",
            "root": args.inputs[0],
            "alignment": offsets,
        },
    }
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(doc, fh)
        print(
            f"{len(events)} events from {len(docs)} process(es) -> "
            f"{args.out} (load in chrome://tracing or ui.perfetto.dev)",
            file=sys.stderr,
        )
    else:
        json.dump(doc, sys.stdout)
        print(file=sys.stdout)
    if args.validate:
        problems = validate(events)
        for p in problems:
            print(f"VALIDATE: {p}", file=sys.stderr)
        if problems:
            return 1
        print(f"validated {len(events)} events OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
