#!/usr/bin/env python
"""Chaos acceptance gate: the orchestrated plane under random SIGKILLs.

Exercises the full orchestration stack (docs/orchestration.md) device-free
in one process tree and prints ONE JSON line (the repo's bench-tooling
contract, like plane_bench_r6/r7):

1. **control**: a supervised C++ env-server fleet -> ZMQ -> master -> null
   predictor -> n-step assembly, measured with NO chaos — the steady-state
   baseline.
2. **chaos**: the same plane while a seeded :class:`ChaosMonkey` SIGKILLs
   ``--kills`` (default 3) servers mid-measurement and the
   :class:`FleetSupervisor` respawns them. GATE: the chaos rate must hold
   >= ``--gate`` (default 0.90) of control. Control/chaos reps alternate
   in one session and the gate compares MEDIANS (scheduler drift hits
   both arms equally — the plane_bench_r7 lesson).
3. **autoscale**: a fleet launched at ``fleet_min`` grows to ``fleet_max``
   purely from the starvation signal (queue fill below the low watermark)
   — scale decisions land as flight events + ``tele/orchestrator/*``.
4. **failover**: a real ``train.py`` run under :class:`LearnerSupervisor`
   is SIGKILLed after its first FINALIZED checkpoint and must resume from
   it without operator action, completing its full epoch budget.

The JSON carries the per-rep rates, the orchestrator registry snapshot and
the orchestration flight events — the postmortem evidence IS the bench
artifact (committed as ``runs/chaos_bench_r8.json``). Exit 1 if the
throughput gate or the failover fails. Device-free: forces
``JAX_PLATFORMS=cpu``, never touches the TPU pool.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import statistics
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: flight-event kinds that belong to the orchestration story — the JSON
#: embeds exactly these so the committed artifact shows scale / respawn /
#: failover evidence without a 4096-event dump
_ORCH_KINDS = (
    "server_spawn", "server_respawn", "server_death", "chaos_kill",
    "scale_up", "scale_down", "scale_decision", "circuit_open",
    "circuit_close", "wedged_kill", "learner_failover", "learner_giveup",
    "incarnation_reset", "prune",
)


def _drain_warmup(master, n: int, first_timeout: float = 300.0) -> None:
    from bench import stall_attribution

    try:
        master.queue.get(timeout=first_timeout)
        for _ in range(n - 1):
            master.queue.get(timeout=60)
    except queue.Empty:
        raise RuntimeError(
            f"plane produced no warmup data — {stall_attribution()}"
        ) from None


def _measure(master, seconds: float, windows: int) -> list:
    """Datapoints/s entering the train queue, per window, drained in
    bursts (a blocking consumer would make every producer put pay a futex
    wake — bench.py's measured lesson). No stall-raise here: brief dips
    are exactly what a chaos window produces. Returns the per-window
    rates; the caller takes the BEST window (the repo's scheduler-noise
    filter) — under chaos every window still contains kills, because the
    kill interval is shorter than a window."""
    q = master.queue
    rates = []
    for _ in range(max(1, windows)):
        t0 = time.perf_counter()
        deadline = t0 + seconds
        n = 0
        while True:
            now = time.perf_counter()
            if now >= deadline:
                break
            try:
                q.get_nowait()
                n += 1
            except queue.Empty:
                time.sleep(0.002)
        rates.append(round(n / (time.perf_counter() - t0), 1))
    return rates


class _Plane:
    """One supervised device-free plane (fleet + master + null predictor)."""

    def __init__(
        self, game: str, n_servers: int, per: int, wire: str,
        fleet_min=None, fleet_max=None, backoff_base_s: float = 0.25,
    ):
        import jax
        import numpy as np

        from bench import make_null_predictor
        from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
        from distributed_ba3c_tpu.config import BA3CConfig
        from distributed_ba3c_tpu.envs import native
        from distributed_ba3c_tpu.models.a3c import BA3CNet
        from distributed_ba3c_tpu.orchestrate import FleetSpec, FleetSupervisor

        n_actions = native.CppBatchedEnv(game, 1).num_actions
        cfg = BA3CConfig(
            num_actions=n_actions, predict_batch_size=max(256, per)
        )
        model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
        params = model.init(
            jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
        )["params"]
        self.predictor = make_null_predictor(
            model, params, n_actions,
            batch_size=max(cfg.predict_batch_size, per), num_threads=2,
            coalesce_ms=0.0,
        )
        tmp = tempfile.mkdtemp(prefix="ba3c-chaos-")
        c2s, s2c = f"ipc://{tmp}/c2s", f"ipc://{tmp}/s2c"
        # actor_timeout None: respawns land inside the master's patience,
        # so a respawned slot re-enters as an INCARNATION RESET (same
        # ident, step going backwards) — the PR-4 machinery under test
        self.master = BA3CSimulatorMaster(
            c2s, s2c, self.predictor,
            gamma=cfg.gamma, local_time_max=cfg.local_time_max,
            score_queue=queue.Queue(maxsize=100_000),
        )
        self.spec = FleetSpec(
            pipe_c2s=c2s, pipe_s2c=s2c, game=game, envs_per_server=per,
            wire=wire, fleet_size=n_servers,
            fleet_min=fleet_min if fleet_min is not None else n_servers,
            fleet_max=fleet_max if fleet_max is not None else n_servers,
            backoff_base_s=backoff_base_s, backoff_max_s=5.0,
            stable_after_s=5.0, restart_budget=64, budget_window_s=120.0,
        )
        self.supervisor = FleetSupervisor(self.spec, poll_interval_s=0.1)

    def start(self) -> None:
        self.predictor.start()
        self.master.start()
        self.supervisor.start()

    def settle(self, timeout_s: float = 60.0) -> bool:
        """Wait until every target slot is live again (post-chaos)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.supervisor.live_count() >= self.supervisor.target:
                return True
            time.sleep(0.2)
        return False

    def close(self) -> None:
        self.supervisor.stop()
        self.supervisor.join(timeout=5)
        self.supervisor.close()
        self.master.close()
        self.predictor.stop()
        self.predictor.join(timeout=5)


def _phase_rate(args, chaos_kills: int, seed: int) -> dict:
    """One rep: bring a plane up, (optionally) unleash the monkey inside
    the measurement window, return the rate + orchestration evidence."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate import ChaosMonkey

    telemetry.reset_all()
    plane = _Plane(args.game, args.n_servers, args.envs_per_proc, args.wire)
    monkey = None
    try:
        plane.start()
        _drain_warmup(plane.master, args.warmup_datapoints)
        if chaos_kills:
            # the monkey kills CONTINUOUSLY at an interval shorter than
            # one window, so the fleet is in some phase of dying or
            # respawning inside EVERY window — best-of-windows then
            # filters scheduler starvation, never a kill-free window
            interval = args.seconds / (chaos_kills + 1)
            monkey = ChaosMonkey(
                plane.supervisor,
                interval_s=interval,
                jitter_s=min(0.2, interval / 4),
                max_kills=None,
                seed=seed,
                initial_delay_s=interval / 2,
            )
            monkey.start()
        window_rates = _measure(plane.master, args.seconds, args.windows)
        out = {"rate": max(window_rates), "window_rates": window_rates}
        if chaos_kills:
            monkey.stop()
            monkey.join(timeout=5)
            out["kills"] = monkey.kills
            out["settled"] = plane.settle()
            reg = telemetry.registry("orchestrator")
            out["respawns"] = reg.counter("server_respawns_total").value()
            out["fleet_live_size"] = reg.gauge("fleet_live_size").value()
            out["fleet_target_size"] = reg.gauge("fleet_target_size").value()
            out["incarnation_resets"] = (
                telemetry.registry("master")
                .counter("incarnation_resets_total").value()
            )
            out["orchestrator_series"] = reg.scalars()
        return out
    finally:
        if monkey is not None:
            monkey.stop()
        plane.close()


def _phase_autoscale(args) -> dict:
    """fleet_min -> fleet_max on the starvation signal alone."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate import (
        Autoscaler,
        AutoscalerPolicy,
        master_signals,
    )

    telemetry.reset_all()
    fleet_max = min(3, args.n_servers)
    plane = _Plane(
        args.game, 1, args.envs_per_proc, args.wire,
        fleet_min=1, fleet_max=fleet_max,
    )
    scaler = Autoscaler(
        plane.supervisor,
        master_signals(plane.master),
        policy=AutoscalerPolicy(patience=2, cooldown_ticks=1),
        interval_s=0.5,
    )
    from distributed_ba3c_tpu.utils.concurrency import LoopThread

    def drain_once():  # a hungry learner: keeps the queue at the low watermark
        try:
            plane.master.queue.get(timeout=0.2)
        except queue.Empty:
            pass

    drainer = LoopThread(drain_once)
    try:
        plane.start()
        drainer.start()
        scaler.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if plane.supervisor.live_count() >= fleet_max:
                break
            time.sleep(0.5)
        reg = telemetry.registry("orchestrator")
        return {
            "fleet_min": 1,
            "fleet_max": fleet_max,
            "reached_live": plane.supervisor.live_count(),
            "scale_up_events": reg.counter("scale_up_total").value(),
            "autoscale_ticks": reg.counter("autoscale_ticks_total").value(),
        }
    finally:
        scaler.stop()
        scaler.join(timeout=5)
        drainer.stop()
        drainer.join(timeout=5)
        plane.close()


def _phase_failover(args) -> dict:
    """SIGKILL a real learner after its first finalized checkpoint; the
    supervisor must resume it from that checkpoint to a clean finish."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate import LearnerSupervisor, finalized_step

    logdir = os.path.join(
        tempfile.mkdtemp(prefix="ba3c-chaos-failover-"), "run"
    )
    ckpt_dir = os.path.join(logdir, "checkpoints")
    train_args = [
        "--env", "fake",
        "--simulator_procs", "2",
        "--batch_size", "16",
        "--image_size", "16",
        "--fc_units", "16",
        "--steps_per_epoch", str(args.failover_steps_per_epoch),
        "--max_epoch", "3",
        "--nr_eval", "0",
        "--logdir", logdir,
    ]
    sup = LearnerSupervisor(
        logdir, train_args, max_restarts=3, poll_s=0.2
    )
    killed = {}

    def killer():
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            step = finalized_step(ckpt_dir)
            pid = sup.child_pid
            if step is not None and pid is not None:
                killed["at_step"] = step
                try:
                    os.killpg(pid, signal.SIGKILL)  # the whole process group
                except (OSError, ProcessLookupError):
                    pass
                return
            time.sleep(0.3)

    from distributed_ba3c_tpu.utils.concurrency import StoppableThread

    kt = StoppableThread(target=killer, daemon=True)
    kt.start()
    rc = sup.run()
    kt.join(timeout=5)
    reg = telemetry.registry("orchestrator")
    stats_path = os.path.join(logdir, "stat.json")
    epochs = None
    if os.path.isfile(stats_path):
        with open(stats_path) as fh:
            epochs = len(json.load(fh))
    final = finalized_step(ckpt_dir)
    return {
        "rc": rc,
        "killed_at_step": killed.get("at_step"),
        "resumes": reg.counter("learner_resumes_total").value(),
        "restarts": reg.counter("learner_restarts_total").value(),
        "final_step": final,
        "epochs_in_stat_json": epochs,
        # resume proof is STEP CONTINUITY: the relaunched learner restored
        # the killed attempt's finalized step and trained PAST it (the ZMQ
        # trainer's --max_epoch budget is per-attempt, so stat.json may
        # carry the pre-kill epochs plus the resumed run's — epoch count
        # alone cannot distinguish resume from restart; steps can)
        "ok": rc == 0
        and killed.get("at_step") is not None
        and reg.counter("learner_resumes_total").value() >= 1
        and final is not None
        and final > killed.get("at_step", 0)
        and (epochs or 0) >= 3,
    }


def _phase_network(args) -> dict:
    """The netchaos phase (docs/netchaos.md): the pod's DCN-shaped links
    under emulated 50 ms RTT + 1% loss must hold >= --net_gate of the
    clean-proxy control, a timed full partition must heal restart-free
    with only typed counters, and every rep must replay from its seed."""
    from distributed_ba3c_tpu.netchaos.bench import (
        NetShape,
        dcn_schedule,
        quiet_schedule,
        run_partition_rep,
        run_throughput_rep,
    )

    shape = NetShape(
        hosts=1,
        sims_per_host=args.net_sims,
        segments_per_block=8,
        warmup_timeout=args.warmup_timeout_net,
    )
    clean = run_throughput_rep(
        shape, quiet_schedule(args.seed), args.net_seconds, args.net_windows
    )
    dcn = run_throughput_rep(
        shape,
        dcn_schedule(args.net_rtt_ms, args.net_loss, seed=args.seed),
        args.net_seconds,
        args.net_windows,
    )
    ratio = round(dcn["rate"] / max(clean["rate"], 1e-9), 4)
    partition = run_partition_rep(shape, args.seed, partition_s=10.0)
    return {
        "rtt_ms": args.net_rtt_ms,
        "loss": args.net_loss,
        "clean": clean,
        "dcn": dcn,
        "dcn_over_clean": ratio,
        "gate": args.net_gate,
        "gate_passed": ratio >= args.net_gate,
        "partition": partition,
        "replay_ok": bool(
            clean["replay"]["match"]
            and dcn["replay"]["match"]
            and partition["replay"]["match"]
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--game", default="pong")
    ap.add_argument(
        "--n_servers", type=int, default=8,
        help="fleet size in server processes — each kill idles 1/K of "
        "the fleet for the respawn latency, so K sizes the gate headroom",
    )
    ap.add_argument("--envs_per_proc", type=int, default=16)
    ap.add_argument("--wire", default="block", choices=["block-shm", "block", "per-env"])
    ap.add_argument("--seconds", type=float, default=12.0, help="seconds per measurement window")
    ap.add_argument(
        "--windows", type=int, default=3,
        help="windows per rep; the BEST window is the rep's rate (the "
        "repo's scheduler-noise filter, bench.py policy). Chaos kills "
        "run through ALL windows, so no window is kill-free",
    )
    ap.add_argument(
        "--kills", type=int, default=3,
        help="kill pacing: the monkey SIGKILLs every seconds/(kills+1) "
        "continuously through the rep — >= this many land inside every "
        "window (acceptance: >=3 mid-run)",
    )
    ap.add_argument(
        "--pair_reps", type=int, default=3,
        help="alternating control/chaos rep pairs; the gate compares "
        "MEDIANS — with 3+ pairs one scheduler-starved rep cannot decide "
        "the verdict (the plane_bench_r7 lesson: this container swings "
        "2x run-to-run with zero code change)",
    )
    ap.add_argument("--gate", type=float, default=0.90)
    ap.add_argument("--warmup_datapoints", type=int, default=512)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--skip_failover", action="store_true")
    ap.add_argument("--skip_autoscale", action="store_true")
    ap.add_argument(
        "--net", action="store_true",
        help="add the netchaos network phase: pod-link throughput under "
        "--net_rtt_ms/--net_loss vs a quiet-proxy control, the "
        "partition-and-heal rep, and the seed-replay verdict "
        "(docs/netchaos.md)",
    )
    ap.add_argument(
        "--net_only", action="store_true",
        help="run ONLY the network phase (no native env core needed — "
        "the pod rig runs fake env hosts); the CI netchaos job's mode",
    )
    ap.add_argument("--net_rtt_ms", type=float, default=50.0)
    ap.add_argument("--net_loss", type=float, default=0.01)
    ap.add_argument("--net_gate", type=float, default=0.85, help="degraded pod throughput must hold >= this x the quiet-proxy control")
    ap.add_argument("--net_seconds", type=float, default=6.0)
    ap.add_argument("--net_windows", type=int, default=2)
    ap.add_argument("--net_sims", type=int, default=2, help="fake sims per pod host in the network phase")
    ap.add_argument("--warmup_timeout_net", type=float, default=240.0)
    ap.add_argument(
        "--failover_steps_per_epoch", type=int, default=60,
        help="failover phase train.py epoch length (checkpoint cadence)",
    )
    args = ap.parse_args()

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.envs import native
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    if args.net_only:
        # the network phase is self-contained (fake-env pod hosts): its
        # own JSON, its own gates, evidence before verdict
        net = _phase_network(args)
        stderr_print(
            f"network: clean {net['clean']['rate']:.1f} vs "
            f"{net['rtt_ms']:.0f}ms/{100 * net['loss']:.1f}% "
            f"{net['dcn']['rate']:.1f} env-steps/s "
            f"({net['dcn_over_clean']:.3f}x, gate {net['gate']}), "
            f"partition recovered={net['partition']['recovered']}, "
            f"replay={net['replay_ok']}"
        )
        out = {
            "metric": "netchaos_pod_dcn_over_clean",
            "value": net["dcn_over_clean"],
            "unit": "ratio (degraded/clean ingest env-steps/s)",
            "network": net,
        }
        # evidence prints BEFORE the verdict (the repo's bench contract)
        print(json.dumps(out))
        ok = (
            net["gate_passed"]
            and net["partition"]["recovered"]
            and net["replay_ok"]
        )
        if not ok:
            stderr_print(f"network phase gates FAILED: {json.dumps(net)[:500]}")
        return 0 if ok else 1

    if not native.available():
        stderr_print("native env core not built: run `make -C cpp`")
        return 2

    failures = []
    control_rates, chaos_rates = [], []
    reps = {}
    chaos_evidence = {}
    for rep in range(max(1, args.pair_reps)):
        # alternate which arm goes first: slow host drift (the scheduler,
        # page cache) must hit both arms equally over the session
        order = (0, args.kills) if rep % 2 == 0 else (args.kills, 0)
        for kills in order:
            r = _phase_rate(args, kills, seed=args.seed + rep)
            tag = "chaos" if kills else "control"
            reps[f"{tag}_rep{rep}"] = r
            (chaos_rates if kills else control_rates).append(r["rate"])
            if kills:
                chaos_evidence = r
            stderr_print(
                f"{tag:8s} rep {rep}: {r['rate']:>9.1f} env-steps/s"
                + (f" ({r.get('kills')} kills, {r.get('respawns'):.0f} respawns)" if kills else "")
            )

    med_control = statistics.median(control_rates)
    med_chaos = statistics.median(chaos_rates)
    ratio = med_chaos / max(med_control, 1e-9)
    if ratio < args.gate:
        failures.append(
            f"chaos throughput gate FAILED: median chaos rate {med_chaos:.1f} "
            f"is {100 * (1 - ratio):.1f}% below median control "
            f"{med_control:.1f} (gate: hold >={args.gate:.0%})"
        )
    if chaos_evidence.get("kills", 0) < min(3, args.kills):
        failures.append(
            f"chaos rep killed only {chaos_evidence.get('kills', 0)} servers "
            f"(need >= {min(3, args.kills)} for the acceptance scenario)"
        )
    if chaos_evidence.get("respawns", 0) < chaos_evidence.get("kills", 0):
        failures.append(
            "supervisor respawned fewer servers than chaos killed "
            f"({chaos_evidence.get('respawns')} < {chaos_evidence.get('kills')})"
        )

    autoscale = None
    if not args.skip_autoscale:
        autoscale = _phase_autoscale(args)
        stderr_print(
            f"autoscale: 1 -> {autoscale['reached_live']} servers "
            f"({autoscale['scale_up_events']:.0f} scale-up decisions)"
        )
        if autoscale["reached_live"] < autoscale["fleet_max"]:
            failures.append(
                f"autoscaler never reached fleet_max: live "
                f"{autoscale['reached_live']} < {autoscale['fleet_max']}"
            )

    failover = None
    if not args.skip_failover:
        failover = _phase_failover(args)
        stderr_print(
            f"failover: killed at step {failover['killed_at_step']}, "
            f"resumes {failover['resumes']:.0f}, rc {failover['rc']}, "
            f"final step {failover['final_step']}"
        )
        if not failover["ok"]:
            failures.append(f"learner checkpoint-failover FAILED: {failover}")

    network = None
    if args.net:
        network = _phase_network(args)
        stderr_print(
            f"network: clean {network['clean']['rate']:.1f} vs degraded "
            f"{network['dcn']['rate']:.1f} env-steps/s "
            f"({network['dcn_over_clean']:.3f}x, gate {network['gate']})"
        )
        if not network["gate_passed"]:
            failures.append(
                f"netchaos throughput gate FAILED: degraded pod held only "
                f"{network['dcn_over_clean']:.3f}x clean (gate "
                f">={network['gate']})"
            )
        if not network["partition"]["recovered"]:
            failures.append(
                f"partition-and-heal rep FAILED: {network['partition']}"
            )
        if not network["replay_ok"]:
            failures.append("netchaos seed-replay mismatch (rep not reproducible)")

    # the orchestration flight events ARE the acceptance evidence: dump the
    # ring (postmortem form) and embed the relevant kinds in the artifact
    flight = telemetry.flight_recorder()
    dump_path = flight.dump("chaos bench complete")
    events = [
        {"kind": k, **f}
        for _, k, f in flight.events_since(0)
        if k in _ORCH_KINDS
    ]
    kinds = sorted({e["kind"] for e in events})

    out = {
        "metric": "chaos_plane_env_steps_per_sec_per_host",
        "value": round(med_chaos, 1),
        "unit": "env-steps/sec/host",
        "control_value": round(med_control, 1),
        "chaos_over_control": round(ratio, 4),
        "gate": args.gate,
        "gate_passed": ratio >= args.gate,
        "game": args.game,
        "wire": args.wire,
        "n_servers": args.n_servers,
        "envs_per_proc": args.envs_per_proc,
        "seconds": args.seconds,
        "kills_per_rep": args.kills,
        "pair_reps": args.pair_reps,
        "control_reps": control_rates,
        "chaos_reps": chaos_rates,
        "reps": reps,
        "autoscale": autoscale,
        "failover": failover,
        "network": network,
        "flight_dump": dump_path,
        "flight_event_kinds": kinds,
        "flight_events": events[-200:],
    }
    # evidence prints BEFORE the verdict: per-rep rates and events are most
    # valuable exactly when a gate fails (plane_bench precedent)
    print(json.dumps(out))
    if failures:
        for msg in failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
