"""Measured upper bounds for the claimed-saturated envs (VERDICT r3 #6).

RESULTS.md claims Boxing ~69 is a structural bound, Seaquest saturates
~400, and Qbert's 39k is horizon-capped. Those were impressions from
learning curves; this script converts each into a measured/analytic number
by playing each env with a STATE-AWARE oracle policy (direct access to the
env's NamedTuple state — strictly more information than any pixel policy),
plus closed-form arithmetic where the mechanics make it exact.

Run on CPU with the axon-free PYTHONPATH (safe concurrently with TPU runs —
see the safe-CPU-bypass note in .claude/skills/verify/SKILL.md):
    env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
        python scripts/env_ceilings.py [--episodes 128]

Prints one JSON line per env and writes runs/env_ceilings.json (path
resolved against the repo root, any cwd).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------- boxing --
def boxing_oracle(episodes: int, seed: int = 0) -> dict:
    """Scripted engage/disengage policy with full state. Measured result:
    at FRAME_SKIP=4 the 'flee during cooldown' phase cannot escape punch
    range (knockback 0.05 + 4x0.008 speed edge < 0.10 range), so this
    collapses to the TRADE EQUILIBRIUM — both boxers at their renewal
    rates (mine 1/5 substeps, opponent's 1/8 in-range) — and scores ~5,
    far BELOW the trained agent's 68.6. The honest ceiling is analytic:
    score at KO = 100 - 12.5*E where E = in-range substeps the agent
    exposes per landed punch (opponent's renewal rate is 1/8 per in-range
    substep). E >= 1 structurally => ceiling 87.5 for a substep-level
    controller; the trained 68.6 corresponds to E = 2.51, i.e. the agent
    sits at the 4-substep action-granularity floor. See RESULTS.md."""
    from distributed_ba3c_tpu.envs.jaxenv import boxing as env

    # direction (sign dx, sign dy) -> action index (rows of _MOVES);
    # +8 converts a move action 2..9 into its punch+move variant 10..17
    act_lut = np.zeros((3, 3), np.int32)
    act_lut[0 + 1, -1 + 1] = 2   # up
    act_lut[1 + 1, 0 + 1] = 3    # right
    act_lut[-1 + 1, 0 + 1] = 4   # left
    act_lut[0 + 1, 1 + 1] = 5    # down
    act_lut[1 + 1, -1 + 1] = 6
    act_lut[-1 + 1, -1 + 1] = 7
    act_lut[1 + 1, 1 + 1] = 8
    act_lut[-1 + 1, 1 + 1] = 9
    lut = jnp.asarray(act_lut)

    def policy(st):
        delta = st.opp - st.me
        engage = st.my_cd <= 0
        d = jnp.where(engage, delta, -delta)  # chase vs flee
        sx = jnp.sign(d[0]).astype(jnp.int32)
        sy = jnp.sign(d[1]).astype(jnp.int32)
        move = lut[sx + 1, sy + 1]
        return jnp.where(engage, move + 8, move)  # punch+move when engaging

    def rollout(key):
        st = env.reset(key)

        def body(carry, k):
            st, score, done_seen = carry
            a = policy(st)
            st2, _, r, done = env.step(st, a, k)
            score = score + jnp.where(done_seen, 0.0, r)
            return (st2, score, done_seen | done), None

        keys = jax.random.split(key, env.MAX_T)
        (st, score, _), _ = jax.lax.scan(
            body, (st, jnp.float32(0.0), jnp.bool_(False)), keys
        )
        return score

    keys = jax.random.split(jax.random.PRNGKey(seed), episodes)
    scores = np.asarray(jax.jit(jax.vmap(rollout))(keys))
    return {
        "env": "boxing",
        "oracle": "state-aware engage/disengage (collapses to trade equilibrium at FRAME_SKIP=4)",
        "episodes": episodes,
        "mean": round(float(scores.mean()), 2),
        "p95": round(float(np.percentile(scores, 95)), 2),
        "max": round(float(scores.max()), 2),
        "ceiling_formula": "score_at_KO = 100 - 12.5 * E (E = in-range substeps per landed punch; opp renewal = 1/8 per in-range substep)",
        "ceiling_substep_controller_E1": 87.5,
        "trained_agent_68.6_implies_E": 2.51,
    }


# --------------------------------------------------------------- seaquest --
def seaquest_oracle(episodes: int, seed: int = 0) -> dict:
    """Full-state dip-snipe oracle on the TOP lane only: hover in the band
    between the surface and lane 0 (collision-free by geometry — no fish
    above lane 0), dip into the lane band only to fire at a DISTANT fish,
    rise immediately after the torpedo is away, and dodge upward whenever
    the fish closes. A deliberately conservative strategy — one lane of
    four — yet it measures whether the env's economy supports scores far
    above the trained agent's ~404 plateau; the analytic respawn bound
    (each lane's fish must swim the full width alive between kills) is
    computed alongside. (A naive nearest-lane chaser was tried first and
    died to lane-crossing collisions in ~25 steps, scoring ~27 — kept out;
    this version demonstrates the env rewards oxygen discipline.)"""
    from distributed_ba3c_tpu.envs.jaxenv import seaquest as env

    HOVER_Y = 0.26          # above lane 0 (0.35) minus collision extent
    HOME_X = 0.35
    LANE0 = env.LANE_Y[0]

    def policy(st):
        y = st.sub_xy[1]
        x = st.sub_xy[0]
        # oxygen: from the hover band the surface is ~7 substeps away;
        # leave margin for a dip in progress
        surfacing = (st.oxygen < 60.0) | (
            (y <= env.SURFACE_Y + 0.02) & (st.oxygen < env.OXY_MAX - 1.0)
        )

        fish_x = st.fish_x[0]
        alive = st.fish_alive[0]
        gap = fish_x - x
        facing_ok = jnp.sign(gap) == st.facing
        aligned = jnp.abs(y - LANE0) < 0.035
        in_danger_band = y > HOVER_Y + 0.02

        hunt = alive & ~st.torp_live & (jnp.abs(gap) > 0.30)
        a_home = jnp.where(
            jnp.abs(x - HOME_X) > 0.05,
            jnp.where(x < HOME_X, 5, 4),
            0,
        )
        act = jnp.where(
            surfacing,
            2,
            jnp.where(
                ~hunt,
                # not hunting: retreat to the safe hover band, re-home x
                jnp.where(in_danger_band, 2, a_home),
                jnp.where(
                    ~facing_ok,
                    jnp.where(gap > 0, 5, 4),   # turn toward the fish
                    jnp.where(
                        ~aligned,
                        3,                       # dip into the lane band
                        1,                       # fire
                    ),
                ),
            ),
        )
        return act

    def rollout(key):
        st = env.reset(key)

        def body(carry, k):
            st, score, done_seen = carry
            a = policy(st)
            st2, _, r, done = env.step(st, a, k)
            score = score + jnp.where(done_seen, 0.0, r)
            return (st2, score, done_seen | done), None

        keys = jax.random.split(key, env.MAX_T)
        (st, score, _), _ = jax.lax.scan(
            body, (st, jnp.float32(0.0), jnp.bool_(False)), keys
        )
        return score

    keys = jax.random.split(jax.random.PRNGKey(seed), episodes)
    scores = np.asarray(jax.jit(jax.vmap(rollout))(keys))
    # analytic: per lane, at most one kill per full-width transit
    substeps = env.MAX_T * env.FRAME_SKIP
    transit = 1.10 / env.FISH_SPEED  # spawn edge -0.05 to 1.05
    analytic = env.N_LANES * (substeps / transit) * env.FISH_POINTS
    return {
        "env": "seaquest",
        "oracle": "state-aware lane-sniper with oxygen management",
        "episodes": episodes,
        "mean": round(float(scores.mean()), 2),
        "p95": round(float(np.percentile(scores, 95)), 2),
        "max": round(float(scores.max()), 2),
        "analytic_respawn_bound": round(float(analytic), 1),
    }


# ------------------------------------------------------------------ qbert --
def qbert_oracle(episodes: int, seed: int = 0) -> dict:
    """Snake-path oracle with full state: follow a fixed Hamiltonian-style
    sweep over the pyramid, detouring only when the ball occupies the next
    cube. The analytic ceiling is exact: a board is 21 cubes * 25 + 100
    bonus = 625 points per >=20 hops, MAX_T hops per episode."""
    from distributed_ba3c_tpu.envs.jaxenv import qbert as env

    # Lattice hop distance between cubes: moves are (-1,0) (+1,+1) (+1,0)
    # (-1,-1). Down runs reach dc in [0, dr]; up runs reach dc in [dr, 0];
    # anything outside costs 2 extra hops per unit of excess; same-row
    # lateral moves are down-up pairs (2 hops each).
    cube_r = jnp.asarray([r for r in range(env.ROWS) for _ in range(r + 1)])
    cube_c = jnp.asarray(
        [c for r in range(env.ROWS) for c in range(r + 1)]
    )

    def hop_dist(pr, pc, tr, tc):
        dr = tr - pr
        dc = tc - pc
        # out-of-cone excess (also covers dr==0: excess = |dc|, 2 hops each)
        down_excess = jnp.maximum(dc - jnp.maximum(dr, 0), 0) + jnp.maximum(
            -dc - jnp.maximum(-jnp.minimum(dr, 0), 0), 0
        )
        return jnp.abs(dr) + 2 * down_excess

    def policy(st, key):
        # nearest unflipped cube by hop distance (the agent's own cube can
        # only flip by leaving and returning — exclude it as a target)
        on_own = (cube_r == st.pos[0]) & (cube_c == st.pos[1])
        d = hop_dist(st.pos[0], st.pos[1], cube_r, cube_c)
        d = jnp.where(st.flipped | on_own, 10_000, d)
        tgt = jnp.argmin(d)
        tr, tc = cube_r[tgt], cube_c[tgt]

        # greedy: among the 4 hops, pick the legal one minimizing distance
        # to the target; hopping onto the ball's cube is heavily penalized
        drs = jnp.asarray([-1, 1, 1, -1])
        dcs = jnp.asarray([0, 1, 0, -1])
        nr = st.pos[0] + drs
        nc = st.pos[1] + dcs
        legal = (nr >= 0) & (nr < env.ROWS) & (nc >= 0) & (nc <= nr)
        nd = hop_dist(nr, nc, tr, tc)
        into_ball = st.ball_live & (nr == st.ball[0]) & (nc == st.ball[1])
        score = nd + (~legal) * 10_000 + into_ball * 1_000
        return jnp.argmin(score).astype(jnp.int32) + 1  # actions 1..4

    def rollout(key):
        st = env.reset(key)

        def body(carry, k):
            st, score, done_seen = carry
            a = policy(st, k)
            st2, _, r, done = env.step(st, a, k)
            score = score + jnp.where(done_seen, 0.0, r)
            return (st2, score, done_seen | done), None

        keys = jax.random.split(key, env.MAX_T)
        (st, score, _), _ = jax.lax.scan(
            body, (st, jnp.float32(0.0), jnp.bool_(False)), keys
        )
        return score

    keys = jax.random.split(jax.random.PRNGKey(seed), episodes)
    scores = np.asarray(jax.jit(jax.vmap(rollout))(keys))
    board_pts = env.N_CUBES * env.CUBE_POINTS + env.CLEAR_BONUS
    analytic = env.MAX_T / env.N_CUBES * board_pts  # >= N_CUBES hops/board
    return {
        "env": "qbert",
        "oracle": "state-aware snake sweep with ball dodge",
        "episodes": episodes,
        "mean": round(float(scores.mean()), 2),
        "p95": round(float(np.percentile(scores, 95)), 2),
        "max": round(float(scores.max()), 2),
        "analytic_horizon_bound": round(float(analytic), 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=128)
    ap.add_argument("--out", default="runs/env_ceilings.json")
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    args = ap.parse_args()

    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    _lock = guard_tpu("env_ceilings", mode=args.tpu_lock)  # noqa: F841

    results = []
    for fn in (boxing_oracle, seaquest_oracle, qbert_oracle):
        r = fn(args.episodes)
        results.append(r)
        print(json.dumps(r), flush=True)
    out = args.out
    if not os.path.isabs(out):
        # anchor to the repo root so all the simulated episodes are never
        # lost to a cwd-relative FileNotFoundError at the very end
        out = os.path.join(os.path.dirname(os.path.dirname(__file__)), out)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
