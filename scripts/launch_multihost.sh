#!/usr/bin/env bash
# Multi-host launcher: the reference's cluster launch surface (SURVEY.md §2.8
# #29 — srun/ssh fan-out building --ps_hosts/--worker_hosts lists), minus the
# ps tier (obsolete on TPU; gradients ride ICI/DCN collectives).
#
# Usage:
#   scripts/launch_multihost.sh "host1:9900,host2:9900" [train.py args...]
#
# Runs this host's worker: rank = position of $(hostname) in the list.
# Under Slurm, simply:  srun scripts/launch_multihost.sh "$WORKER_HOSTS" ...
# (every task computes its own rank the same way; SLURM_PROCID overrides).
set -euo pipefail

WORKER_HOSTS="${1:?usage: launch_multihost.sh host1:p,host2:p [args...]}"
shift

if [[ -n "${SLURM_PROCID:-}" ]]; then
  TASK_INDEX="$SLURM_PROCID"
else
  HOSTNAME_SHORT=$(hostname -s)
  TASK_INDEX=$(python3 - "$WORKER_HOSTS" "$HOSTNAME_SHORT" <<'EOF'
import sys
hosts = [h.split(":")[0].split(".")[0] for h in sys.argv[1].split(",")]
print(hosts.index(sys.argv[2]))
EOF
)
fi

echo "[launch] worker_hosts=$WORKER_HOSTS task_index=$TASK_INDEX"
exec python train.py \
  --job_name worker \
  --worker_hosts "$WORKER_HOSTS" \
  --task_index "$TASK_INDEX" \
  "$@"
