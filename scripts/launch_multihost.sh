#!/usr/bin/env bash
# Multi-host launcher: the reference's cluster launch surface (SURVEY.md §2.8
# #29 — srun/ssh fan-out building --ps_hosts/--worker_hosts lists), minus the
# ps tier (obsolete on TPU; gradients ride ICI/DCN collectives).
#
# Usage:
#   scripts/launch_multihost.sh "host1:9900,host2:9900" [train.py args...]
#
# Runs this host's worker: rank = position of $(hostname) in the list.
# Under Slurm, simply:  srun scripts/launch_multihost.sh "$WORKER_HOSTS" ...
# (every task computes its own rank the same way; SLURM_PROCID overrides).
set -euo pipefail

WORKER_HOSTS="${1:?usage: launch_multihost.sh host1:p,host2:p [args...]}"
shift

if [[ -n "${SLURM_PROCID:-}" ]]; then
  TASK_INDEX="$SLURM_PROCID"
else
  HOSTNAME_SHORT=$(hostname -s)
  TASK_INDEX=$(python3 - "$WORKER_HOSTS" "$HOSTNAME_SHORT" <<'EOF'
import sys
hosts = [h.split(":")[0].split(".")[0] for h in sys.argv[1].split(",")]
print(hosts.index(sys.argv[2]))
EOF
)
fi

echo "[launch] worker_hosts=$WORKER_HOSTS task_index=$TASK_INDEX"
# Rank-failure semantics (parallel/watchdog.py): if a peer rank dies, every
# survivor exits 75 within --rank_stall_timeout (default 600s). Exit 75 is
# retry-able: loop a relaunch that RESUMES from the run's shared checkpoint
# dir instead of stranding the allocation (README 'Rank-failure semantics').
LOGDIR=""
CALLER_LOADS=0
prev=""
for a in "$@"; do
  case "$a" in
    --logdir=*) LOGDIR="${a#--logdir=}" ;;
    --load|--load=*) CALLER_LOADS=1 ;;
  esac
  if [[ "$prev" == "--logdir" ]]; then LOGDIR="$a"; fi
  prev="$a"
done
relaunch=0
while :; do
  args=("$@")
  # resume ONLY on relaunch after a lost-lockstep exit: the first launch
  # keeps fresh-start semantics even over a reused logdir (a silent
  # auto-resume there could "complete" a finished run with zero training).
  # On relaunch the run's OWN checkpoints take precedence over a
  # caller-supplied --load: the caller's path is a warm-START source, and
  # reusing it verbatim would discard every checkpoint saved since launch
  # (recurring rank failures would replay the same training span forever).
  if [[ $relaunch -eq 1 ]]; then
    # a FINALIZED saved checkpoint, not just the dir or a ckpt-* entry:
    # CheckpointManager creates $LOGDIR/checkpoints at startup, and a rank
    # killed mid-save leaves orbax temp dirs / finalized dirs whose
    # checkpoint.json "latest" was never written — resuming from any of
    # those crashes with exit 1 and permanently kills the retry loop (and
    # discards a caller warm start). The meta's non-null "latest" is the
    # only resumable signal (written strictly after wait_until_finished).
    have_run_ckpt=0
    if [[ -n "$LOGDIR" && -f "$LOGDIR/checkpoints/checkpoint.json" ]]; then
      if python3 - "$LOGDIR/checkpoints/checkpoint.json" <<'EOF'
import json, sys
meta = json.load(open(sys.argv[1]))
sys.exit(0 if meta.get("latest") is not None else 1)
EOF
      then
        have_run_ckpt=1
      fi
    fi
    if [[ $have_run_ckpt -eq 1 ]]; then
      if [[ $CALLER_LOADS -eq 1 ]]; then
        echo "[launch] resume: replacing caller --load with the run's own" \
          "$LOGDIR/checkpoints (progress since launch lives there)" >&2
        stripped=()
        skip_next=0
        for a in "${args[@]}"; do
          if [[ $skip_next -eq 1 ]]; then skip_next=0; continue; fi
          case "$a" in
            --load) skip_next=1; continue ;;
            --load=*) continue ;;
          esac
          stripped+=("$a")
        done
        args=("${stripped[@]}")
      fi
      args+=(--load "$LOGDIR/checkpoints")
    elif [[ $CALLER_LOADS -eq 1 ]]; then
      echo "[launch] exit 75, no run-local checkpoint saved yet — retrying" \
        "with the caller's --load (warm start)" >&2
    else
      echo "[launch] exit 75 but no saved checkpoint to resume from" \
        "(logdir='$LOGDIR') — relaunching fresh" >&2
    fi
  fi
  set +e
  python train.py \
    --job_name worker \
    --worker_hosts "$WORKER_HOSTS" \
    --task_index "$TASK_INDEX" \
    "${args[@]}"
  rc=$?
  set -e
  if [[ $rc -ne 75 ]]; then
    exit $rc
  fi
  relaunch=1
  echo "[launch] rank lost lockstep (exit 75) — relaunching with resume" >&2
done
