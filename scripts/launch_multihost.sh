#!/usr/bin/env bash
# DEPRECATED SHIM — the multi-host launch loop lives in the Python
# orchestrator now (orchestrate/multihost.py: rank derivation, the exit-75
# relaunch loop, and the finalized-checkpoint resume gate shared with
# `python -m distributed_ba3c_tpu.orchestrate` learner failover — counted
# and flight-recorded there). This script only warns and delegates so
# existing srun/ssh fan-out lines keep working:
#
#   scripts/launch_multihost.sh "host1:9900,host2:9900" [train.py args...]
#     ==  python -m distributed_ba3c_tpu.orchestrate \
#             --multihost "host1:9900,host2:9900" -- [train.py args...]
#
# Under Slurm: srun scripts/launch_multihost.sh "$WORKER_HOSTS" ...
# (SLURM_PROCID still overrides the hostname->rank lookup, as before).
set -euo pipefail

WORKER_HOSTS="${1:?usage: launch_multihost.sh host1:p,host2:p [args...]}"
shift

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"

echo "[launch] launch_multihost.sh is a deprecated shim — use" \
  "'python -m distributed_ba3c_tpu.orchestrate --multihost ...' directly" >&2

exec python3 -m distributed_ba3c_tpu.orchestrate \
  --multihost "$WORKER_HOSTS" -- "$@"
