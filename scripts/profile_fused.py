"""Fused-step cost breakdown on the real chip (VERDICT r1 weak #2).

Times isolated pieces of the fused step at several (n_envs, rollout_len,
chunk) shapes so the optimization is profile-driven, not asserted:

  rollout   — scan of [fwd + sample + env.step + stack update]  (actor side)
  learner   — grad accumulation over the collected trajectory    (learner side)
  full      — the shipped fused step
  env_only  — scan of env.step alone (no net) to price the env+render

Usage: python scripts/profile_fused.py [--trace DIR]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.jaxenv import pong
from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh


def timeit(fn, *args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_full_only(n_envs: int, rollout_len: int, chunk: int):
    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    step = make_fused_step(
        model, opt, cfg, mesh, pong, rollout_len=rollout_len,
        grad_chunk_samples=chunk,
    )
    state = step.put(
        create_fused_state(
            jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs, n_shards=1
        )
    )
    try:
        s, m = step(state, cfg.entropy_beta)
        float(m["loss"])
        t0 = time.perf_counter()
        iters = 10
        for _ in range(iters):
            s, m = step(s, cfg.entropy_beta)
        float(m["loss"])
        t_full = (time.perf_counter() - t0) / iters
        sps = n_envs * rollout_len / t_full
        print(
            f"n_envs={n_envs:5d} T={rollout_len:3d} chunk={chunk:6d} | "
            f"full {t_full*1e3:7.2f}ms ({sps:9.0f} sps)",
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        print(
            f"n_envs={n_envs:5d} T={rollout_len:3d} chunk={chunk:6d} | "
            f"FAILED {type(e).__name__}",
            flush=True,
        )


def bench_shape(n_envs: int, rollout_len: int):
    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    mesh = make_mesh()
    step = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=rollout_len)
    state = create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs, n_shards=1
    )
    state = step.put(state)

    # -- full step (carries state: the step donates its input) -------------
    s, m = step(state, cfg.entropy_beta)
    float(m["loss"])
    t0 = time.perf_counter()
    iters = 10
    for _ in range(iters):
        s, m = step(s, cfg.entropy_beta)
    float(m["loss"])
    t_full = (time.perf_counter() - t0) / iters
    state = step.put(
        create_fused_state(
            jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs, n_shards=1
        )
    )

    # -- env only ----------------------------------------------------------
    @jax.jit
    def env_only(env_state, key):
        def body(carry, _):
            es, k = carry
            k, ka, ke = jax.random.split(k, 3)
            actions = jax.random.randint(ka, (n_envs,), 0, pong.num_actions)
            es, obs, r, d = jax.vmap(pong.step)(
                es, actions, jax.random.split(ke, n_envs)
            )
            return (es, k), obs.sum()
        (es, _), sums = jax.lax.scan(body, (env_state, key), None, length=rollout_len)
        return sums.sum()

    t_env = timeit(env_only, state.env_state, jax.random.PRNGKey(1))

    # -- rollout only (fwd + sample + env) ---------------------------------
    params = state.train.params

    @jax.jit
    def rollout_only(params, env_state, stack, key):
        def body(carry, _):
            es, st, k = carry
            out = model.apply({"params": params}, st)
            k, ka, ke = jax.random.split(k, 3)
            a = jax.random.categorical(ka, out.logits, -1).astype(jnp.int32)
            es, obs, r, d = jax.vmap(pong.step)(es, a, jax.random.split(ke, n_envs))
            st = jnp.concatenate([st[..., 1:], obs[..., None]], axis=-1)
            return (es, st, k), (st, a, r, d)
        (es, st, k), traj = jax.lax.scan(
            body, (env_state, stack, key), None, length=rollout_len
        )
        return traj[0].sum()

    t_roll = timeit(
        rollout_only, params, state.env_state, state.obs_stack,
        jax.random.PRNGKey(2),
    )

    # -- learner only on a fixed trajectory --------------------------------
    from distributed_ba3c_tpu.ops.loss import a3c_loss

    states_t = jnp.zeros((rollout_len, n_envs, 84, 84, cfg.frame_history), jnp.uint8)
    actions_t = jnp.zeros((rollout_len, n_envs), jnp.int32)
    returns_t = jnp.zeros((rollout_len, n_envs), jnp.float32)

    @jax.jit
    def learner_only(params, states_t, actions_t, returns_t):
        def chunk_grad(p, chunk):
            sc, ac, rc = chunk
            def loss_fn(pp):
                out = model.apply({"params": pp}, sc)
                l = a3c_loss(out.logits, out.value, ac, rc,
                             entropy_beta=cfg.entropy_beta,
                             value_loss_coef=cfg.value_loss_coef)
                return l.total, l
            return jax.value_and_grad(loss_fn, has_aux=True)(p)

        def acc_body(g_acc, chunk):
            (_, _), g = chunk_grad(params, chunk)
            return jax.tree_util.tree_map(jnp.add, g_acc, g), None

        g0 = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        g, _ = jax.lax.scan(acc_body, g0, (states_t, actions_t, returns_t))
        return jax.tree_util.tree_leaves(g)[0].sum()

    t_learn = timeit(learner_only, params, states_t, actions_t, returns_t)

    # -- learner, single flat [T*B] fwd+bwd (memory permitting) ------------
    flat_states = states_t.reshape(-1, 84, 84, cfg.frame_history)
    flat_actions = actions_t.reshape(-1)
    flat_returns = returns_t.reshape(-1)

    @jax.jit
    def learner_flat(params, s, a, r):
        def loss_fn(pp):
            out = model.apply({"params": pp}, s)
            l = a3c_loss(out.logits, out.value, a, r,
                         entropy_beta=cfg.entropy_beta,
                         value_loss_coef=cfg.value_loss_coef)
            return l.total
        return jax.grad(loss_fn)(params)["Dense_0"]["kernel"].sum()

    try:
        t_flat = timeit(learner_flat, params, flat_states, flat_actions, flat_returns)
    except Exception as e:  # noqa: BLE001
        t_flat = float("nan")
        print(f"  flat learner failed: {type(e).__name__}")

    steps = n_envs * rollout_len
    print(
        f"n_envs={n_envs:5d} T={rollout_len:3d} | "
        f"full {t_full*1e3:7.2f}ms ({steps/t_full:9.0f} sps) | "
        f"rollout {t_roll*1e3:7.2f}ms | env {t_env*1e3:6.2f}ms | "
        f"learner {t_learn*1e3:7.2f}ms | flat {t_flat*1e3:7.2f}ms",
        flush=True,
    )


def bench_attribution(n_envs: int, rollout_len: int, inner: int = 50):
    """Close the full-vs-parts gap (VERDICT r2 #3): price the returns scan,
    the Adam+clip update, and the episode bookkeeping individually, so
    full - (rollout + learner + returns + adam + bookkeeping) is a measured
    residual, not a guess. Each component repeats ``inner`` times INSIDE one
    jitted lax.scan with threaded carries — per-dispatch tunnel latency
    (~10ms/call on the dev link, larger than the components themselves)
    divides out, and the chain is unfoldable so XLA cannot elide it."""
    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    state = create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs, n_shards=1
    )
    params = state.train.params
    T, B = rollout_len, n_envs
    steps = T * B

    def time_scanned(jitted, carry, outer=5):
        out = jitted(carry)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(outer):
            out = jitted(out)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / (outer * inner)

    # -- n-step discounted returns scan on [T, B] --------------------------
    from distributed_ba3c_tpu.ops.returns import n_step_returns

    @jax.jit
    def returns_rep(carry):
        def body(c, _):
            rew, done, boot = c
            ret = n_step_returns(rew, done, boot, cfg.gamma)
            # thread outputs back into inputs: unfoldable chain
            return (rew + 1e-9 * ret, done, boot + 1e-9 * ret[-1]), None
        out, _ = jax.lax.scan(body, carry, None, length=inner)
        return out

    t_ret = time_scanned(
        returns_rep,
        (
            jnp.zeros((T, B), jnp.float32),
            jnp.zeros((T, B), jnp.bool_),
            jnp.zeros((B,), jnp.float32),
        ),
    )

    # -- Adam + global-norm clip update alone ------------------------------
    import optax

    opt_state = opt.init(params)
    grads = jax.tree_util.tree_map(lambda p: jnp.ones_like(p) * 1e-9, params)

    @jax.jit
    def adam_rep(carry):
        def body(c, _):
            p, os_ = c
            # derive grads from the CARRY so the global-norm reduction and
            # clip scaling are iteration-dependent — loop-invariant grads
            # would let XLA hoist the clip out of the scan
            g = jax.tree_util.tree_map(lambda gl, pl: gl + 1e-12 * pl, grads, p)
            updates, os_ = opt.update(g, os_, p)
            return (optax.apply_updates(p, updates), os_), None
        out, _ = jax.lax.scan(body, carry, None, length=inner)
        return out

    t_adam = time_scanned(adam_rep, (params, opt_state))

    # -- episode bookkeeping (the where/accumulate plane on [T, B]) --------
    @jax.jit
    def book_rep(carry):
        def rep(c, _):
            ep_ret, ep_count, ep_sum, rew, done = c
            def body(cc, td):
                er, cnt, s = cc
                r, d = td
                er = er + r
                cnt = cnt + d.astype(jnp.int32)
                s = s + jnp.where(d, er, 0.0)
                er = jnp.where(d, 0.0, er)
                return (er, cnt, s), None
            (ep_ret, ep_count, ep_sum), _ = jax.lax.scan(
                body, (ep_ret, ep_count, ep_sum), (rew, done)
            )
            return (ep_ret, ep_count, ep_sum, rew + 1e-9 * ep_ret, done), None
        out, _ = jax.lax.scan(rep, carry, None, length=inner)
        return out

    t_book = time_scanned(
        book_rep,
        (
            jnp.zeros(B, jnp.float32),
            jnp.zeros(B, jnp.int32),
            jnp.zeros(B, jnp.float32),
            jnp.zeros((T, B), jnp.float32),
            jnp.zeros((T, B), jnp.bool_),
        ),
    )

    print(
        f"attribution @ {n_envs}x{rollout_len} ({steps} samples/step):\n"
        f"  returns scan  {t_ret*1e6:9.1f} us  ({t_ret/steps*1e9:6.2f} ns/sample)\n"
        f"  adam+clip     {t_adam*1e6:9.1f} us  ({t_adam/steps*1e9:6.2f} ns/sample)\n"
        f"  bookkeeping   {t_book*1e6:9.1f} us  ({t_book/steps*1e9:6.2f} ns/sample)",
        flush=True,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None)
    ap.add_argument("--shapes", default="1024x20")
    ap.add_argument(
        "--attribute", action="store_true",
        help="price returns/adam/bookkeeping to close the full-vs-parts gap",
    )
    ap.add_argument(
        "--full-chunks",
        default=None,
        help="comma list of grad_chunk_samples: time the FULL step only",
    )
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    args = ap.parse_args()

    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    _lock = guard_tpu("profile_fused", mode=args.tpu_lock)  # noqa: F841

    print("devices:", jax.devices(), flush=True)
    shapes = [tuple(map(int, s.split("x"))) for s in args.shapes.split(",")]
    if args.attribute:
        for n, t in shapes:
            bench_attribution(n, t)
        return
    if args.full_chunks:
        for n, t in shapes:
            for c in map(int, args.full_chunks.split(",")):
                bench_full_only(n, t, c)
        return
    if args.trace:
        with jax.profiler.trace(args.trace):
            for n, t in shapes:
                bench_shape(n, t)
    else:
        for n, t in shapes:
            bench_shape(n, t)


if __name__ == "__main__":
    main()
