#!/usr/bin/env bash
# Short fused-trainer learning-curve runs for the remaining env families
# (BASELINE configs #3/#5 evidence): CoinRun, Seaquest, Q*bert. Each run is
# ~10 epochs under the stall watchdog; curves land in runs/<game>/stat.json.
set -u
HERE=$(cd "$(dirname "$0")/.." && pwd)
EPOCHS=${EPOCHS:-10}
for game in coinrun seaquest qbert; do
  echo "=== $game ===" >&2
  bash "$HERE/scripts/run_with_resume.sh" "$HERE/runs/$game" 2 240 -- \
    --trainer tpu_fused_ba3c --env "jax:$game" \
    --batch_size 20480 --rollout_len 20 --steps_per_epoch 100 \
    --max_epoch "$EPOCHS" --nr_eval 32 --eval_every 2 --eval_max_steps 3000 \
    --entropy_beta 0.01 --learning_rate 6e-4 \
    --logdir "$HERE/runs/$game"
done
