#!/usr/bin/env python
"""Latency-vs-throughput frontier for the SLO-aware serving plane.

Drives the real ``BatchedPredictor`` scheduler — continuous batching,
deadline admission, load shedding (docs/serving.md) — with OPEN-LOOP
Poisson arrivals at a sweep of offered rates, and publishes per-rate
p50/p90/p99 serve latency, shed rate and batch occupancy: the frontier the
way ``plane_bench_r6/r7`` publish throughput.

Open-loop matters: a closed-loop driver slows down with the server and
hides the overload region entirely; here arrivals keep coming at the
offered rate no matter what, so past saturation the plane must SHED (fast
typed rejects) while the p99 of what it does serve stays under the SLO —
that is the acceptance shape, load shedding rather than latency collapse.

Device-free by default: the device is the plane-bench null predictor with
a SIMULATED per-call service time (``--service_us``, slept at fetch like a
real serialized device queue), so the frontier's service-time axis is real
while no accelerator (and no tunnel RTT) is in the loop —
``device_free_proxy: true`` in the JSON, same convention as BENCH_r06.

Prints ONE JSON line on stdout (the repo's bench-tooling contract), with
the per-rate evidence BEFORE any gate verdict; diagnostics go to stderr.

Usage:
  python scripts/serving_bench.py                       # default sweep + gate
  python scripts/serving_bench.py --rates 1000,4000 --seconds 2   # CI smoke
  python scripts/plane_bench.py --serving               # embedded in the
                                                        # plane instrument
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from types import SimpleNamespace

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _percentiles_ms(lats):
    import numpy as np

    if not lats:
        return None, None, None
    arr = np.asarray(lats) * 1000.0
    return (
        round(float(np.percentile(arr, 50)), 3),
        round(float(np.percentile(arr, 90)), 3),
        round(float(np.percentile(arr, 99)), 3),
    )


def run_point(rate_rows_per_s: float, opts) -> dict:
    """One open-loop rate point: fresh predictor, Poisson arrivals of
    ``block_rows``-row block tasks for ``seconds``, drained to completion."""
    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from bench import make_null_predictor

    telemetry.reset_all()
    # a stub model is enough: the null predictor never traces the forward,
    # and the scheduler only reads num_actions for the fallback contract
    model = SimpleNamespace(num_actions=opts.num_actions, apply=None)
    pred = make_null_predictor(
        model, {}, opts.num_actions,
        service_s=opts.service_us / 1e6,
        batch_size=opts.batch_size,
        coalesce_ms=0.0,
        slo_ms=opts.slo_ms,
        queue_depth=opts.queue_depth,
    )
    pred.start()
    lats: list = []    # served: admit -> callback, seconds
    sheds: list = []   # ShedReject.reason per shed task
    state = np.zeros((opts.block_rows, 1), np.uint8)  # content is irrelevant
    rng = np.random.default_rng(opts.seed)
    n_tasks = max(1, int(opts.seconds * rate_rows_per_s / opts.block_rows))
    mean_gap = opts.block_rows / rate_rows_per_s
    gaps = rng.exponential(mean_gap, n_tasks)
    clock = time.monotonic
    try:
        t_start = clock()
        next_t = t_start
        for i in range(n_tasks):
            next_t += gaps[i]
            now = clock()
            if next_t > now:
                time.sleep(next_t - now)
            t0 = clock()

            def cb(a, v, lp, t0=t0):
                lats.append(clock() - t0)

            def shed_cb(rej):
                sheds.append(rej.reason)

            pred.put_block_task(state, cb, shed_callback=shed_cb)
        submit_elapsed = clock() - t_start
        # drain: every deadline'd task resolves (served, or shed at pop)
        deadline = clock() + opts.slo_ms / 1000.0 * 4 + 10.0
        while len(lats) + len(sheds) < n_tasks and clock() < deadline:
            time.sleep(0.01)
        # served throughput is measured over the WHOLE service window
        # (submission + drain): dividing drain-phase completions by the
        # submission window alone would overstate capacity exactly at the
        # knee, where the backlog drains after arrivals stop
        total_elapsed = clock() - t_start
    finally:
        pred.stop()
        pred.join(timeout=5)
    scal = telemetry.registry("predictor").scalars()
    batches = scal.get("batches_total", 0)
    rows = scal.get("rows_total", 0)
    p50, p90, p99 = _percentiles_ms(lats)
    served = len(lats)
    shed = len(sheds)
    return {
        "offered_rows_per_s": round(
            n_tasks * opts.block_rows / max(submit_elapsed, 1e-9), 1
        ),
        "target_rows_per_s": rate_rows_per_s,
        "submitted_tasks": n_tasks,
        "served_tasks": served,
        "shed_tasks": shed,
        "unresolved_tasks": n_tasks - served - shed,
        "shed_rate": round(shed / n_tasks, 4),
        "sheds_by_reason": {
            r: sheds.count(r) for r in sorted(set(sheds))
        },
        "p50_ms": p50,
        "p90_ms": p90,
        "p99_ms": p99,
        "served_rows_per_s": round(
            served * opts.block_rows / max(total_elapsed, 1e-9), 1
        ),
        "mean_batch_rows": round(rows / batches, 2) if batches else None,
        "deadline_misses": scal.get("deadline_misses_total", 0),
    }


def run_frontier(opts) -> tuple:
    """The full sweep + gate. Returns (json_row, gate_failure_messages)."""
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    points = []
    for rate in opts.rates:
        p = run_point(rate, opts)
        points.append(p)
        stderr_print(
            f"serving {rate:>8.0f} rows/s offered: "
            f"p99={p['p99_ms']} ms shed={p['shed_rate']:.1%} "
            f"occupancy={p['mean_batch_rows']}"
        )

    slo = opts.slo_ms
    failures = []
    ok = [
        p for p in points
        if p["shed_rate"] < 0.01 and p["p99_ms"] is not None
        and p["p99_ms"] <= slo
    ]
    best = max(ok, key=lambda p: p["offered_rows_per_s"]) if ok else None
    if best is None:
        failures.append(
            f"serving gate FAILED: no rate point met the SLO "
            f"(p99 <= {slo} ms with shed < 1%)"
        )
        overload = None
    else:
        over = [
            p for p in points
            if p["offered_rows_per_s"] >= 2 * best["offered_rows_per_s"]
        ]
        overload = max(over, key=lambda p: p["offered_rows_per_s"]) \
            if over else None
        if overload is None:
            failures.append(
                "serving gate FAILED: sweep never reached 2x the best "
                f"SLO-meeting rate ({best['offered_rows_per_s']} rows/s) — "
                "extend --rates to cover overload"
            )
        else:
            if not overload["shed_rate"] > best["shed_rate"]:
                failures.append(
                    "serving gate FAILED: 2x overload did not raise the "
                    f"shed rate ({overload['shed_rate']} vs "
                    f"{best['shed_rate']} at the SLO point)"
                )
            if overload["p99_ms"] is not None and overload["p99_ms"] > slo:
                failures.append(
                    "serving gate FAILED: served-task p99 "
                    f"{overload['p99_ms']} ms exceeded the {slo} ms SLO "
                    "under overload — latency collapse, not load shedding"
                )
    out = {
        "metric": "serving_frontier_rows_per_s_vs_latency",
        "unit": "rows/sec vs ms",
        "slo_ms": slo,
        "block_rows": opts.block_rows,
        "batch_size": opts.batch_size,
        "service_us": opts.service_us,
        "queue_depth": opts.queue_depth,
        "seconds": opts.seconds,
        "seed": opts.seed,
        # same convention as BENCH_r06: no accelerator in the loop; the
        # service-time axis is simulated at the null device's fetch
        "device_free_proxy": True,
        "rate_points": points,
        "gate": {
            "criterion": (
                f"exists rate point with p99 <= {slo} ms and shed < 1%; at "
                ">= 2x that rate, shed rises while served p99 stays <= SLO"
            ),
            "best_slo_point_rows_per_s": (
                best["offered_rows_per_s"] if best else None
            ),
            "overload_point_rows_per_s": (
                overload["offered_rows_per_s"] if overload else None
            ),
            "passed": not failures,
        },
    }
    return out, failures


def parse_opts(argv=None) -> SimpleNamespace:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--rates", default="1000,2000,4000,8000,16000",
        help="comma list of offered rates in ROWS/s (each request is a "
        "--block_rows block). The default tops out at ~2x the default "
        "service capacity so the sweep covers both sides of the knee",
    )
    ap.add_argument(
        "--block_rows", type=int, default=8,
        help="rows per request (the block wire's natural request unit)",
    )
    ap.add_argument(
        "--batch_size", type=int, default=32,
        help="predictor coalesce target; the bucket cap is the next pow-2 "
        "(capacity = cap rows per --service_us device call)",
    )
    ap.add_argument(
        "--service_us", type=float, default=4000.0,
        help="simulated device time per call (slept at fetch) — the "
        "frontier's service-time axis on a device-free host",
    )
    ap.add_argument("--slo_ms", type=float, default=50.0)
    ap.add_argument(
        "--queue_depth", type=int, default=64,
        help="admission-queue bound in TASKS (overload beyond it is fast "
        "queue_full rejection)",
    )
    ap.add_argument("--seconds", type=float, default=4.0, help="per rate point")
    ap.add_argument("--num_actions", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if not rates:
        raise SystemExit("--rates must name at least one rate")
    return SimpleNamespace(rates=rates, **{
        k: getattr(args, k)
        for k in ("block_rows", "batch_size", "service_us", "slo_ms",
                  "queue_depth", "seconds", "num_actions", "seed")
    })


def main(argv=None) -> int:
    # no accelerator in the loop, ever: pin cpu BEFORE jax imports and
    # never take the TPU-claim mutex (same stance as plane_bench
    # device-free mode)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    opts = parse_opts(argv)
    out, failures = run_frontier(opts)
    # the JSON (per-point evidence) prints BEFORE any gate verdict — the
    # evidence is most valuable exactly when the gate fails
    print(json.dumps(out))
    if failures:
        from distributed_ba3c_tpu.utils.devicelock import stderr_print

        for msg in failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
