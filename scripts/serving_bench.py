#!/usr/bin/env python
"""Latency-vs-throughput frontier for the SLO-aware serving plane.

Drives the real ``BatchedPredictor`` scheduler — continuous batching,
deadline admission, load shedding (docs/serving.md) — with OPEN-LOOP
Poisson arrivals at a sweep of offered rates, and publishes per-rate
p50/p90/p99 serve latency, shed rate and batch occupancy: the frontier the
way ``plane_bench_r6/r7`` publish throughput.

Open-loop matters: a closed-loop driver slows down with the server and
hides the overload region entirely; here arrivals keep coming at the
offered rate no matter what, so past saturation the plane must SHED (fast
typed rejects) while the p99 of what it does serve stays under the SLO —
that is the acceptance shape, load shedding rather than latency collapse.

Device-free by default: the device is the plane-bench null predictor with
a SIMULATED per-call service time (``--service_us``, slept at fetch like a
real serialized device queue), so the frontier's service-time axis is real
while no accelerator (and no tunnel RTT) is in the loop —
``device_free_proxy: true`` in the JSON, same convention as BENCH_r06.

Prints ONE JSON line on stdout (the repo's bench-tooling contract), with
the per-rate evidence BEFORE any gate verdict; diagnostics go to stderr.

``--dtype f32,bf16,int8`` sweeps the rollout-precision LADDER: one
frontier per dtype with the null device's service time scaled by the
MXU-throughput model (bf16 2x f32, int8 2x bf16 — the relative-rate
claim the audit entries' byte censuses back), per-dtype param-table
bytes measured on the REAL quantized tables (quantize/), the int8 spec
calibrated from real jax-Pong rollouts (its hash stamped in every int8
row), a Pong parity section holding the int8 forward inside the bf16
bands, and the rows/s-per-replica gate (int8 >= 1.05x bf16 at equal p99
inside the SLO). Every JSON row carries ``rollout_dtype``.

Usage:
  python scripts/serving_bench.py                       # default sweep + gate
  python scripts/serving_bench.py --rates 1000,4000 --seconds 2   # CI smoke
  python scripts/serving_bench.py --dtype f32,bf16,int8 # the quant frontier
  python scripts/plane_bench.py --serving               # embedded in the
                                                        # plane instrument
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from types import SimpleNamespace

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


#: the MXU-throughput model the --dtype sweep scales the null device's
#: service time by: bf16 doubles f32's matmul rate, int8 doubles bf16's
#: (the relative-rate shape the audit entries' byte censuses back); the
#: absolute numbers stay a device-free proxy — on-chip re-capture is the
#: ROADMAP item, the RATIO at equal p99 is what this instrument pins
_DTYPE_SERVICE_FACTOR = {"float32": 1.0, "bfloat16": 0.5, "int8": 0.25}

_DTYPE_ALIASES = {
    "f32": "float32", "float32": "float32",
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "int8": "int8",
}


def _percentiles_ms(lats):
    import numpy as np

    if not lats:
        return None, None, None
    arr = np.asarray(lats) * 1000.0
    return (
        round(float(np.percentile(arr, 50)), 3),
        round(float(np.percentile(arr, 90)), 3),
        round(float(np.percentile(arr, 99)), 3),
    )


def _make_replica(opts, tele_role: str):
    """One null-device replica (a complete BatchedPredictor serving plane
    with simulated service time — bench.make_null_predictor) under its
    own telemetry role, started."""
    from bench import make_null_predictor

    # a stub model is enough: the null predictor never traces the forward,
    # and the scheduler only reads num_actions for the fallback contract
    model = SimpleNamespace(num_actions=opts.num_actions, apply=None)
    pred = make_null_predictor(
        model, {}, opts.num_actions,
        service_s=opts.service_us / 1e6,
        batch_size=opts.batch_size,
        coalesce_ms=0.0,
        slo_ms=opts.slo_ms,
        queue_depth=opts.queue_depth,
        tele_role=tele_role,
    )
    pred.start()
    return pred


def _make_plane(opts, replicas: int):
    """Build the measurand: a single predictor (``replicas == 1``, the
    PR-9 plane, byte-identical behavior) or R replicas behind the REAL
    ServingRouter. Returns ``(target, roles, teardown)`` where ``roles``
    are the telemetry registries the point's evidence reads."""
    from distributed_ba3c_tpu import telemetry

    telemetry.reset_all()
    if replicas == 1:
        pred = _make_replica(opts, "predictor")
        return pred, ["predictor"], lambda: (pred.stop(), pred.join(5))

    from distributed_ba3c_tpu.predict.router import (
        ServingRouter,
        replica_role,
    )

    router = ServingRouter(health_interval_s=0.1)
    preds = []
    roles = []
    for i in range(replicas):
        role = replica_role("predictor", i)
        pred = _make_replica(opts, role)
        router.add_replica(f"r{i}", pred)
        preds.append(pred)
        roles.append(role)
    router.start()

    def teardown():
        router.stop()
        router.join(timeout=5)
        for p in preds:
            p.stop()
            p.join(timeout=5)

    target = SimpleNamespace(
        put_block_task=router.put_block_task, router=router, preds=preds
    )
    return target, roles, teardown


def _replica_sub_rows(roles) -> list:
    """Per-replica occupancy/shed/p99 evidence rows — a dead replica must
    not hide behind a healthy aggregate (ISSUE 15 house style)."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.predict.router import signals_from_snapshot

    rows = []
    for role in roles:
        snap = telemetry.registry(role).collect()
        s = signals_from_snapshot(snap)
        batches = float(snap.get("batches_total", {}).get("value", 0.0))
        served_rows = s["rows_total"]
        rows.append({
            "role": role,
            "rows": served_rows,
            "batches": batches,
            "mean_batch_rows": (
                round(served_rows / batches, 2) if batches else None
            ),
            "sheds": s["sheds_total"],
            "serve_p99_ms": s["serve_p99_ms"],
            "deadline_misses": float(
                snap.get("deadline_misses_total", {}).get("value", 0.0)
            ),
        })
    return rows


def _drive_point(target, rate_rows_per_s: float, opts) -> tuple:
    """The open-loop Poisson submit/drain loop against ``target`` (a
    predictor or the routed facade). Returns (lats, sheds, submit_elapsed,
    total_elapsed, n_tasks)."""
    import numpy as np

    lats: list = []    # served: admit -> callback, seconds
    sheds: list = []   # ShedReject.reason per shed task
    state = np.zeros((opts.block_rows, 1), np.uint8)  # content is irrelevant
    rng = np.random.default_rng(opts.seed)
    n_tasks = max(1, int(opts.seconds * rate_rows_per_s / opts.block_rows))
    mean_gap = opts.block_rows / rate_rows_per_s
    gaps = rng.exponential(mean_gap, n_tasks)
    clock = time.monotonic
    t_start = clock()
    next_t = t_start
    for i in range(n_tasks):
        next_t += gaps[i]
        now = clock()
        if next_t > now:
            time.sleep(next_t - now)
        t0 = clock()

        def cb(a, v, lp, t0=t0):
            lats.append(clock() - t0)

        def shed_cb(rej):
            sheds.append(rej.reason)

        target.put_block_task(state, cb, shed_callback=shed_cb)
    submit_elapsed = clock() - t_start
    # drain: every deadline'd task resolves (served, or shed at pop)
    deadline = clock() + opts.slo_ms / 1000.0 * 4 + 10.0
    while len(lats) + len(sheds) < n_tasks and clock() < deadline:
        time.sleep(0.01)
    # served throughput is measured over the WHOLE service window
    # (submission + drain): dividing drain-phase completions by the
    # submission window alone would overstate capacity exactly at the
    # knee, where the backlog drains after arrivals stop
    total_elapsed = clock() - t_start
    return lats, sheds, submit_elapsed, total_elapsed, n_tasks


def run_point(rate_rows_per_s: float, opts, replicas: int = 1) -> dict:
    """One open-loop rate point: fresh plane, Poisson arrivals of
    ``block_rows``-row block tasks for ``seconds``, drained to
    completion. ``replicas > 1`` drives the routed plane and embeds
    per-replica sub-rows."""
    from distributed_ba3c_tpu import telemetry

    target, roles, teardown = _make_plane(opts, replicas)
    try:
        lats, sheds, submit_elapsed, total_elapsed, n_tasks = _drive_point(
            target, rate_rows_per_s, opts
        )
    finally:
        teardown()
    batches = rows = misses = 0.0
    for role in roles:
        scal = telemetry.registry(role).scalars()
        batches += scal.get("batches_total", 0)
        rows += scal.get("rows_total", 0)
        misses += scal.get("deadline_misses_total", 0)
    p50, p90, p99 = _percentiles_ms(lats)
    served = len(lats)
    shed = len(sheds)
    point = {
        "offered_rows_per_s": round(
            n_tasks * opts.block_rows / max(submit_elapsed, 1e-9), 1
        ),
        "target_rows_per_s": rate_rows_per_s,
        "submitted_tasks": n_tasks,
        "served_tasks": served,
        "shed_tasks": shed,
        "unresolved_tasks": n_tasks - served - shed,
        "shed_rate": round(shed / n_tasks, 4),
        "sheds_by_reason": {
            r: sheds.count(r) for r in sorted(set(sheds))
        },
        "p50_ms": p50,
        "p90_ms": p90,
        "p99_ms": p99,
        "served_rows_per_s": round(
            served * opts.block_rows / max(total_elapsed, 1e-9), 1
        ),
        "mean_batch_rows": round(rows / batches, 2) if batches else None,
        "deadline_misses": misses,
    }
    if replicas > 1:
        point["replica_rows"] = _replica_sub_rows(roles)
    return point


def run_frontier(opts, replicas: int = 1, rates=None) -> tuple:
    """The full sweep + gate. Returns (json_row, gate_failure_messages)."""
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    points = []
    for rate in (opts.rates if rates is None else rates):
        p = run_point(rate, opts, replicas=replicas)
        points.append(p)
        stderr_print(
            f"serving x{replicas} {rate:>8.0f} rows/s offered: "
            f"p99={p['p99_ms']} ms shed={p['shed_rate']:.1%} "
            f"occupancy={p['mean_batch_rows']}"
        )

    slo = opts.slo_ms
    failures = []
    ok = [
        p for p in points
        if p["shed_rate"] < 0.01 and p["p99_ms"] is not None
        and p["p99_ms"] <= slo
    ]
    best = max(ok, key=lambda p: p["offered_rows_per_s"]) if ok else None
    if best is None:
        failures.append(
            f"serving gate FAILED: no rate point met the SLO "
            f"(p99 <= {slo} ms with shed < 1%)"
        )
        overload = None
    else:
        over = [
            p for p in points
            if p["offered_rows_per_s"] >= 2 * best["offered_rows_per_s"]
        ]
        overload = max(over, key=lambda p: p["offered_rows_per_s"]) \
            if over else None
        if overload is None:
            failures.append(
                "serving gate FAILED: sweep never reached 2x the best "
                f"SLO-meeting rate ({best['offered_rows_per_s']} rows/s) — "
                "extend --rates to cover overload"
            )
        else:
            if not overload["shed_rate"] > best["shed_rate"]:
                failures.append(
                    "serving gate FAILED: 2x overload did not raise the "
                    f"shed rate ({overload['shed_rate']} vs "
                    f"{best['shed_rate']} at the SLO point)"
                )
            if overload["p99_ms"] is not None and overload["p99_ms"] > slo:
                failures.append(
                    "serving gate FAILED: served-task p99 "
                    f"{overload['p99_ms']} ms exceeded the {slo} ms SLO "
                    "under overload — latency collapse, not load shedding"
                )
    out = {
        "metric": "serving_frontier_rows_per_s_vs_latency",
        "unit": "rows/sec vs ms",
        "rollout_dtype": getattr(opts, "rollout_dtype", "float32"),
        "replicas": replicas,
        "slo_ms": slo,
        "block_rows": opts.block_rows,
        "batch_size": opts.batch_size,
        "service_us": opts.service_us,
        "queue_depth": opts.queue_depth,
        "seconds": opts.seconds,
        "seed": opts.seed,
        # same convention as BENCH_r06: no accelerator in the loop; the
        # service-time axis is simulated at the null device's fetch
        "device_free_proxy": True,
        "rate_points": points,
        "gate": {
            "criterion": (
                f"exists rate point with p99 <= {slo} ms and shed < 1%; at "
                ">= 2x that rate, shed rises while served p99 stays <= SLO"
            ),
            "best_slo_point_rows_per_s": (
                best["offered_rows_per_s"] if best else None
            ),
            "overload_point_rows_per_s": (
                overload["offered_rows_per_s"] if overload else None
            ),
            "passed": not failures,
        },
    }
    if getattr(opts, "quant_spec_hash", None):
        out["quant_spec_hash"] = opts.quant_spec_hash
    if getattr(opts, "param_table_bytes", None):
        out["param_table_bytes"] = opts.param_table_bytes
    return out, failures


def run_chaos_rep(opts, replicas: int, rate_rows_per_s: float) -> dict:
    """Replica-kill chaos: open-loop load on the routed plane, one
    replica's scheduler killed mid-submission (the SIGKILL analogue for
    an in-process replica: its queue survives, nobody serves it). The
    acceptance shape: every task RESOLVES (served, or a typed shed the
    masters answer with the uniform fallback — zero lockstep wedges),
    served p99 stays inside the SLO, and the router's flight record
    carries the replica_dead verdict."""
    import numpy as np

    from distributed_ba3c_tpu import telemetry

    target, roles, teardown = _make_plane(opts, replicas)
    router = target.router
    victim = target.preds[0]
    lats: list = []
    sheds: list = []
    state = np.zeros((opts.block_rows, 1), np.uint8)
    rng = np.random.default_rng(opts.seed + 1)
    n_tasks = max(2, int(opts.seconds * rate_rows_per_s / opts.block_rows))
    kill_at = n_tasks // 2
    gaps = rng.exponential(opts.block_rows / rate_rows_per_s, n_tasks)
    clock = time.monotonic
    killed_t = None
    try:
        t_start = clock()
        next_t = t_start
        for i in range(n_tasks):
            if i == kill_at:
                # the kill: the victim's next dispatch raises, its
                # scheduler thread dies with the queue intact — exactly
                # what a SIGKILL leaves behind
                def _die(params, batch):
                    raise RuntimeError("chaos: replica killed")

                victim._dispatch = _die
                killed_t = clock() - t_start
            next_t += gaps[i]
            now = clock()
            if next_t > now:
                time.sleep(next_t - now)
            t0 = clock()

            def cb(a, v, lp, t0=t0):
                lats.append(clock() - t0)

            def shed_cb(rej):
                sheds.append(rej.reason)

            target.put_block_task(state, cb, shed_callback=shed_cb)
        deadline = clock() + opts.slo_ms / 1000.0 * 4 + 10.0
        while len(lats) + len(sheds) < n_tasks and clock() < deadline:
            time.sleep(0.01)
    finally:
        teardown()
    _, _, p99 = _percentiles_ms(lats)
    dead_events = [
        ev for ev in telemetry.flight_recorder().snapshot()
        if ev.get("kind") == "replica_dead"
    ]
    router_scal = telemetry.registry(router.tele_role).scalars()
    return {
        "rate_rows_per_s": rate_rows_per_s,
        "submitted_tasks": n_tasks,
        "killed_after_s": round(killed_t, 3) if killed_t else None,
        "served_tasks": len(lats),
        "shed_tasks": len(sheds),
        "unresolved_tasks": n_tasks - len(lats) - len(sheds),
        "sheds_by_reason": {
            r: sheds.count(r) for r in sorted(set(sheds))
        },
        "served_p99_ms": p99,
        "replica_dead_flight_events": len(dead_events),
        "replica_lost_sheds": router_scal.get("replica_lost_sheds_total", 0),
        "replica_rows": _replica_sub_rows(roles),
    }


def run_canary_rep(opts, replicas: int, rate_rows_per_s: float) -> dict:
    """The canary loop e2e on the routed plane: a WINNING canary is
    auto-promoted to default (statistical reward win inside the SLO),
    then a second, OVERLOADED canary is auto-rolled-back on its SLO
    breach — both decisions land in the flight record WITH their input
    snapshots (the committed evidence)."""
    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate.serving import PromotionController

    target, roles, teardown = _make_plane(opts, replicas)
    router = target.router
    rng = np.random.default_rng(opts.seed + 2)
    state = np.zeros((opts.block_rows, 1), np.uint8)
    clock = time.monotonic

    def drive(n_tasks: int, rate: float):
        gaps = rng.exponential(opts.block_rows / rate, n_tasks)
        next_t = clock()
        for i in range(n_tasks):
            next_t += gaps[i]
            now = clock()
            if next_t > now:
                time.sleep(next_t - now)
            target.put_block_task(
                state, lambda a, v, lp: None,
                shed_callback=lambda rej: None,
            )
        deadline = clock() + opts.slo_ms / 1000.0 * 4 + 5.0
        while router.outstanding_rows() > 0 and clock() < deadline:
            time.sleep(0.01)

    out = {}
    try:
        n = max(20, int(opts.seconds * rate_rows_per_s / opts.block_rows))
        # phase 1: a healthy candidate that WINS on reward
        ctrl = PromotionController(
            router, fraction=0.3, slo_ms=opts.slo_ms,
            min_samples=16, min_decide_tasks=8, interval_s=3600.0,
        )
        ctrl.start_canary({"w": np.float32(1.0)})
        drive(n, rate_rows_per_s)
        for i in range(20):
            ctrl.observe_reward("canary", float(rng.normal(10.0, 0.5)))
            ctrl.observe_reward("default", float(rng.normal(1.0, 0.5)))
        ctrl.tick()
        out["promoted"] = ctrl.state == PromotionController.PROMOTED
        # phase 2: a candidate whose traffic BREACHES the SLO (offered at
        # many times capacity, its share sheds) — auto-rollback
        ctrl2 = PromotionController(
            router, fraction=0.3, slo_ms=opts.slo_ms,
            min_samples=10_000,  # reward evidence can never promote it
            min_decide_tasks=8, breach_shed_rate=0.02, interval_s=3600.0,
        )
        ctrl2.start_canary({"w": np.float32(2.0)})
        drive(4 * n, 8 * rate_rows_per_s)
        ctrl2.tick()
        out["rolled_back"] = ctrl2.state == PromotionController.ROLLED_BACK
    finally:
        teardown()
    flights = telemetry.flight_recorder().snapshot()
    promote_ev = [e for e in flights if e.get("kind") == "canary_promote"]
    rollback_ev = [e for e in flights if e.get("kind") == "canary_rollback"]
    out["promote_flight_event"] = promote_ev[-1] if promote_ev else None
    out["rollback_flight_event"] = rollback_ev[-1] if rollback_ev else None
    return out


def run_replicated(opts) -> tuple:
    """The ISSUE-15 instrument: single-replica frontier and R-replica
    routed frontier in ONE session (same host, same nulls — same-session
    ratios are the honest unit, PERF.md convention), the near-linear
    scaling gate, the replica-kill chaos rep, and the canary
    promote/rollback e2e. Returns (json_row, failures)."""
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    R = opts.replicas
    single_row, single_failures = run_frontier(opts, replicas=1)
    routed_rates = [r * R for r in opts.rates]
    routed_row, routed_failures = run_frontier(
        opts, replicas=R, rates=routed_rates
    )
    failures = [f"single-replica {m}" for m in single_failures]
    failures += [f"routed x{R} {m}" for m in routed_failures]

    slo = opts.slo_ms

    def best(row):
        ok = [
            p for p in row["rate_points"]
            if p["shed_rate"] < 0.01 and p["p99_ms"] is not None
            and p["p99_ms"] <= slo
        ]
        return max(ok, key=lambda p: p["served_rows_per_s"]) if ok else None

    b1, bR = best(single_row), best(routed_row)
    required = opts.gate_frac * R
    ratio = None
    if b1 is None or bR is None:
        failures.append(
            "scaling gate FAILED: no SLO-meeting rate point on "
            f"{'the single plane' if b1 is None else 'the routed plane'}"
        )
    else:
        ratio = bR["served_rows_per_s"] / max(b1["served_rows_per_s"], 1e-9)
        if ratio < required:
            failures.append(
                f"scaling gate FAILED: x{R} routed served "
                f"{bR['served_rows_per_s']} rows/s = {ratio:.2f}x the "
                f"single plane's {b1['served_rows_per_s']} at equal p99 "
                f"(need >= {required:.2f}x)"
            )
        dead = [
            sub for p in routed_row["rate_points"]
            for sub in p.get("replica_rows", ())
            if sub["rows"] == 0
        ]
        if dead:
            failures.append(
                f"scaling gate FAILED: {len(dead)} per-replica sub-rows "
                "served ZERO rows — a dead replica is hiding in the "
                "aggregate"
            )
    stderr_print(
        f"scaling: single best {b1['served_rows_per_s'] if b1 else None} "
        f"rows/s, x{R} routed best "
        f"{bR['served_rows_per_s'] if bR else None} rows/s "
        f"(ratio {f'{ratio:.2f}' if ratio else 'n/a'}, "
        f"gate >= {required:.2f})"
    )

    chaos_rate = (
        0.5 * bR["served_rows_per_s"] if bR is not None
        else 0.5 * routed_rates[0]
    )
    chaos = run_chaos_rep(opts, R, chaos_rate)
    if chaos["unresolved_tasks"] != 0:
        failures.append(
            f"chaos gate FAILED: {chaos['unresolved_tasks']} tasks never "
            "resolved after the replica kill — a lockstep caller would "
            "have wedged"
        )
    if chaos["served_p99_ms"] is not None and chaos["served_p99_ms"] > slo:
        failures.append(
            f"chaos gate FAILED: served p99 {chaos['served_p99_ms']} ms "
            f"breached the {slo} ms SLO during the replica kill"
        )
    if chaos["replica_dead_flight_events"] == 0:
        failures.append(
            "chaos gate FAILED: the kill left no replica_dead flight "
            "event — the router never noticed"
        )

    canary = run_canary_rep(
        opts, R, chaos_rate if bR is None else 0.3 * bR["served_rows_per_s"]
    )
    if not canary["promoted"] or canary["promote_flight_event"] is None:
        failures.append(
            "canary gate FAILED: the winning candidate was not promoted "
            "(or its decision left no flight event)"
        )
    if not canary["rolled_back"] or canary["rollback_flight_event"] is None:
        failures.append(
            "canary gate FAILED: the SLO-breaching candidate was not "
            "rolled back (or its decision left no flight event)"
        )

    out = {
        "metric": "replicated_serving_rows_per_s_vs_latency",
        "unit": "rows/sec vs ms",
        "rollout_dtype": getattr(opts, "rollout_dtype", "float32"),
        "replicas": R,
        "slo_ms": slo,
        "block_rows": opts.block_rows,
        "batch_size": opts.batch_size,
        "service_us": opts.service_us,
        "queue_depth": opts.queue_depth,
        "seconds": opts.seconds,
        "seed": opts.seed,
        "device_free_proxy": True,
        "single": single_row,
        "routed": routed_row,
        "scaling_gate": {
            "criterion": (
                f"x{R} routed served rows/s >= {required:.2f}x the "
                f"same-session single plane at equal p99 inside the "
                f"{slo} ms SLO; every per-replica sub-row served > 0"
            ),
            "single_best_rows_per_s": (
                b1["served_rows_per_s"] if b1 else None
            ),
            "routed_best_rows_per_s": (
                bR["served_rows_per_s"] if bR else None
            ),
            "ratio": round(ratio, 3) if ratio is not None else None,
            "required": round(required, 3),
        },
        "chaos": chaos,
        "canary": canary,
        "gate": {"passed": not failures},
    }
    return out, failures


def _quant_artifacts(opts) -> dict:
    """The REAL int8 artifacts the dtype sweep's evidence is measured on:
    canonical BA3CNet params, a QuantSpec calibrated from real jax-Pong
    rollout frames (calibrate_from_env — the same path ``--rollout_dtype
    int8 --quant_calibrate N`` takes), per-dtype param-table bytes summed
    over the actual table leaves, and the Pong parity section holding the
    int8 forward inside the bf16 bands (tests/test_staging.py: |d log mu|
    < 0.1, |dV| < 0.05)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import make_rollout_body
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.quantize import (
        calibrate_from_env,
        make_quant_apply,
        quantize_params,
    )

    cfg = BA3CConfig(num_actions=pong.num_actions)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    key = jax.random.PRNGKey(opts.seed)
    dummy = jnp.zeros((1, *cfg.state_shape), jnp.uint8)
    params = model.init(key, dummy)["params"]
    spec = calibrate_from_env(
        model, cfg, pong, params, jax.random.fold_in(key, 1),
        n_envs=8, batches=2, rollout_len=16,
    )
    qparams = jax.device_get(
        jax.jit(lambda p: quantize_params(p, spec))(params)
    )

    def table_bytes(tree):
        return int(sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(tree)
        ))

    bf16 = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        params,
    )
    # parity frames: a FRESH rollout window (distinct key) through the
    # actor's own scan body — real game pixels, not the calibration set
    keys = jax.random.split(jax.random.fold_in(key, 2), 8)
    env_state = jax.vmap(pong.reset)(keys)
    obs = jax.vmap(pong.render)(env_state)
    stack = jnp.zeros(
        (8, *obs.shape[1:], cfg.frame_history), jnp.uint8
    ).at[..., -1].set(obs)
    body = make_rollout_body(model, cfg, pong, params)
    carry = (
        env_state, stack, jax.random.fold_in(key, 3),
        jnp.zeros(8, jnp.float32), jnp.zeros(8, jnp.int32),
        jnp.zeros(8, jnp.float32),
    )
    _, traj = jax.jit(
        lambda c: lax.scan(body, c, None, length=16)
    )(carry)
    frames = jnp.asarray(traj[0]).reshape(-1, *cfg.state_shape)
    out32 = model.apply({"params": params}, frames)
    outq = make_quant_apply(model)(qparams, frames)
    lp32 = jax.nn.log_softmax(out32.logits, axis=-1)
    lpq = jax.nn.log_softmax(outq.logits, axis=-1)
    d_logmu = float(jnp.max(jnp.abs(lp32 - lpq)))
    d_value = float(jnp.max(jnp.abs(out32.value - outq.value)))
    return {
        "spec": spec,
        "param_table_bytes": {
            "float32": table_bytes(params),
            "bfloat16": table_bytes(jax.device_get(bf16)),
            "int8": table_bytes(qparams),
        },
        "parity": {
            "env": "jax:pong",
            "frames": int(frames.shape[0]),
            "calibration_batches": spec.calibration_batches,
            "calibration_rows": spec.calibration_rows,
            "max_abs_d_log_mu": round(d_logmu, 6),
            "max_abs_d_value": round(d_value, 6),
            # the acceptance bands are the bf16 rung's own
            # (tests/test_staging.py) — int8 must not be a WORSE serving
            # numerics rung than the one below it on the ladder
            "band_log_mu": 0.1,
            "band_value": 0.05,
            "inside_bf16_bands": d_logmu < 0.1 and d_value < 0.05,
        },
    }


def run_dtype_sweep(opts) -> tuple:
    """The rollout-precision ladder frontier (``--dtype f32,bf16,int8``):
    one single-replica frontier per dtype, service time and offered rates
    scaled by the MXU-throughput model so each sweep covers ITS OWN knee,
    plus the Pong parity section and the rows/s-per-replica gate (int8
    best >= ``--quant_gate_ratio`` x bf16 best at equal p99 inside the
    SLO). Returns (json_row, failures)."""
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    artifacts = _quant_artifacts(opts) if "int8" in opts.dtypes else None
    failures = []
    frontiers = {}
    for dtype in opts.dtypes:
        factor = _DTYPE_SERVICE_FACTOR[dtype]
        sub = SimpleNamespace(**vars(opts))
        sub.rollout_dtype = dtype
        sub.service_us = opts.service_us * factor
        # faster service moves the knee up — scale the offered rates so
        # every dtype's sweep covers both sides of ITS knee (otherwise
        # the rate ceiling, not the device, caps the faster rungs and the
        # ratio gate reads 1.0x)
        sub.rates = [r / factor for r in opts.rates]
        if artifacts is not None:
            sub.param_table_bytes = artifacts["param_table_bytes"][dtype]
            if dtype == "int8":
                sub.quant_spec_hash = artifacts["spec"].sha256()
        stderr_print(
            f"dtype {dtype}: service_us={sub.service_us:.0f} "
            f"(factor {factor})"
        )
        row, fr = run_frontier(sub, replicas=1)
        frontiers[dtype] = row
        failures += [f"{dtype} {m}" for m in fr]

    def best(row):
        # a dtype's capacity claim is its best SERVED rows/s among points
        # whose served p99 holds the SLO — shedding is the admission
        # control protecting that latency, so an overloaded point still
        # counts (its served rate IS the sustainable capacity). Requiring
        # shed < 1% here would collapse every dtype onto the same
        # pre-knee rate on a loaded CI host and read the ratio as 1.0x
        slo = opts.slo_ms
        ok = [
            p for p in row["rate_points"]
            if p["p99_ms"] is not None and p["p99_ms"] <= slo
        ]
        return max(ok, key=lambda p: p["served_rows_per_s"]) if ok else None

    gate = None
    if "int8" in frontiers and "bfloat16" in frontiers:
        b8, bbf = best(frontiers["int8"]), best(frontiers["bfloat16"])
        required = opts.quant_gate_ratio
        ratio = None
        if b8 is None or bbf is None:
            failures.append(
                "quant gate FAILED: no SLO-meeting rate point on the "
                f"{'int8' if b8 is None else 'bf16'} frontier"
            )
        else:
            ratio = b8["served_rows_per_s"] / max(
                bbf["served_rows_per_s"], 1e-9
            )
            if ratio < required:
                failures.append(
                    f"quant gate FAILED: int8 served "
                    f"{b8['served_rows_per_s']} rows/s/replica = "
                    f"{ratio:.2f}x bf16's {bbf['served_rows_per_s']} with "
                    f"served p99 inside the {opts.slo_ms} ms SLO "
                    f"(need >= {required:.2f}x)"
                )
        gate = {
            "criterion": (
                f"int8 best served rows/s-per-replica >= "
                f"{opts.quant_gate_ratio:.2f}x bf16's, both at served "
                f"p99 inside the {opts.slo_ms} ms SLO; int8 Pong parity "
                "inside the bf16 bands"
            ),
            "int8_best_rows_per_s": (
                b8["served_rows_per_s"] if b8 else None
            ),
            "bf16_best_rows_per_s": (
                bbf["served_rows_per_s"] if bbf else None
            ),
            "ratio": round(ratio, 3) if ratio is not None else None,
            "required": opts.quant_gate_ratio,
        }
    if artifacts is not None and not artifacts["parity"]["inside_bf16_bands"]:
        failures.append(
            "quant gate FAILED: int8 Pong parity outside the bf16 bands "
            f"(d_log_mu={artifacts['parity']['max_abs_d_log_mu']}, "
            f"d_value={artifacts['parity']['max_abs_d_value']})"
        )
    out = {
        "metric": "quantized_serving_frontier_rows_per_s_vs_latency",
        "unit": "rows/sec vs ms",
        "rollout_dtype": ",".join(opts.dtypes),
        "replicas": 1,
        "slo_ms": opts.slo_ms,
        "block_rows": opts.block_rows,
        "batch_size": opts.batch_size,
        "service_us": opts.service_us,
        "service_factor_model": {
            d: _DTYPE_SERVICE_FACTOR[d] for d in opts.dtypes
        },
        "seconds": opts.seconds,
        "seed": opts.seed,
        # the frontier's service-time axis is the MXU-throughput MODEL on
        # the null device; the parity section and table bytes are real.
        # On-chip re-capture of the absolute rows/s is tracked in ROADMAP
        # item 1 — the RATIO at equal p99 is the pinned claim
        "device_free_proxy": True,
        "frontiers": frontiers,
        "gate": dict(gate or {}, passed=not failures),
    }
    if artifacts is not None:
        out["quant_spec_hash"] = artifacts["spec"].sha256()
        out["param_table_bytes"] = artifacts["param_table_bytes"]
        out["pong_parity"] = artifacts["parity"]
    return out, failures


def parse_opts(argv=None) -> SimpleNamespace:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--rates", default="1000,2000,4000,8000,16000",
        help="comma list of offered rates in ROWS/s (each request is a "
        "--block_rows block). The default tops out at ~2x the default "
        "service capacity so the sweep covers both sides of the knee",
    )
    ap.add_argument(
        "--block_rows", type=int, default=8,
        help="rows per request (the block wire's natural request unit)",
    )
    ap.add_argument(
        "--batch_size", type=int, default=32,
        help="predictor coalesce target; the bucket cap is the next pow-2 "
        "(capacity = cap rows per --service_us device call)",
    )
    ap.add_argument(
        "--service_us", type=float, default=4000.0,
        help="simulated device time per call (slept at fetch) — the "
        "frontier's service-time axis on a device-free host",
    )
    ap.add_argument("--slo_ms", type=float, default=50.0)
    ap.add_argument(
        "--queue_depth", type=int, default=64,
        help="admission-queue bound in TASKS (overload beyond it is fast "
        "queue_full rejection)",
    )
    ap.add_argument("--seconds", type=float, default=4.0, help="per rate point")
    ap.add_argument("--num_actions", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--replicas", type=int, default=1,
        help="R > 1 = the ISSUE-15 replicated instrument: single AND "
        "R-replica routed frontiers same-session (routed rates = --rates "
        "x R), the near-linear scaling gate, a replica-kill chaos rep, "
        "and the canary promote/rollback e2e",
    )
    ap.add_argument(
        "--gate_frac", type=float, default=0.8,
        help="scaling gate: routed served rows/s must be >= gate_frac * R "
        "x the same-session single plane (0.8 * 4 = the 3.2x acceptance "
        "bar)",
    )
    ap.add_argument(
        "--dtype", default="float32",
        help="comma list from {f32,bf16,int8}: one entry = stamp every "
        "row with that rollout_dtype; several = the rollout-precision "
        "ladder sweep (one frontier per dtype under the MXU-throughput "
        "service model, int8 calibrated from real jax-Pong rollouts, "
        "Pong parity section, rows/s-per-replica gate)",
    )
    ap.add_argument(
        "--quant_gate_ratio", type=float, default=1.05,
        help="dtype sweep gate: int8 best served rows/s-per-replica must "
        "be >= this x bf16's at equal p99 inside the SLO",
    )
    args = ap.parse_args(argv)
    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if not rates:
        raise SystemExit("--rates must name at least one rate")
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    dtypes = []
    for d in args.dtype.split(","):
        d = d.strip()
        if not d:
            continue
        if d not in _DTYPE_ALIASES:
            raise SystemExit(
                f"--dtype {d!r} is not on the ladder "
                f"(choose from {sorted(set(_DTYPE_ALIASES))})"
            )
        dtypes.append(_DTYPE_ALIASES[d])
    if not dtypes:
        raise SystemExit("--dtype must name at least one dtype")
    if args.replicas > 1 and len(dtypes) > 1:
        raise SystemExit(
            "--dtype sweeps and --replicas > 1 are separate instruments — "
            "run them as two invocations"
        )
    return SimpleNamespace(rates=rates, dtypes=dtypes, **{
        k: getattr(args, k)
        for k in ("block_rows", "batch_size", "service_us", "slo_ms",
                  "queue_depth", "seconds", "num_actions", "seed",
                  "replicas", "gate_frac", "quant_gate_ratio")
    })


def main(argv=None) -> int:
    # no accelerator in the loop, ever: pin cpu BEFORE jax imports and
    # never take the TPU-claim mutex (same stance as plane_bench
    # device-free mode)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    opts = parse_opts(argv)
    if len(opts.dtypes) > 1:
        out, failures = run_dtype_sweep(opts)
    elif opts.replicas > 1:
        opts.rollout_dtype = opts.dtypes[0]
        out, failures = run_replicated(opts)
    else:
        opts.rollout_dtype = opts.dtypes[0]
        out, failures = run_frontier(opts)
    # the JSON (per-point evidence) prints BEFORE any gate verdict — the
    # evidence is most valuable exactly when the gate fails
    print(json.dumps(out))
    if failures:
        from distributed_ba3c_tpu.utils.devicelock import stderr_print

        for msg in failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
