#!/usr/bin/env python
"""Latency-vs-throughput frontier for the SLO-aware serving plane.

Drives the real ``BatchedPredictor`` scheduler — continuous batching,
deadline admission, load shedding (docs/serving.md) — with OPEN-LOOP
Poisson arrivals at a sweep of offered rates, and publishes per-rate
p50/p90/p99 serve latency, shed rate and batch occupancy: the frontier the
way ``plane_bench_r6/r7`` publish throughput.

Open-loop matters: a closed-loop driver slows down with the server and
hides the overload region entirely; here arrivals keep coming at the
offered rate no matter what, so past saturation the plane must SHED (fast
typed rejects) while the p99 of what it does serve stays under the SLO —
that is the acceptance shape, load shedding rather than latency collapse.

Device-free by default: the device is the plane-bench null predictor with
a SIMULATED per-call service time (``--service_us``, slept at fetch like a
real serialized device queue), so the frontier's service-time axis is real
while no accelerator (and no tunnel RTT) is in the loop —
``device_free_proxy: true`` in the JSON, same convention as BENCH_r06.

Prints ONE JSON line on stdout (the repo's bench-tooling contract), with
the per-rate evidence BEFORE any gate verdict; diagnostics go to stderr.

Usage:
  python scripts/serving_bench.py                       # default sweep + gate
  python scripts/serving_bench.py --rates 1000,4000 --seconds 2   # CI smoke
  python scripts/plane_bench.py --serving               # embedded in the
                                                        # plane instrument
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from types import SimpleNamespace

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _percentiles_ms(lats):
    import numpy as np

    if not lats:
        return None, None, None
    arr = np.asarray(lats) * 1000.0
    return (
        round(float(np.percentile(arr, 50)), 3),
        round(float(np.percentile(arr, 90)), 3),
        round(float(np.percentile(arr, 99)), 3),
    )


def _make_replica(opts, tele_role: str):
    """One null-device replica (a complete BatchedPredictor serving plane
    with simulated service time — bench.make_null_predictor) under its
    own telemetry role, started."""
    from bench import make_null_predictor

    # a stub model is enough: the null predictor never traces the forward,
    # and the scheduler only reads num_actions for the fallback contract
    model = SimpleNamespace(num_actions=opts.num_actions, apply=None)
    pred = make_null_predictor(
        model, {}, opts.num_actions,
        service_s=opts.service_us / 1e6,
        batch_size=opts.batch_size,
        coalesce_ms=0.0,
        slo_ms=opts.slo_ms,
        queue_depth=opts.queue_depth,
        tele_role=tele_role,
    )
    pred.start()
    return pred


def _make_plane(opts, replicas: int):
    """Build the measurand: a single predictor (``replicas == 1``, the
    PR-9 plane, byte-identical behavior) or R replicas behind the REAL
    ServingRouter. Returns ``(target, roles, teardown)`` where ``roles``
    are the telemetry registries the point's evidence reads."""
    from distributed_ba3c_tpu import telemetry

    telemetry.reset_all()
    if replicas == 1:
        pred = _make_replica(opts, "predictor")
        return pred, ["predictor"], lambda: (pred.stop(), pred.join(5))

    from distributed_ba3c_tpu.predict.router import (
        ServingRouter,
        replica_role,
    )

    router = ServingRouter(health_interval_s=0.1)
    preds = []
    roles = []
    for i in range(replicas):
        role = replica_role("predictor", i)
        pred = _make_replica(opts, role)
        router.add_replica(f"r{i}", pred)
        preds.append(pred)
        roles.append(role)
    router.start()

    def teardown():
        router.stop()
        router.join(timeout=5)
        for p in preds:
            p.stop()
            p.join(timeout=5)

    target = SimpleNamespace(
        put_block_task=router.put_block_task, router=router, preds=preds
    )
    return target, roles, teardown


def _replica_sub_rows(roles) -> list:
    """Per-replica occupancy/shed/p99 evidence rows — a dead replica must
    not hide behind a healthy aggregate (ISSUE 15 house style)."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.predict.router import signals_from_snapshot

    rows = []
    for role in roles:
        snap = telemetry.registry(role).collect()
        s = signals_from_snapshot(snap)
        batches = float(snap.get("batches_total", {}).get("value", 0.0))
        served_rows = s["rows_total"]
        rows.append({
            "role": role,
            "rows": served_rows,
            "batches": batches,
            "mean_batch_rows": (
                round(served_rows / batches, 2) if batches else None
            ),
            "sheds": s["sheds_total"],
            "serve_p99_ms": s["serve_p99_ms"],
            "deadline_misses": float(
                snap.get("deadline_misses_total", {}).get("value", 0.0)
            ),
        })
    return rows


def _drive_point(target, rate_rows_per_s: float, opts) -> tuple:
    """The open-loop Poisson submit/drain loop against ``target`` (a
    predictor or the routed facade). Returns (lats, sheds, submit_elapsed,
    total_elapsed, n_tasks)."""
    import numpy as np

    lats: list = []    # served: admit -> callback, seconds
    sheds: list = []   # ShedReject.reason per shed task
    state = np.zeros((opts.block_rows, 1), np.uint8)  # content is irrelevant
    rng = np.random.default_rng(opts.seed)
    n_tasks = max(1, int(opts.seconds * rate_rows_per_s / opts.block_rows))
    mean_gap = opts.block_rows / rate_rows_per_s
    gaps = rng.exponential(mean_gap, n_tasks)
    clock = time.monotonic
    t_start = clock()
    next_t = t_start
    for i in range(n_tasks):
        next_t += gaps[i]
        now = clock()
        if next_t > now:
            time.sleep(next_t - now)
        t0 = clock()

        def cb(a, v, lp, t0=t0):
            lats.append(clock() - t0)

        def shed_cb(rej):
            sheds.append(rej.reason)

        target.put_block_task(state, cb, shed_callback=shed_cb)
    submit_elapsed = clock() - t_start
    # drain: every deadline'd task resolves (served, or shed at pop)
    deadline = clock() + opts.slo_ms / 1000.0 * 4 + 10.0
    while len(lats) + len(sheds) < n_tasks and clock() < deadline:
        time.sleep(0.01)
    # served throughput is measured over the WHOLE service window
    # (submission + drain): dividing drain-phase completions by the
    # submission window alone would overstate capacity exactly at the
    # knee, where the backlog drains after arrivals stop
    total_elapsed = clock() - t_start
    return lats, sheds, submit_elapsed, total_elapsed, n_tasks


def run_point(rate_rows_per_s: float, opts, replicas: int = 1) -> dict:
    """One open-loop rate point: fresh plane, Poisson arrivals of
    ``block_rows``-row block tasks for ``seconds``, drained to
    completion. ``replicas > 1`` drives the routed plane and embeds
    per-replica sub-rows."""
    from distributed_ba3c_tpu import telemetry

    target, roles, teardown = _make_plane(opts, replicas)
    try:
        lats, sheds, submit_elapsed, total_elapsed, n_tasks = _drive_point(
            target, rate_rows_per_s, opts
        )
    finally:
        teardown()
    batches = rows = misses = 0.0
    for role in roles:
        scal = telemetry.registry(role).scalars()
        batches += scal.get("batches_total", 0)
        rows += scal.get("rows_total", 0)
        misses += scal.get("deadline_misses_total", 0)
    p50, p90, p99 = _percentiles_ms(lats)
    served = len(lats)
    shed = len(sheds)
    point = {
        "offered_rows_per_s": round(
            n_tasks * opts.block_rows / max(submit_elapsed, 1e-9), 1
        ),
        "target_rows_per_s": rate_rows_per_s,
        "submitted_tasks": n_tasks,
        "served_tasks": served,
        "shed_tasks": shed,
        "unresolved_tasks": n_tasks - served - shed,
        "shed_rate": round(shed / n_tasks, 4),
        "sheds_by_reason": {
            r: sheds.count(r) for r in sorted(set(sheds))
        },
        "p50_ms": p50,
        "p90_ms": p90,
        "p99_ms": p99,
        "served_rows_per_s": round(
            served * opts.block_rows / max(total_elapsed, 1e-9), 1
        ),
        "mean_batch_rows": round(rows / batches, 2) if batches else None,
        "deadline_misses": misses,
    }
    if replicas > 1:
        point["replica_rows"] = _replica_sub_rows(roles)
    return point


def run_frontier(opts, replicas: int = 1, rates=None) -> tuple:
    """The full sweep + gate. Returns (json_row, gate_failure_messages)."""
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    points = []
    for rate in (opts.rates if rates is None else rates):
        p = run_point(rate, opts, replicas=replicas)
        points.append(p)
        stderr_print(
            f"serving x{replicas} {rate:>8.0f} rows/s offered: "
            f"p99={p['p99_ms']} ms shed={p['shed_rate']:.1%} "
            f"occupancy={p['mean_batch_rows']}"
        )

    slo = opts.slo_ms
    failures = []
    ok = [
        p for p in points
        if p["shed_rate"] < 0.01 and p["p99_ms"] is not None
        and p["p99_ms"] <= slo
    ]
    best = max(ok, key=lambda p: p["offered_rows_per_s"]) if ok else None
    if best is None:
        failures.append(
            f"serving gate FAILED: no rate point met the SLO "
            f"(p99 <= {slo} ms with shed < 1%)"
        )
        overload = None
    else:
        over = [
            p for p in points
            if p["offered_rows_per_s"] >= 2 * best["offered_rows_per_s"]
        ]
        overload = max(over, key=lambda p: p["offered_rows_per_s"]) \
            if over else None
        if overload is None:
            failures.append(
                "serving gate FAILED: sweep never reached 2x the best "
                f"SLO-meeting rate ({best['offered_rows_per_s']} rows/s) — "
                "extend --rates to cover overload"
            )
        else:
            if not overload["shed_rate"] > best["shed_rate"]:
                failures.append(
                    "serving gate FAILED: 2x overload did not raise the "
                    f"shed rate ({overload['shed_rate']} vs "
                    f"{best['shed_rate']} at the SLO point)"
                )
            if overload["p99_ms"] is not None and overload["p99_ms"] > slo:
                failures.append(
                    "serving gate FAILED: served-task p99 "
                    f"{overload['p99_ms']} ms exceeded the {slo} ms SLO "
                    "under overload — latency collapse, not load shedding"
                )
    out = {
        "metric": "serving_frontier_rows_per_s_vs_latency",
        "unit": "rows/sec vs ms",
        "replicas": replicas,
        "slo_ms": slo,
        "block_rows": opts.block_rows,
        "batch_size": opts.batch_size,
        "service_us": opts.service_us,
        "queue_depth": opts.queue_depth,
        "seconds": opts.seconds,
        "seed": opts.seed,
        # same convention as BENCH_r06: no accelerator in the loop; the
        # service-time axis is simulated at the null device's fetch
        "device_free_proxy": True,
        "rate_points": points,
        "gate": {
            "criterion": (
                f"exists rate point with p99 <= {slo} ms and shed < 1%; at "
                ">= 2x that rate, shed rises while served p99 stays <= SLO"
            ),
            "best_slo_point_rows_per_s": (
                best["offered_rows_per_s"] if best else None
            ),
            "overload_point_rows_per_s": (
                overload["offered_rows_per_s"] if overload else None
            ),
            "passed": not failures,
        },
    }
    return out, failures


def run_chaos_rep(opts, replicas: int, rate_rows_per_s: float) -> dict:
    """Replica-kill chaos: open-loop load on the routed plane, one
    replica's scheduler killed mid-submission (the SIGKILL analogue for
    an in-process replica: its queue survives, nobody serves it). The
    acceptance shape: every task RESOLVES (served, or a typed shed the
    masters answer with the uniform fallback — zero lockstep wedges),
    served p99 stays inside the SLO, and the router's flight record
    carries the replica_dead verdict."""
    import numpy as np

    from distributed_ba3c_tpu import telemetry

    target, roles, teardown = _make_plane(opts, replicas)
    router = target.router
    victim = target.preds[0]
    lats: list = []
    sheds: list = []
    state = np.zeros((opts.block_rows, 1), np.uint8)
    rng = np.random.default_rng(opts.seed + 1)
    n_tasks = max(2, int(opts.seconds * rate_rows_per_s / opts.block_rows))
    kill_at = n_tasks // 2
    gaps = rng.exponential(opts.block_rows / rate_rows_per_s, n_tasks)
    clock = time.monotonic
    killed_t = None
    try:
        t_start = clock()
        next_t = t_start
        for i in range(n_tasks):
            if i == kill_at:
                # the kill: the victim's next dispatch raises, its
                # scheduler thread dies with the queue intact — exactly
                # what a SIGKILL leaves behind
                def _die(params, batch):
                    raise RuntimeError("chaos: replica killed")

                victim._dispatch = _die
                killed_t = clock() - t_start
            next_t += gaps[i]
            now = clock()
            if next_t > now:
                time.sleep(next_t - now)
            t0 = clock()

            def cb(a, v, lp, t0=t0):
                lats.append(clock() - t0)

            def shed_cb(rej):
                sheds.append(rej.reason)

            target.put_block_task(state, cb, shed_callback=shed_cb)
        deadline = clock() + opts.slo_ms / 1000.0 * 4 + 10.0
        while len(lats) + len(sheds) < n_tasks and clock() < deadline:
            time.sleep(0.01)
    finally:
        teardown()
    _, _, p99 = _percentiles_ms(lats)
    dead_events = [
        ev for ev in telemetry.flight_recorder().snapshot()
        if ev.get("kind") == "replica_dead"
    ]
    router_scal = telemetry.registry(router.tele_role).scalars()
    return {
        "rate_rows_per_s": rate_rows_per_s,
        "submitted_tasks": n_tasks,
        "killed_after_s": round(killed_t, 3) if killed_t else None,
        "served_tasks": len(lats),
        "shed_tasks": len(sheds),
        "unresolved_tasks": n_tasks - len(lats) - len(sheds),
        "sheds_by_reason": {
            r: sheds.count(r) for r in sorted(set(sheds))
        },
        "served_p99_ms": p99,
        "replica_dead_flight_events": len(dead_events),
        "replica_lost_sheds": router_scal.get("replica_lost_sheds_total", 0),
        "replica_rows": _replica_sub_rows(roles),
    }


def run_canary_rep(opts, replicas: int, rate_rows_per_s: float) -> dict:
    """The canary loop e2e on the routed plane: a WINNING canary is
    auto-promoted to default (statistical reward win inside the SLO),
    then a second, OVERLOADED canary is auto-rolled-back on its SLO
    breach — both decisions land in the flight record WITH their input
    snapshots (the committed evidence)."""
    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate.serving import PromotionController

    target, roles, teardown = _make_plane(opts, replicas)
    router = target.router
    rng = np.random.default_rng(opts.seed + 2)
    state = np.zeros((opts.block_rows, 1), np.uint8)
    clock = time.monotonic

    def drive(n_tasks: int, rate: float):
        gaps = rng.exponential(opts.block_rows / rate, n_tasks)
        next_t = clock()
        for i in range(n_tasks):
            next_t += gaps[i]
            now = clock()
            if next_t > now:
                time.sleep(next_t - now)
            target.put_block_task(
                state, lambda a, v, lp: None,
                shed_callback=lambda rej: None,
            )
        deadline = clock() + opts.slo_ms / 1000.0 * 4 + 5.0
        while router.outstanding_rows() > 0 and clock() < deadline:
            time.sleep(0.01)

    out = {}
    try:
        n = max(20, int(opts.seconds * rate_rows_per_s / opts.block_rows))
        # phase 1: a healthy candidate that WINS on reward
        ctrl = PromotionController(
            router, fraction=0.3, slo_ms=opts.slo_ms,
            min_samples=16, min_decide_tasks=8, interval_s=3600.0,
        )
        ctrl.start_canary({"w": np.float32(1.0)})
        drive(n, rate_rows_per_s)
        for i in range(20):
            ctrl.observe_reward("canary", float(rng.normal(10.0, 0.5)))
            ctrl.observe_reward("default", float(rng.normal(1.0, 0.5)))
        ctrl.tick()
        out["promoted"] = ctrl.state == PromotionController.PROMOTED
        # phase 2: a candidate whose traffic BREACHES the SLO (offered at
        # many times capacity, its share sheds) — auto-rollback
        ctrl2 = PromotionController(
            router, fraction=0.3, slo_ms=opts.slo_ms,
            min_samples=10_000,  # reward evidence can never promote it
            min_decide_tasks=8, breach_shed_rate=0.02, interval_s=3600.0,
        )
        ctrl2.start_canary({"w": np.float32(2.0)})
        drive(4 * n, 8 * rate_rows_per_s)
        ctrl2.tick()
        out["rolled_back"] = ctrl2.state == PromotionController.ROLLED_BACK
    finally:
        teardown()
    flights = telemetry.flight_recorder().snapshot()
    promote_ev = [e for e in flights if e.get("kind") == "canary_promote"]
    rollback_ev = [e for e in flights if e.get("kind") == "canary_rollback"]
    out["promote_flight_event"] = promote_ev[-1] if promote_ev else None
    out["rollback_flight_event"] = rollback_ev[-1] if rollback_ev else None
    return out


def run_replicated(opts) -> tuple:
    """The ISSUE-15 instrument: single-replica frontier and R-replica
    routed frontier in ONE session (same host, same nulls — same-session
    ratios are the honest unit, PERF.md convention), the near-linear
    scaling gate, the replica-kill chaos rep, and the canary
    promote/rollback e2e. Returns (json_row, failures)."""
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    R = opts.replicas
    single_row, single_failures = run_frontier(opts, replicas=1)
    routed_rates = [r * R for r in opts.rates]
    routed_row, routed_failures = run_frontier(
        opts, replicas=R, rates=routed_rates
    )
    failures = [f"single-replica {m}" for m in single_failures]
    failures += [f"routed x{R} {m}" for m in routed_failures]

    slo = opts.slo_ms

    def best(row):
        ok = [
            p for p in row["rate_points"]
            if p["shed_rate"] < 0.01 and p["p99_ms"] is not None
            and p["p99_ms"] <= slo
        ]
        return max(ok, key=lambda p: p["served_rows_per_s"]) if ok else None

    b1, bR = best(single_row), best(routed_row)
    required = opts.gate_frac * R
    ratio = None
    if b1 is None or bR is None:
        failures.append(
            "scaling gate FAILED: no SLO-meeting rate point on "
            f"{'the single plane' if b1 is None else 'the routed plane'}"
        )
    else:
        ratio = bR["served_rows_per_s"] / max(b1["served_rows_per_s"], 1e-9)
        if ratio < required:
            failures.append(
                f"scaling gate FAILED: x{R} routed served "
                f"{bR['served_rows_per_s']} rows/s = {ratio:.2f}x the "
                f"single plane's {b1['served_rows_per_s']} at equal p99 "
                f"(need >= {required:.2f}x)"
            )
        dead = [
            sub for p in routed_row["rate_points"]
            for sub in p.get("replica_rows", ())
            if sub["rows"] == 0
        ]
        if dead:
            failures.append(
                f"scaling gate FAILED: {len(dead)} per-replica sub-rows "
                "served ZERO rows — a dead replica is hiding in the "
                "aggregate"
            )
    stderr_print(
        f"scaling: single best {b1['served_rows_per_s'] if b1 else None} "
        f"rows/s, x{R} routed best "
        f"{bR['served_rows_per_s'] if bR else None} rows/s "
        f"(ratio {f'{ratio:.2f}' if ratio else 'n/a'}, "
        f"gate >= {required:.2f})"
    )

    chaos_rate = (
        0.5 * bR["served_rows_per_s"] if bR is not None
        else 0.5 * routed_rates[0]
    )
    chaos = run_chaos_rep(opts, R, chaos_rate)
    if chaos["unresolved_tasks"] != 0:
        failures.append(
            f"chaos gate FAILED: {chaos['unresolved_tasks']} tasks never "
            "resolved after the replica kill — a lockstep caller would "
            "have wedged"
        )
    if chaos["served_p99_ms"] is not None and chaos["served_p99_ms"] > slo:
        failures.append(
            f"chaos gate FAILED: served p99 {chaos['served_p99_ms']} ms "
            f"breached the {slo} ms SLO during the replica kill"
        )
    if chaos["replica_dead_flight_events"] == 0:
        failures.append(
            "chaos gate FAILED: the kill left no replica_dead flight "
            "event — the router never noticed"
        )

    canary = run_canary_rep(
        opts, R, chaos_rate if bR is None else 0.3 * bR["served_rows_per_s"]
    )
    if not canary["promoted"] or canary["promote_flight_event"] is None:
        failures.append(
            "canary gate FAILED: the winning candidate was not promoted "
            "(or its decision left no flight event)"
        )
    if not canary["rolled_back"] or canary["rollback_flight_event"] is None:
        failures.append(
            "canary gate FAILED: the SLO-breaching candidate was not "
            "rolled back (or its decision left no flight event)"
        )

    out = {
        "metric": "replicated_serving_rows_per_s_vs_latency",
        "unit": "rows/sec vs ms",
        "replicas": R,
        "slo_ms": slo,
        "block_rows": opts.block_rows,
        "batch_size": opts.batch_size,
        "service_us": opts.service_us,
        "queue_depth": opts.queue_depth,
        "seconds": opts.seconds,
        "seed": opts.seed,
        "device_free_proxy": True,
        "single": single_row,
        "routed": routed_row,
        "scaling_gate": {
            "criterion": (
                f"x{R} routed served rows/s >= {required:.2f}x the "
                f"same-session single plane at equal p99 inside the "
                f"{slo} ms SLO; every per-replica sub-row served > 0"
            ),
            "single_best_rows_per_s": (
                b1["served_rows_per_s"] if b1 else None
            ),
            "routed_best_rows_per_s": (
                bR["served_rows_per_s"] if bR else None
            ),
            "ratio": round(ratio, 3) if ratio is not None else None,
            "required": round(required, 3),
        },
        "chaos": chaos,
        "canary": canary,
        "gate": {"passed": not failures},
    }
    return out, failures


def parse_opts(argv=None) -> SimpleNamespace:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--rates", default="1000,2000,4000,8000,16000",
        help="comma list of offered rates in ROWS/s (each request is a "
        "--block_rows block). The default tops out at ~2x the default "
        "service capacity so the sweep covers both sides of the knee",
    )
    ap.add_argument(
        "--block_rows", type=int, default=8,
        help="rows per request (the block wire's natural request unit)",
    )
    ap.add_argument(
        "--batch_size", type=int, default=32,
        help="predictor coalesce target; the bucket cap is the next pow-2 "
        "(capacity = cap rows per --service_us device call)",
    )
    ap.add_argument(
        "--service_us", type=float, default=4000.0,
        help="simulated device time per call (slept at fetch) — the "
        "frontier's service-time axis on a device-free host",
    )
    ap.add_argument("--slo_ms", type=float, default=50.0)
    ap.add_argument(
        "--queue_depth", type=int, default=64,
        help="admission-queue bound in TASKS (overload beyond it is fast "
        "queue_full rejection)",
    )
    ap.add_argument("--seconds", type=float, default=4.0, help="per rate point")
    ap.add_argument("--num_actions", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--replicas", type=int, default=1,
        help="R > 1 = the ISSUE-15 replicated instrument: single AND "
        "R-replica routed frontiers same-session (routed rates = --rates "
        "x R), the near-linear scaling gate, a replica-kill chaos rep, "
        "and the canary promote/rollback e2e",
    )
    ap.add_argument(
        "--gate_frac", type=float, default=0.8,
        help="scaling gate: routed served rows/s must be >= gate_frac * R "
        "x the same-session single plane (0.8 * 4 = the 3.2x acceptance "
        "bar)",
    )
    args = ap.parse_args(argv)
    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if not rates:
        raise SystemExit("--rates must name at least one rate")
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    return SimpleNamespace(rates=rates, **{
        k: getattr(args, k)
        for k in ("block_rows", "batch_size", "service_us", "slo_ms",
                  "queue_depth", "seconds", "num_actions", "seed",
                  "replicas", "gate_frac")
    })


def main(argv=None) -> int:
    # no accelerator in the loop, ever: pin cpu BEFORE jax imports and
    # never take the TPU-claim mutex (same stance as plane_bench
    # device-free mode)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    opts = parse_opts(argv)
    if opts.replicas > 1:
        out, failures = run_replicated(opts)
    else:
        out, failures = run_frontier(opts)
    # the JSON (per-point evidence) prints BEFORE any gate verdict — the
    # evidence is most valuable exactly when the gate fails
    print(json.dumps(out))
    if failures:
        from distributed_ba3c_tpu.utils.devicelock import stderr_print

        for msg in failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
