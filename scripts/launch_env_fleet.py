#!/usr/bin/env python
"""Launch a native env-server fleet on an ACTOR host (BASELINE config #3).

The remote-actor topology: a learner runs `train.py --env zmq:<game>
--pipe_c2s tcp://0.0.0.0:C --pipe_s2c tcp://0.0.0.0:S`; each actor host runs
this script pointed at the learner. Every server process hosts up to 16
native envs stepped in lockstep (envs/native.py CppEnvServerProcess), each
env indistinguishable on the wire from a SimulatorProcess — the reference's
remote simulators spoke the same ipc/tcp pipe pair (SURVEY.md §2.12 plane 1,
expected RL/simulator.py).

No jax in this process or its children: actor hosts need only numpy + pyzmq
+ the cpp/ shared object.

Example (256 actors over 2 hosts, learner at 10.0.0.1):
  actor-host-1$ python scripts/launch_env_fleet.py --game pong --n_envs 128 \
      --c2s tcp://10.0.0.1:5555 --s2c tcp://10.0.0.1:5556 --base_idx 0
  actor-host-2$ ... --base_idx 8   (distinct idx => distinct ZMQ identities)
"""

import argparse
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--game", default="pong", help="native env name (cpp core)")
    p.add_argument("--n_envs", type=int, default=64, help="total envs on this host")
    p.add_argument("--c2s", required=True, help="learner's experience pipe, tcp://host:port")
    p.add_argument("--s2c", required=True, help="learner's action pipe, tcp://host:port")
    p.add_argument("--envs_per_proc", type=int, default=16)
    p.add_argument("--frame_history", type=int, default=4)
    p.add_argument(
        "--base_idx", type=int, default=0,
        help="first server index — MUST differ across actor hosts so ZMQ "
        "identities (cppsim-<idx>-<env> / cppsim-<idx>*block) never collide",
    )
    p.add_argument(
        "--wire", default="block", choices=["block-shm", "block", "per-env"],
        help="block = one zero-copy multipart message per server per step "
        "(docs/actor_plane.md, the tcp:// cross-host wire and the default "
        "here); block-shm = obs through a /dev/shm ring — ONLY when this "
        "fleet runs on the LEARNER's host; per-env = B msgpack messages "
        "per step (reference-compatible compat foil)",
    )
    p.add_argument(
        "--shm_ring_cap", type=int, default=None,
        help="block-shm ring capacity in steps (default: sized for ~8192 "
        "env-steps). The learner's master REFUSES rings smaller than its "
        "queue+feed buffering needs (utils/shm.py safety contract) and "
        "drops the client — size this to the learner's config when it "
        "rejects the default",
    )
    args = p.parse_args(argv)

    from distributed_ba3c_tpu.envs import native

    if not native.available():
        print("native env core not built: run `make -C cpp`", file=sys.stderr)
        return 2

    per = max(1, args.envs_per_proc)
    procs = []
    left = args.n_envs
    i = args.base_idx
    while left > 0:
        procs.append(
            native.CppEnvServerProcess(
                i,
                args.c2s,
                args.s2c,
                game=args.game,
                n_envs=min(per, left),
                frame_history=args.frame_history,
                wire=args.wire,
                shm_ring_cap=args.shm_ring_cap,
            )
        )
        left -= per
        i += 1
    for pr in procs:
        pr.start()
    print(
        f"fleet up: {args.n_envs} x {args.game} in {len(procs)} processes -> "
        f"{args.c2s} / {args.s2c}",
        flush=True,
    )

    stop = []
    rc = 0
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    try:
        while not stop:
            for pr in procs:
                if not pr.is_alive():
                    # non-zero exit so a supervisor (systemd/k8s) restarts
                    # the fleet instead of leaving the learner starved
                    print(f"server {pr.name} died; shutting fleet down", file=sys.stderr)
                    stop.append(1)
                    rc = 1
                    break
            time.sleep(1.0)
    finally:
        for pr in procs:
            pr.terminate()
        for pr in procs:
            pr.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
