#!/usr/bin/env python
"""Launch a SUPERVISED native env-server fleet on an ACTOR host.

The remote-actor topology (BASELINE config #3): a learner runs `train.py
--env zmq:<game> --pipe_c2s tcp://0.0.0.0:C --pipe_s2c tcp://0.0.0.0:S`;
each actor host runs this script pointed at the learner. Every server
process hosts up to 16 native envs stepped in lockstep (envs/native.py
CppEnvServerProcess), each env indistinguishable on the wire from a
SimulatorProcess — the reference's remote simulators spoke the same
ipc/tcp pipe pair (SURVEY.md §2.12 plane 1).

Unlike the old spawn-and-walk-away launcher, the fleet is owned by a
FleetSupervisor (docs/orchestration.md): crashed servers respawn with
exponential backoff, stale /dev/shm rings from a previous crashed fleet
are reclaimed at spawn (any cap — a leftover ring file with different
geometry no longer wedges the slot), and a crash LOOP exhausts the
restart budget and exits 1 so a host-level supervisor (systemd/k8s) can
take over — the circuit breaker turns an infinite fork storm into one
visible failure. With ``--fleet_min/--fleet_max`` plus the learner's
``--telemetry_url``, the host autoscales its fleet against the LEARNER'S
backpressure signals (``/json`` scrape endpoint, docs/observability.md).

No jax in this process or its children: actor hosts need only numpy +
pyzmq + the cpp/ shared object.

Example (256 actors over 2 hosts, learner at 10.0.0.1):
  actor-host-1$ python scripts/launch_env_fleet.py --game pong --n_envs 128 \
      --c2s tcp://10.0.0.1:5555 --s2c tcp://10.0.0.1:5556 --base_idx 0
  actor-host-2$ ... --base_idx 8   (distinct idx => distinct ZMQ identities)
"""

import argparse
import math
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--game", default="pong", help="native env name (cpp core)")
    p.add_argument("--n_envs", type=int, default=64, help="total envs on this host")
    p.add_argument("--c2s", required=True, help="learner's experience pipe, tcp://host:port")
    p.add_argument("--s2c", required=True, help="learner's action pipe, tcp://host:port")
    p.add_argument("--envs_per_proc", type=int, default=16)
    p.add_argument("--frame_history", type=int, default=4)
    p.add_argument(
        "--base_idx", type=int, default=0,
        help="first server index — MUST differ across actor hosts so ZMQ "
        "identities (cppsim-<idx>-<env> / cppsim-<idx>*block) never collide",
    )
    p.add_argument(
        "--wire", default="block", choices=["block-shm", "block", "per-env"],
        help="block = one zero-copy multipart message per server per step "
        "(docs/actor_plane.md, the tcp:// cross-host wire and the default "
        "here); block-shm = obs through a /dev/shm ring — ONLY when this "
        "fleet runs on the LEARNER's host; per-env = B msgpack messages "
        "per step (reference-compatible compat foil)",
    )
    p.add_argument(
        "--shm_ring_cap", type=int, default=None,
        help="block-shm ring capacity in steps (default: sized for ~8192 "
        "env-steps). The learner's master REFUSES rings smaller than its "
        "queue+feed buffering needs (utils/shm.py safety contract) and "
        "drops the client — size this to the learner's config when it "
        "rejects the default",
    )
    p.add_argument(
        "--fleet_spec", default=None,
        help="JSON FleetSpec file (docs/orchestration.md) — the fully "
        "declarative path; overrides every fleet-shape flag above",
    )
    p.add_argument(
        "--fleet_min", type=int, default=0,
        help="autoscaler lower bound in server processes (0 = launch size)",
    )
    p.add_argument(
        "--fleet_max", type=int, default=0,
        help="autoscaler upper bound in server processes (0 = launch "
        "size); with --telemetry_url this host grows/shrinks its fleet on "
        "the learner's backpressure signals",
    )
    p.add_argument(
        "--telemetry_url", default=None,
        help="the learner's --telemetry_port endpoint (http://host:port) "
        "— enables cross-host autoscaling between the fleet bounds",
    )
    p.add_argument(
        "--fleet_index", type=int, default=None,
        help="which of a multi-fleet learner's masters to autoscale "
        "against (--fleets N exports one registry per fleet as "
        "master.f<k> — the per-fleet scrape label); default: the "
        "single-fleet 'master' registry. This host's servers must also "
        "connect to THAT fleet's derived pipe pair (docs/OPERATIONS.md)",
    )
    p.add_argument("--autoscale_interval", type=float, default=2.0)
    p.add_argument(
        "--restart_budget", type=int, default=16,
        help="respawns tolerated per 5-minute window before the circuit "
        "opens and this launcher exits 1 (host-level supervisor's turn)",
    )
    args = p.parse_args(argv)

    from distributed_ba3c_tpu.envs import native

    if not native.available():
        print("native env core not built: run `make -C cpp`", file=sys.stderr)
        return 2

    from distributed_ba3c_tpu.orchestrate import (
        Autoscaler,
        FleetSpec,
        FleetSupervisor,
        default_factory,
        http_signals,
    )

    try:
        if args.fleet_spec:
            spec = FleetSpec.load(args.fleet_spec)
            total_envs = spec.fleet_size * spec.envs_per_server
        else:
            per = max(1, args.envs_per_proc)
            n_servers = max(1, math.ceil(args.n_envs / per))
            lo = args.fleet_min or n_servers
            hi = args.fleet_max or n_servers
            if not lo <= n_servers <= hi:
                raise ValueError(
                    f"launch fleet size {n_servers} servers "
                    f"({args.n_envs} envs / {per} per proc) is outside "
                    f"[--fleet_min {lo}, --fleet_max {hi}] — size --n_envs "
                    "inside the bounds"
                )
            spec = FleetSpec(
                pipe_c2s=args.c2s,
                pipe_s2c=args.s2c,
                game=args.game,
                envs_per_server=per,
                frame_history=args.frame_history,
                wire=args.wire,
                shm_ring_cap=args.shm_ring_cap,
                base_idx=args.base_idx,
                fleet_size=n_servers,
                fleet_min=lo,
                fleet_max=hi,
                restart_budget=args.restart_budget,
            )
            total_envs = args.n_envs
    except (OSError, ValueError) as e:
        # a misconfigured fleet (bad bounds, typoed spec field, missing
        # spec file) is a usage error, not a traceback
        print(f"fleet spec error: {e}", file=sys.stderr)
        return 2
    supervisor = FleetSupervisor(
        spec, factory=default_factory(spec, total_envs=total_envs)
    )
    scaler = None
    if spec.fleet_max > spec.fleet_min:
        if not args.telemetry_url:
            print(
                "--fleet_min/--fleet_max without --telemetry_url: an actor "
                "host has no master in-process — autoscaling needs the "
                "learner's /json endpoint",
                file=sys.stderr,
            )
            return 2
        scaler = Autoscaler(
            supervisor,
            http_signals(args.telemetry_url, fleet=args.fleet_index),
            interval_s=args.autoscale_interval,
        )

    supervisor.start()
    if scaler is not None:
        scaler.start()
    print(
        f"fleet up: {total_envs} x {spec.game} in {supervisor.target} "
        f"supervised processes -> {spec.pipe_c2s} / {spec.pipe_s2c}",
        flush=True,
    )

    from distributed_ba3c_tpu import telemetry

    deaths = telemetry.registry("orchestrator").counter("server_deaths_total")
    stop = []
    rc = 0
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    try:
        while not stop:
            # --restart_budget 0 keeps the circuit permanently open (no
            # respawns — the pre-supervisor contract): exit only once a
            # server has actually died, not at launch
            if supervisor.circuit_open and (
                spec.restart_budget > 0 or deaths.value() > 0
            ):
                # the fleet is crash-looping beyond its budget: one loud
                # exit (evidence already dumped by the breaker) instead of
                # a starved learner behind a quietly-respawning launcher
                print(
                    "respawn circuit open — fleet degraded beyond its "
                    "restart budget; exiting for the host supervisor",
                    file=sys.stderr,
                )
                rc = 1
                break
            time.sleep(1.0)
    finally:
        if scaler is not None:
            scaler.stop()
            scaler.join(timeout=5)
        supervisor.stop()
        supervisor.join(timeout=5)
        supervisor.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
