"""Standalone greedy evaluation of a fused-trainer checkpoint.

Usage:
    python scripts/eval_fused.py --env jax:pong \
        --load runs/pong_northstar/checkpoints [--step N] \
        --nr_eval 32 --max_steps 20000

Loads the TrainState from orbax, runs the on-device greedy Evaluator with a
horizon long enough for full episodes, prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from distributed_ba3c_tpu.train.eval_tools import make_checkpoint_evaluator


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--env", default="jax:pong")
    ap.add_argument("--load", required=True)
    ap.add_argument("--step", type=int, default=None)
    ap.add_argument("--best", action="store_true", help="use the best-marked step")
    ap.add_argument("--nr_eval", type=int, default=32)
    ap.add_argument("--max_steps", type=int, default=20000)
    ap.add_argument("--fc_units", type=int, default=512)
    ap.add_argument("--tpu_lock", default="wait", choices=["wait", "fail", "off"])
    args = ap.parse_args()

    from distributed_ba3c_tpu.utils.devicelock import guard_tpu

    _lock = guard_tpu("eval_fused", mode=args.tpu_lock)  # noqa: F841

    mgr, target, evaluate, _ = make_checkpoint_evaluator(
        args.env, args.load, args.nr_eval, args.max_steps, args.fc_units
    )
    step = args.step
    if args.best and step is None:
        step = mgr.best_step
        if step is None:
            raise SystemExit(
                "--best: no best-marked checkpoint in this run "
                "(eval never improved); pass --step or drop --best"
            )
    state = mgr.restore(target, step)

    mean, mx, n = evaluate(state.params, 123)
    print(
        json.dumps(
            {
                "env": args.env,
                "ckpt_step": int(state.step),
                # n==0: no episode finished inside the horizon — 0.0/-inf
                # would masquerade as scores (and -Infinity is invalid JSON)
                "eval_mean_score": round(mean, 3) if n > 0 else None,
                "eval_max_score": round(mx, 3) if n > 0 else None,
                "episodes": n,
                "max_steps": args.max_steps,
            }
        )
    )
    if n == 0:
        raise SystemExit(
            "no episode completed within --max_steps; raise the horizon"
        )


if __name__ == "__main__":
    main()
