"""Standalone greedy evaluation of a fused-trainer checkpoint.

Usage:
    python scripts/eval_fused.py --env jax:pong \
        --load runs/pong_northstar/checkpoints [--step N] \
        --nr_eval 32 --max_steps 20000

Loads the TrainState from orbax, runs the on-device greedy Evaluator with a
horizon long enough for full episodes, prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs import jaxenv
from distributed_ba3c_tpu.fused.loop import make_greedy_eval
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh
from distributed_ba3c_tpu.parallel.train_step import create_train_state
from distributed_ba3c_tpu.train.checkpoint import CheckpointManager


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--env", default="jax:pong")
    ap.add_argument("--load", required=True)
    ap.add_argument("--step", type=int, default=None)
    ap.add_argument("--best", action="store_true", help="use the best-marked step")
    ap.add_argument("--nr_eval", type=int, default=32)
    ap.add_argument("--max_steps", type=int, default=20000)
    ap.add_argument("--fc_units", type=int, default=512)
    args = ap.parse_args()

    env = jaxenv.get_env(args.env.split(":", 1)[1])
    cfg = BA3CConfig(num_actions=env.num_actions, fc_units=args.fc_units)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    target = create_train_state(jax.random.PRNGKey(0), model, cfg, opt)

    mgr = CheckpointManager(args.load)
    step = args.step
    if args.best and step is None:
        step = mgr.best_step
        if step is None:
            raise SystemExit(
                "--best: no best-marked checkpoint in this run "
                "(eval never improved); pass --step or drop --best"
            )
    state = mgr.restore(jax.device_get(target), step)

    mesh = make_mesh()
    n_data = mesh.shape["data"]
    n_eval = max(n_data, (args.nr_eval + n_data - 1) // n_data * n_data)
    evaluate = make_greedy_eval(
        model, cfg, mesh, env, n_eval, max_steps=args.max_steps
    )
    mean, mx, n = evaluate(state.params, 123)
    print(
        json.dumps(
            {
                "env": args.env,
                "ckpt_step": int(state.step),
                # n==0: no episode finished inside the horizon — 0.0/-inf
                # would masquerade as scores (and -Infinity is invalid JSON)
                "eval_mean_score": round(mean, 3) if n > 0 else None,
                "eval_max_score": round(mx, 3) if n > 0 else None,
                "episodes": n,
                "max_steps": args.max_steps,
            }
        )
    )
    if n == 0:
        raise SystemExit(
            "no episode completed within --max_steps; raise the horizon"
        )


if __name__ == "__main__":
    main()
