#!/usr/bin/env python
"""Pod acceptance gate: aggregate scaling, the staleness curve, host loss.

Exercises the whole pod parameter plane (docs/pod.md) device-free on
localhost tcp and prints ONE JSON line (the repo's bench-tooling
contract, like chaos_bench/plane_bench):

1. **aggregate**: for each host count in ``--hosts``, a real pod — N
   supervised ``pod.host`` processes (fake envs, equal per-host shape)
   against one bounded-staleness learner — measured as env-steps/s
   ARRIVING at the learner's ingest. GATE: 2 hosts must aggregate
   >= ``--gate`` (default 1.6x) the single-host rate measured in the
   same session. This is the scaling story the reference paper's 64-node
   PS cluster hand-tended, run by the orchestrator.
2. **staleness curve**: the measurement the paper never published —
   LaggedBlockDriver rollouts at measured lag k (jax pong, device-free)
   for each ``--lags`` entry, reporting mean ``value_lag_mae``, mean
   rho, and the ``params_lag`` histogram; plus a ``--max_staleness``
   rejection demo showing the typed counter engage while the consuming
   loop keeps draining.
3. **host-kill chaos rep**: with 2 hosts live, SIGKILL one host's whole
   process GROUP mid-run. The learner must keep training on the
   survivor (no learner restart — ``learner_restarts_total`` stays 0),
   the supervisor must respawn the host, and its rejoined cache must
   catch back up to the current params version.

Evidence prints BEFORE the verdict; exit 1 if any gate fails. The
committed full-shape capture is ``runs/pod_bench_r12.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _free_port_base() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"tcp://127.0.0.1:{port}", f"tcp://127.0.0.1:{port + 1}"


def _cfg(args):
    from distributed_ba3c_tpu.config import BA3CConfig

    return BA3CConfig(
        image_size=(args.image_size, args.image_size),
        frame_history=4,
        num_actions=4,
        fc_units=args.fc_units,
        local_time_max=args.unroll_len,
        predict_batch_size=16,
    )


def _phase_aggregate(args, n_hosts: int) -> dict:
    """One pod at ``n_hosts`` actor hosts; env-steps/s at the ingest."""
    from bench import stall_attribution
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate.pod import (
        PodLearnerPlane,
        PodSupervisor,
        host_argv,
    )

    telemetry.reset_all()
    c2s, s2c = _free_port_base()
    plane = PodLearnerPlane(
        _cfg(args), c2s, s2c, max_staleness=args.max_staleness or None
    )
    plane.start()
    sup = PodSupervisor(
        n_hosts,
        lambda i: host_argv(
            i, c2s, s2c, env="fake", n_sims=args.sims_per_host,
            unroll_len=args.unroll_len,
            segments_per_block=args.segments_per_block,
            image_size=args.image_size, frame_history=4, num_actions=4,
            fc_units=args.fc_units,
        ),
        backoff_base_s=0.25,
    )
    sup.start()
    reg = telemetry.registry("learner")
    c_steps = reg.counter("pod_ingest_env_steps_total")
    c_blocks = reg.counter("pod_ingest_blocks_total")
    try:
        # warmup: every host reported at least one block (startup includes
        # a jax import + predictor bucket warmup per host)
        deadline = time.monotonic() + args.warmup_timeout
        while time.monotonic() < deadline:
            plane.step_once(timeout=0.2)
            if c_blocks.value() >= 2 * n_hosts and len(
                [r for r in telemetry.all_registries()
                 if r.startswith("pod.host")]
            ) >= n_hosts:
                break
        else:
            raise RuntimeError(
                f"pod produced no warmup blocks from {n_hosts} hosts — "
                f"{stall_attribution()}"
            )
        window_rates = []
        for _ in range(max(1, args.windows)):
            n0, t0 = c_steps.value(), time.perf_counter()
            wdeadline = t0 + args.seconds
            while time.perf_counter() < wdeadline:
                plane.step_once(timeout=0.05)
            dt = time.perf_counter() - t0
            window_rates.append(round((c_steps.value() - n0) / dt, 1))
        hosts_reporting = sorted(
            r for r in telemetry.all_registries() if r.startswith("pod.host")
        )
        return {
            "hosts": n_hosts,
            "rate": max(window_rates),  # best window: scheduler-noise filter
            "window_rates": window_rates,
            "updates": int(plane.learner.version),
            "ingest_blocks": int(c_blocks.value()),
            "ingest_dropped": int(
                reg.counter("pod_ingest_dropped_total").value()
            ),
            "stale_rejected": int(
                reg.counter("stale_blocks_rejected_total").value()
            ),
            "hosts_reporting": hosts_reporting,
        }
    finally:
        sup.stop()
        sup.join(timeout=5)
        sup.close()
        plane.close()


def _phase_staleness_curve(args) -> dict:
    """value_lag_mae / params_lag at measured lag k, device-free (pong)."""
    import jax

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh
    from distributed_ba3c_tpu.parallel.train_step import create_train_state
    from distributed_ba3c_tpu.pod.learner import (
        LaggedBlockDriver,
        PodLearner,
        make_pod_learner_step,
    )

    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=args.fc_units)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    mesh = make_mesh(num_data=1, devices=jax.devices()[:1])
    ostep = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=args.unroll_len
    )
    pstep = make_pod_learner_step(model, opt, cfg, mesh)
    n_envs = 2

    curve = []
    for lag in args.lags:
        telemetry.reset_all()
        learner = PodLearner(
            pstep, create_train_state(jax.random.PRNGKey(0), model, cfg, opt),
            cfg,
        )
        learner.learning_rate = args.curve_lr
        drv = LaggedBlockDriver(ostep, learner, lag=lag)
        drv.prime(
            ostep.put(
                create_fused_state(
                    jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                    n_shards=1,
                )
            )
        )
        maes, rhos = [], []
        for _ in range(args.lag_iters):
            m = drv.iterate()
            maes.append(float(m["value_lag_mae"]))
            rhos.append(float(m["mean_rho"]))
        post_ramp = maes[lag:] or maes
        hist = telemetry.registry("learner").histogram(
            "params_lag", unit=1
        ).collect()
        curve.append({
            "lag": lag,
            "value_lag_mae_mean": round(sum(post_ramp) / len(post_ramp), 6),
            "mean_rho": round(sum(rhos) / len(rhos), 6),
            "params_lag_hist": {
                "count": hist["count"],
                "sum": hist["sum"],
                "buckets": hist["buckets"][:8],
            },
            "iters": args.lag_iters,
        })

    # the bound engaging: lag 2x the bound, rejections counted, loop drains
    telemetry.reset_all()
    bound = max(1, args.max_staleness or 2)
    learner = PodLearner(
        pstep, create_train_state(jax.random.PRNGKey(0), model, cfg, opt),
        cfg, max_staleness=bound,
    )
    drv = LaggedBlockDriver(ostep, learner, lag=2 * bound)
    drv.prime(
        ostep.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=1,
            )
        )
    )
    consumed = rejected = 0
    # the driver's snapshot ring takes 2*bound iterations to ramp past
    # the bound — size the demo to ITS lag, not the curve's iter count,
    # or a small --lag_iters never reaches a rejectable staleness
    for _ in range(max(args.lag_iters, 2 * bound + 6)):
        if drv.iterate() is None:
            rejected += 1
        else:
            consumed += 1
    return {
        "curve": curve,
        "rejection_demo": {
            "bound": bound,
            "driver_lag": 2 * bound,
            "consumed": consumed,
            "rejected": rejected,
            "stale_blocks_rejected_total": int(
                telemetry.registry("learner")
                .counter("stale_blocks_rejected_total").value()
            ),
        },
    }


def _phase_host_kill(args) -> dict:
    """SIGKILL one of two hosts mid-run; recovery without learner restart."""
    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate.pod import (
        PodLearnerPlane,
        PodSupervisor,
        host_argv,
    )

    telemetry.reset_all()
    c2s, s2c = _free_port_base()
    plane = PodLearnerPlane(_cfg(args), c2s, s2c, max_staleness=None)
    plane.start()
    sup = PodSupervisor(
        2,
        lambda i: host_argv(
            i, c2s, s2c, env="fake", n_sims=args.sims_per_host,
            unroll_len=args.unroll_len,
            segments_per_block=args.segments_per_block,
            image_size=args.image_size, frame_history=4, num_actions=4,
            fc_units=args.fc_units,
        ),
        backoff_base_s=0.25,
    )
    sup.start()
    out = {"recovered": False}
    try:
        def train_until(n, timeout):
            deadline = time.monotonic() + timeout
            while plane.learner.version < n and time.monotonic() < deadline:
                plane.step_once(timeout=0.5)
            return plane.learner.version >= n

        if not train_until(5, args.warmup_timeout):
            out["error"] = "pod never reached 5 updates before the kill"
            return out
        v_kill = plane.learner.version
        out["killed_at_version"] = v_kill
        assert sup.sigkill_slot(0)
        out["survivor_progress"] = train_until(v_kill + 5, 120)
        # respawn + rejoin: the killed host's mirrored params_version must
        # catch up to the post-kill publish frontier
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            plane.step_once(timeout=0.5)
            g = telemetry.registry("pod.host0").scalars()
            if g.get("params_version", -1) >= v_kill:
                out["rejoined_at_version"] = g["params_version"]
                break
        out["respawns"] = int(
            telemetry.registry("orchestrator")
            .counter("server_respawns_total").value()
        )
        out["learner_restarts"] = int(
            telemetry.registry("orchestrator")
            .counter("learner_restarts_total").value()
        )
        out["final_version"] = int(plane.learner.version)
        out["recovered"] = bool(
            out.get("survivor_progress")
            and "rejoined_at_version" in out
            and out["respawns"] >= 1
            and out["learner_restarts"] == 0
        )
        return out
    finally:
        sup.stop()
        sup.join(timeout=5)
        sup.close()
        plane.close()


def _phase_net(args) -> dict:
    """The emulated-DCN rows (ISSUE 13 / ROADMAP item 2a): the same pod,
    measured through netchaos proxies — a quiet-proxy control, one row
    per (RTT, loss) point, the partition-and-heal rep, the live
    corruption rep against CRC-armed codecs, and a seed-replay verdict
    on every rep (docs/netchaos.md). Committed capture:
    ``runs/netchaos_bench_r14.json``."""
    from distributed_ba3c_tpu.netchaos.bench import (
        NetShape,
        dcn_schedule,
        quiet_schedule,
        run_corrupt_rep,
        run_partition_rep,
        run_throughput_rep,
    )
    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    shape = NetShape(
        hosts=args.net_hosts,
        sims_per_host=args.sims_per_host,
        segments_per_block=args.segments_per_block,
        unroll_len=args.unroll_len,
        image_size=args.image_size,
        fc_units=args.fc_units,
        max_staleness=args.max_staleness,
        warmup_timeout=args.warmup_timeout,
    )
    clean = run_throughput_rep(
        shape, quiet_schedule(args.net_seed), args.seconds, args.windows
    )
    stderr_print(f"net clean (quiet proxies): {clean['rate']:>9.1f} env-steps/s")
    rows = []
    for spec in str(args.net_points).split(","):
        if not spec:
            continue
        rtt_s, loss_s = spec.split(":")
        rtt, loss = float(rtt_s), float(loss_s)
        r = run_throughput_rep(
            shape, dcn_schedule(rtt, loss, seed=args.net_seed),
            args.seconds, args.windows,
        )
        row = {
            "rtt_ms": rtt,
            "loss": loss,
            "rate": r["rate"],
            "window_rates": r["window_rates"],
            "over_clean": round(r["rate"] / max(clean["rate"], 1e-9), 4),
            "updates": r["updates"],
            "injected": r["injected"],
            "replay_match": r["replay"]["match"],
            "schedule": r["schedule"],
        }
        rows.append(row)
        stderr_print(
            f"net DCN {rtt:>5.0f}ms RTT / {100 * loss:4.1f}% loss: "
            f"{r['rate']:>9.1f} env-steps/s ({row['over_clean']:.3f}x clean, "
            f"replay {'ok' if row['replay_match'] else 'MISMATCH'})"
        )
    # a 10 s window outlasts the emulated wire's + the kernel's buffering
    # at this block rate, so the host's OWN bounds (SNDHWM -> spill ->
    # ship_backpressure_total) are what the artifact shows engaging
    partition = run_partition_rep(shape, args.net_seed, partition_s=10.0)
    stderr_print(
        f"net partition-and-heal: pre {partition['pre']['rate']:.1f} -> "
        f"partition {partition['partition']['rate']:.1f} -> heal "
        f"{partition['heal']['rate']:.1f} env-steps/s, rejoined at "
        f"v{partition['rejoined_at_version']}, learner restarts "
        f"{partition['learner_restarts']}, backpressure "
        f"{partition['ship_backpressure']}, recovered "
        f"{partition['recovered']}"
    )
    corrupt = run_corrupt_rep(shape, args.net_seed)
    stderr_print(
        f"net corruption: {corrupt['injected_mangled']} frames mangled -> "
        f"{corrupt['typed_rejects']} typed rejects, training continued "
        f"({corrupt['blocks']} blocks)"
    )
    gate_row = next(
        (
            r for r in rows
            if r["rtt_ms"] == args.net_rtt_ms and r["loss"] == args.net_loss
        ),
        None,
    )
    return {
        "clean": clean,
        "rows": rows,
        "gate_point": {"rtt_ms": args.net_rtt_ms, "loss": args.net_loss},
        "gate": args.net_gate,
        "gate_row_over_clean": gate_row["over_clean"] if gate_row else None,
        # the gate applies to the NAMED point only — verdicting a milder
        # row while the artifact claims 50ms/1% would be a silent lie, so
        # a sweep that omits the gate point FAILS with the reason named
        "gate_error": (
            None if gate_row else
            f"gate point {args.net_rtt_ms}:{args.net_loss} not in "
            f"--net_points {args.net_points!r}"
        ),
        "gate_passed": bool(
            gate_row and gate_row["over_clean"] >= args.net_gate
        ),
        "partition": partition,
        "corrupt": corrupt,
        "replay_ok": bool(
            clean["replay"]["match"]
            and all(r["replay_match"] for r in rows)
            and partition["replay"]["match"]
            and corrupt["replay"]["match"]
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hosts", default="1,2", help="comma-separated host counts for the aggregate phase (equal per-host shape)")
    ap.add_argument("--sims_per_host", type=int, default=4)
    ap.add_argument("--segments_per_block", type=int, default=16)
    ap.add_argument("--unroll_len", type=int, default=5)
    ap.add_argument("--image_size", type=int, default=16)
    ap.add_argument("--fc_units", type=int, default=16)
    ap.add_argument("--seconds", type=float, default=10.0, help="seconds per measurement window")
    ap.add_argument("--windows", type=int, default=3, help="windows per host count; best window is the rate (scheduler-noise filter)")
    ap.add_argument("--gate", type=float, default=1.6, help="2-host aggregate must be >= gate x single-host")
    ap.add_argument("--max_staleness", type=int, default=8)
    ap.add_argument("--lags", default="0,1,2,4,8", help="measured-lag points of the staleness curve")
    ap.add_argument("--lag_iters", type=int, default=24)
    ap.add_argument("--curve_lr", type=float, default=1e-2, help="curve-phase LR (large enough that lag shows in value drift)")
    ap.add_argument("--warmup_timeout", type=float, default=240.0)
    ap.add_argument("--skip_curve", action="store_true")
    ap.add_argument("--skip_chaos", action="store_true")
    ap.add_argument(
        "--net", action="store_true",
        help="add the netchaos emulated-DCN phase (docs/netchaos.md): "
        "per-(RTT, loss) throughput rows through real proxy pumps, the "
        "partition-and-heal rep, the CRC corruption rep, seed-replay "
        "verdicts — the rows ROADMAP item 2a owed",
    )
    ap.add_argument(
        "--net_only", action="store_true",
        help="run ONLY the netchaos phase (skips aggregate/curve/chaos)",
    )
    ap.add_argument("--net_hosts", type=int, default=1, help="pod hosts in the netchaos phase")
    ap.add_argument("--net_points", default="10:0.001,50:0.01,100:0.02", help="comma-separated rtt_ms:loss rows")
    ap.add_argument("--net_rtt_ms", type=float, default=50.0, help="the (rtt, loss) row the gate applies to")
    ap.add_argument("--net_loss", type=float, default=0.01)
    ap.add_argument("--net_gate", type=float, default=0.85)
    ap.add_argument("--net_seed", type=int, default=0)
    args = ap.parse_args()
    args.lags = [int(x) for x in str(args.lags).split(",") if x != ""]
    host_counts = [int(x) for x in str(args.hosts).split(",") if x != ""]

    from distributed_ba3c_tpu.utils.devicelock import stderr_print

    failures = []
    net = None
    if args.net or args.net_only:
        net = _phase_net(args)
        if not net["gate_passed"]:
            failures.append(
                net["gate_error"]
                or f"netchaos DCN gate FAILED: {net['gate_row_over_clean']}x"
                f" clean at {args.net_rtt_ms:.0f}ms/{args.net_loss:.3f} "
                f"(gate >= {args.net_gate})"
            )
        if not net["partition"]["recovered"]:
            failures.append(
                f"netchaos partition-and-heal FAILED: {net['partition']}"
            )
        if not net["corrupt"]["all_typed"]:
            failures.append(
                f"netchaos corruption rep FAILED (untyped or zero rejects): "
                f"{net['corrupt']}"
            )
        if not net["replay_ok"]:
            failures.append(
                "netchaos seed-replay mismatch (rep not reproducible)"
            )
        if args.net_only:
            out = {
                "metric": "netchaos_pod_dcn_over_clean",
                "value": net["gate_row_over_clean"],
                "unit": "ratio (degraded/clean ingest env-steps/s)",
                "hosts": args.net_hosts,
                "sims_per_host": args.sims_per_host,
                "segments_per_block": args.segments_per_block,
                "unroll_len": args.unroll_len,
                "image_size": args.image_size,
                "fc_units": args.fc_units,
                "seconds": args.seconds,
                "windows": args.windows,
                "max_staleness": args.max_staleness,
                "net": net,
            }
            print(json.dumps(out))
            if failures:
                for msg in failures:
                    stderr_print(msg)
                return 1
            return 0

    aggregate = []
    for n in host_counts:
        r = _phase_aggregate(args, n)
        aggregate.append(r)
        stderr_print(
            f"aggregate {n} host(s): {r['rate']:>9.1f} env-steps/s "
            f"({r['updates']} updates, {r['ingest_blocks']} blocks, "
            f"{r['ingest_dropped']} dropped)"
        )
    by_hosts = {r["hosts"]: r["rate"] for r in aggregate}
    scaling = None
    if 1 in by_hosts and 2 in by_hosts:
        scaling = round(by_hosts[2] / max(by_hosts[1], 1e-9), 4)
        if scaling < args.gate:
            failures.append(
                f"aggregate scaling gate FAILED: 2-host rate {by_hosts[2]:.1f}"
                f" is {scaling:.2f}x the single-host {by_hosts[1]:.1f} "
                f"(gate: >= {args.gate}x at equal per-host shape)"
            )

    curve = None
    if not args.skip_curve:
        curve = _phase_staleness_curve(args)
        for p in curve["curve"]:
            stderr_print(
                f"staleness lag {p['lag']}: value_lag_mae "
                f"{p['value_lag_mae_mean']:.5f}, mean_rho {p['mean_rho']:.4f}"
            )
        rd = curve["rejection_demo"]
        stderr_print(
            f"rejection demo: bound {rd['bound']}, driver lag "
            f"{rd['driver_lag']} -> {rd['rejected']} rejected / "
            f"{rd['consumed']} consumed (loop kept draining)"
        )
        if rd["rejected"] < 1:
            failures.append(
                "staleness bound never rejected a block in the demo"
            )
        lag0 = next((p for p in curve["curve"] if p["lag"] == 0), None)
        lag_hi = curve["curve"][-1]
        # inversion check needs the lag-0 anchor; a --lags without 0 still
        # gets its points measured and printed, just not this verdict
        if (
            lag0 is not None
            and lag_hi["value_lag_mae_mean"] < lag0["value_lag_mae_mean"]
        ):
            failures.append(
                "staleness curve inverted: value_lag_mae at the highest "
                "lag is below lag 0"
            )

    chaos = None
    if not args.skip_chaos:
        chaos = _phase_host_kill(args)
        stderr_print(
            f"host-kill: killed at v{chaos.get('killed_at_version')}, "
            f"survivor progress {chaos.get('survivor_progress')}, "
            f"rejoined at v{chaos.get('rejoined_at_version')}, "
            f"respawns {chaos.get('respawns')}, learner restarts "
            f"{chaos.get('learner_restarts')}"
        )
        if not chaos["recovered"]:
            failures.append(
                f"host-loss chaos rep FAILED to recover without a learner "
                f"restart: {chaos}"
            )

    out = {
        "metric": "pod_aggregate_env_steps_per_sec",
        "value": by_hosts.get(max(host_counts), None),
        "unit": "env-steps/sec (learner-ingest aggregate)",
        "hosts": host_counts,
        "aggregate": aggregate,
        "scaling_2_over_1": scaling,
        "gate": args.gate,
        "gate_passed": scaling is None or scaling >= args.gate,
        "sims_per_host": args.sims_per_host,
        "segments_per_block": args.segments_per_block,
        "unroll_len": args.unroll_len,
        "image_size": args.image_size,
        "fc_units": args.fc_units,
        "seconds": args.seconds,
        "windows": args.windows,
        "max_staleness": args.max_staleness,
        "staleness": curve,
        "host_kill": chaos,
        "net": net,
    }
    # evidence prints BEFORE the verdict (plane_bench/chaos_bench precedent)
    print(json.dumps(out))
    if failures:
        for msg in failures:
            stderr_print(msg)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
