"""Shared scaffolding for the ba3clint / ba3cflow / ba3cwire analyzer family.

Three analyzers, one surface contract: per-line ``# <tool>: disable=RULE``
suppression comments, a ``--check-suppressions`` audit that reports dead
suppressions as S001 findings, SARIF/JSON emission, and the 0/1/2 exit
status scripts/check.sh and the CI jobs gate on. This module is the single
implementation of that shared plumbing; the analyzers own only their rules
and their project models.

Import direction: the analyzers import from here, never the reverse.
:class:`Finding` lives here too (it is what ``stale_suppressions`` emits),
and is re-exported from ``tools.ba3clint.engine`` — the historical home
every rule module and test imports it from.
"""

from __future__ import annotations

import dataclasses
import io
import json
import re
import sys
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, \
    Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------------
# suppression comments
# --------------------------------------------------------------------------

_SUPPRESS_RE_CACHE: Dict[str, "re.Pattern[str]"] = {}


def suppress_re(tool: str) -> "re.Pattern[str]":
    pat = _SUPPRESS_RE_CACHE.get(tool)
    if pat is None:
        pat = re.compile(
            r"#\s*" + re.escape(tool) + r":\s*disable=([A-Za-z0-9_*,\s-]+)")
        _SUPPRESS_RE_CACHE[tool] = pat
    return pat


def suppressions(source: str, tool: str = "ba3clint") -> Dict[int, Set[str]]:
    """Map line number -> suppressed rule ids (``ALL`` disables every rule).

    A trailing comment suppresses its own line; a standalone comment line
    suppresses the following line as well (for statements too long to carry
    the comment inline). ``tool`` selects the comment spelling — ba3cflow
    and ba3cwire reuse this parser with their own tool names.
    """
    pat = suppress_re(tool)
    out: Dict[int, Set[str]] = {}
    for i, text, standalone in comment_tokens(source):
        m = pat.search(text)
        if not m:
            continue
        rules = {
            r.strip().upper()
            for r in m.group(1).replace(";", ",").split(",")
            if r.strip()
        }
        out.setdefault(i, set()).update(rules)
        if standalone:
            out.setdefault(i + 1, set()).update(rules)
    return out


def comment_tokens(source: str) -> Iterator[Tuple[int, str, bool]]:
    """(line, comment text, is-standalone) for each REAL comment.

    Tokenizing (rather than regex over raw lines) keeps ``disable=`` text
    inside string literals — docstrings documenting the suppression syntax —
    from acting as, or being audited as, a live suppression.
    """
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        # unparseable tail: fall back to the raw-line scan so a suppression
        # above the damage still works
        for i, line in enumerate(source.splitlines(), start=1):
            if "#" in line:
                yield i, line[line.index("#"):], line.lstrip().startswith("#")
        return
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            yield tok.start[0], tok.string, tok.line.lstrip().startswith("#")


def stale_suppressions(source: str, path: str, raw: Sequence[Finding],
                       tool: str) -> List[Finding]:
    """Suppression comments in ``source`` that no longer mask any finding.

    ``raw`` must be the UNSUPPRESSED findings for this file. Each rule id in
    a ``disable=`` list is checked independently: disabling A6,A12 when only
    A6 still fires reports A12 as stale. Stale suppressions are findings in
    their own right (rule ``S001``) — a dead suppression is a claim about an
    invariant the code no longer exercises, which misleads the next reader.
    """
    pat = suppress_re(tool)
    by_line: Dict[int, Set[str]] = {}
    for f in raw:
        by_line.setdefault(f.line, set()).add(f.rule.upper())
    out: List[Finding] = []
    for i, text, standalone in comment_tokens(source):
        m = pat.search(text)
        if not m:
            continue
        covered = {i}
        if standalone:
            covered.add(i + 1)
        fired: Set[str] = set()
        for ln in covered:
            fired |= by_line.get(ln, set())
        rules = [r.strip().upper()
                 for r in m.group(1).replace(";", ",").split(",")
                 if r.strip()]
        for rid in rules:
            used = bool(fired) if rid == "ALL" else rid in fired
            if not used:
                out.append(Finding(
                    path, i, 0, "S001",
                    f"stale suppression: {tool}: disable={rid} masks no "
                    f"finding on this line"))
    return out


# --------------------------------------------------------------------------
# CLI plumbing (exit status: 0 = clean, 1 = findings, 2 = bad usage)
# --------------------------------------------------------------------------


def print_rule_catalog(rules: Iterable) -> None:
    for r in rules:
        print(f"{r.id:4s} {r.name:32s} {r.summary}")


def narrow_rules(rules: Sequence, select: str) -> Optional[List]:
    """Apply ``--select``; None (after an stderr diagnostic) on unknown ids."""
    wanted = {s.strip().upper() for s in select.split(",") if s.strip()}
    unknown = wanted - {r.id for r in rules}
    if unknown:
        print(
            f"unknown rule id(s): {', '.join(sorted(unknown))}",
            file=sys.stderr,
        )
        return None
    return [r for r in rules if r.id in wanted]


def emit_findings(findings: Sequence[Finding], tool: str, rules: Iterable,
                  as_json: bool, sarif: Optional[str]) -> int:
    """SARIF side-channel + stdout report; returns the process exit status."""
    if sarif:
        from tools.sarif import write_sarif
        write_sarif(sarif, findings, tool, rules)
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] {f.message}")
        n = len(findings)
        print(f"{tool}: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0
