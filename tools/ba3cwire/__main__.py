"""CLI: ``python -m tools.ba3cwire [paths...]``.

Exit status: 0 = clean, 1 = findings, 2 = bad usage — same contract as
ba3clint/ba3cflow, so scripts/check.sh and the CI ``wire`` job gate on it
directly.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from tools.analyzer_core import emit_findings, narrow_rules, \
    print_rule_catalog, stale_suppressions
from tools.ba3cwire import all_rules
from tools.ba3cwire.engine import build_context, filter_suppressed, run_rules

DEFAULT_PATHS = ["distributed_ba3c_tpu", "tools"]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ba3cwire",
        description="Wire-protocol/failure-path conformance analysis for "
        "the BA3C stack (rule catalog: docs/static_analysis.md).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=DEFAULT_PATHS,
        help="files or directories to analyze "
        f"(default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit findings as JSON instead of human-readable lines",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="also write findings as SARIF 2.1.0 to FILE",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--check-suppressions",
        action="store_true",
        help="flag '# ba3cwire: disable=' comments that mask no finding",
    )
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        print_rule_catalog(rules)
        return 0
    if args.select:
        rules = narrow_rules(rules, args.select)
        if rules is None:
            return 2

    try:
        ctx = build_context(args.paths)
    except FileNotFoundError as e:
        print(f"ba3cwire: {e}", file=sys.stderr)
        return 2
    raw = run_rules(ctx, rules)

    if args.check_suppressions:
        findings = []
        for path, mod in sorted(ctx.project.by_path.items()):
            per_file = [f for f in raw if f.path == path]
            findings.extend(
                stale_suppressions(mod.source, path, per_file, "ba3cwire"))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    else:
        findings = filter_suppressed(ctx, raw)

    return emit_findings(findings, "ba3cwire", rules,
                         as_json=args.json, sarif=args.sarif)


if __name__ == "__main__":
    sys.exit(main())
