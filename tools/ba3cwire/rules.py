"""ba3cwire rules W1-W6: wire-protocol and failure-path conformance.

Each rule is a class with ``id``/``name``/``summary`` and a ``check(ctx)``
generator over a :class:`~tools.ba3cwire.engine.WireContext`. The catalog
(docs/static_analysis.md) is the contract; fixtures under
tests/lint_fixtures/wire/ pin each rule to a flagged/clean pair plus the
two historical replays (PR 14's receive-loop kill, PR 5's sign-mixed
counter).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.ba3clint.engine import Finding, dotted_name
from tools.ba3cwire.model import (
    HeaderAnalysis,
    first_positional_param,
    first_recv_line,
    handler_catches_decode,
    handler_reraises,
    is_codec_module,
    loop_protected_ids,
    max_positional_index,
    packer_frame_count,
    recv_loops,
    sign_guarded,
    walk_scope,
    walk_stmts,
    wire_scope,
)


class WireRule:
    """Base class: subclasses set ``id``/``name``/``summary`` and ``check``."""

    id: str = ""
    name: str = ""
    summary: str = ""

    def check(self, ctx) -> Iterator[Finding]:
        raise NotImplementedError


def _finding(rule: WireRule, path: str, node: ast.AST,
             message: str) -> Finding:
    return Finding(path, getattr(node, "lineno", 1),
                   getattr(node, "col_offset", 0), rule.id, message)


def _short(qual: str) -> str:
    return ".".join(qual.split(".")[-3:])


# --------------------------------------------------------------------------
# W1: codec-pair symmetry
# --------------------------------------------------------------------------

_PAIR_PREFIXES = (("pack_", "unpack_"), ("encode_", "decode_"))


class W1CodecPairSymmetry(WireRule):
    """Every public ``pack_X``/``encode_X`` in a wire-scope module must have
    a matching ``unpack_X``/``decode_X`` somewhere in the project (and vice
    versa), and when a packer's frame count is statically certain, its
    paired unpacker must not index past it. An orphan codec half means one
    side of the wire ships a layout nobody can parse; an index overrun
    means sender and receiver disagree on the layout — both are findings
    here instead of runtime ``IndexError``s on a production socket.
    """

    id = "W1"
    name = "codec-pair-symmetry"
    summary = "pack/unpack halves must pair up and agree on frame layout"

    def check(self, ctx) -> Iterator[Finding]:
        project = ctx.project
        defined = {fn.name for fn in project.functions.values()}
        for fn in sorted(project.functions.values(),
                         key=lambda f: (f.path, f.node.lineno)):
            mod = project.module_of(fn)
            if not wire_scope(mod) or fn.name.startswith("_"):
                continue
            if fn.cls is not None:
                continue  # methods pair through their class API, not names
            for fwd, rev in _PAIR_PREFIXES:
                if fn.name.startswith(fwd):
                    suffix = fn.name[len(fwd):]
                    mates = {rev + suffix, rev + suffix + "_full"}
                    if not (mates & defined):
                        yield _finding(
                            self, fn.path, fn.node,
                            f"packer {fn.name} has no {rev}{suffix} "
                            f"counterpart — the wire layout it emits is "
                            f"write-only (add the decoder or pair it "
                            f"explicitly)")
                        continue
                    yield from self._frame_symmetry(ctx, fn, mates)
                elif fn.name.startswith(rev):
                    suffix = fn.name[len(rev):]
                    if suffix.endswith("_full"):
                        suffix = suffix[:-len("_full")]
                    if fwd + suffix not in defined:
                        yield _finding(
                            self, fn.path, fn.node,
                            f"unpacker {fn.name} has no {fwd}{suffix} "
                            f"counterpart — it parses a layout nothing in "
                            f"the project emits")

    def _frame_symmetry(self, ctx, packer, mates) -> Iterator[Finding]:
        count = packer_frame_count(packer.node)
        if count is None:
            return
        for unpacker in ctx.project.functions.values():
            if unpacker.name not in mates or unpacker.cls is not None:
                continue
            param = first_positional_param(unpacker.node)
            if param is None:
                continue
            hit = max_positional_index(unpacker.node, param)
            if hit is not None and hit[0] >= count:
                yield _finding(
                    self, unpacker.path, hit[1],
                    f"{unpacker.name} indexes frame {hit[0]} of {param!r} "
                    f"but its paired packer {packer.name} emits only "
                    f"{count} frame{'s' if count != 1 else ''} — "
                    f"sender/receiver layout drift")


# --------------------------------------------------------------------------
# W2: header versioning discipline
# --------------------------------------------------------------------------


class W2HeaderVersioning(WireRule):
    """Length-versioned headers are append-only with pinned positions: the
    base elements are validated once (``if len(h) < BASE: raise``), and
    every read past the base is guarded by a length check
    (``h[4] if len(h) > 4 else default``) so frames from old senders keep
    parsing. A positional read at or past the validated/guarded base with
    no covering guard is exactly the drift that turns a rolling upgrade
    into an ``IndexError`` storm.
    """

    id = "W2"
    name = "header-versioning-discipline"
    summary = "optional header element read without a length/version guard"

    def check(self, ctx) -> Iterator[Finding]:
        for fn in sorted(ctx.project.functions.values(),
                         key=lambda f: (f.path, f.node.lineno)):
            if not wire_scope(ctx.project.module_of(fn)):
                continue
            ha = HeaderAnalysis(fn.node)
            names = set(ha.validated) | set(ha.guards_seen)
            for name in sorted(names):
                base = ha.base_floor(name)
                sym_floors = ha.symbolic_floors(name)
                for sub, _nm, idx in ha.positional_reads(name):
                    sym, off = idx
                    if ha.guarded(sub, name, idx):
                        continue
                    if sym is None:
                        if base is None or off < base:
                            continue
                        yield _finding(
                            self, fn.path, sub,
                            f"read of optional header element "
                            f"{name}[{off}] is unguarded — the validated "
                            f"base length is {base}; guard with "
                            f"len({name}) > {off} so old senders keep "
                            f"parsing (append-only, positions pinned)")
                    else:
                        floors = [fk for fsym, fk in sym_floors
                                  if fsym == sym]
                        guards = [fk for fsym, fk in
                                  ha.guards_seen.get(name, [])
                                  if fsym == sym]
                        if not floors and not guards:
                            continue  # convention unknown: stay quiet
                        if any(off < fk for fk in floors):
                            continue
                        yield _finding(
                            self, fn.path, sub,
                            f"read of versioned header element "
                            f"{name}[{sym} + {off}] is not covered by its "
                            f"length validation — guard it or extend the "
                            f"validated floor (append-only, positions "
                            f"pinned)")


# --------------------------------------------------------------------------
# W3: receive-loop resilience
# --------------------------------------------------------------------------


class W3RecvLoopResilience(WireRule):
    """Any decode reachable inside a socket receive loop must be wrapped so
    typed decode errors (``CorruptFrameError``, msgpack errors, header
    ``KeyError``/``ValueError``) continue the loop. A bare decode — or a
    handler that re-raises/returns/breaks — means one corrupt frame from
    one peer permanently kills the loop for every peer: the PR 14 class.
    """

    id = "W3"
    name = "receive-loop-resilience"
    summary = "decode inside a receive loop can kill it on a corrupt frame"

    def check(self, ctx) -> Iterator[Finding]:
        for fn in sorted(ctx.project.functions.values(),
                         key=lambda f: (f.path, f.node.lineno)):
            loops = recv_loops(fn.node)
            if not loops:
                continue
            locals_ = ctx.locals_of(fn)
            seen = set()
            for loop in loops:
                protected = loop_protected_ids(loop)
                for call in walk_scope(loop):
                    if not isinstance(call, ast.Call) or id(call) in seen:
                        continue
                    if id(call) in protected:
                        continue
                    hit = ctx.facts.raising_chain(fn, call, locals_)
                    if hit is None:
                        continue
                    seen.add(id(call))
                    chain, label = hit
                    if not chain:
                        yield _finding(
                            self, fn.path, call,
                            f"bare {label} inside the receive loop of "
                            f"{_short(fn.qualname)} — a corrupt frame "
                            f"raises out of the loop and kills it; catch "
                            f"typed decode errors, count the reject, and "
                            f"continue (PR 14 class)")
                    else:
                        witness = " -> ".join(
                            _short(q) for q in (fn.qualname,) + chain)
                        yield _finding(
                            self, fn.path, call,
                            f"call to {_short(chain[0])} can raise a "
                            f"decode error inside the receive loop of "
                            f"{_short(fn.qualname)} (witness: {witness}) "
                            f"— wrap it so the loop continues, or contain "
                            f"the error in the callee (PR 14 class)")


# --------------------------------------------------------------------------
# W4: typed-reject accounting
# --------------------------------------------------------------------------


class W4TypedRejectAccounting(WireRule):
    """Every except branch that discards a wire message must increment a
    registered ``*_total`` reject/corrupt/stale counter, directly or via a
    callee. A silent swallow hides protocol rot: the fleet looks healthy
    while frames quietly vanish — drops must be visible in /metrics with
    the same fidelity as successes.
    """

    id = "W4"
    name = "typed-reject-accounting"
    summary = "decode-failure handler discards a message without counting it"

    def check(self, ctx) -> Iterator[Finding]:
        for fn in sorted(ctx.project.functions.values(),
                         key=lambda f: (f.path, f.node.lineno)):
            locals_ = None
            for t in walk_scope(fn.node):
                if not isinstance(t, ast.Try):
                    continue
                decodes: List[Tuple[ast.Call, str]] = []
                for n in walk_stmts(t.body):
                    if isinstance(n, ast.Call):
                        if locals_ is None:
                            locals_ = ctx.locals_of(fn)
                        hit = ctx.facts.raising_chain(fn, n, locals_)
                        if hit is not None:
                            decodes.append((n, hit[1]))
                if not decodes:
                    continue
                for h in t.handlers:
                    if not handler_catches_decode(h) or handler_reraises(h):
                        continue
                    if ctx.facts.counts_reject(fn, h, locals_):
                        continue
                    recv_line = first_recv_line(fn.node)
                    dnode, dlabel = decodes[0]
                    witness = (f"recv at line {recv_line}, "
                               if recv_line is not None else "")
                    yield _finding(
                        self, fn.path, h,
                        f"decode-failure handler in "
                        f"{_short(fn.qualname)} discards the message "
                        f"without counting it ({witness}{dlabel} at line "
                        f"{dnode.lineno}, swallowed here) — increment a "
                        f"typed *_total reject/corrupt counter so drops "
                        f"stay visible")


# --------------------------------------------------------------------------
# W5: metrics-contract cross-check
# --------------------------------------------------------------------------


class W5MetricsContract(WireRule):
    """The series catalog in docs/observability.md IS the metrics contract:
    every literal ``counter/gauge/histogram("name")`` in code must have a
    catalog row and every catalog row a code-side series. ``*_total``
    series are monotonic counters — never ``gauge``s, never ``set()``, and
    ``inc()`` arguments must be non-negative (a negated increment needs a
    dominating ``< 0`` sign-split guard: the PR 5 reward-sign class).
    """

    id = "W5"
    name = "metrics-contract-cross-check"
    summary = "series names, catalog rows, and counter monotonicity agree"

    def check(self, ctx) -> Iterator[Finding]:
        catalog = ctx.catalog
        declared = set()
        for decl in ctx.series:
            declared.add(decl.name)
            if decl.kind == "gauge" and decl.name.endswith("_total"):
                yield _finding(
                    self, decl.path, decl.node,
                    f"series {decl.name} is a gauge but *_total names a "
                    f"monotonic counter — rename it or make it a counter")
            if catalog is not None and not catalog.documents(decl.name):
                yield _finding(
                    self, decl.path, decl.node,
                    f"series {decl.name} is not in the "
                    f"docs/observability.md catalog — add a row (the "
                    f"catalog is the dashboard/alerting contract)")
        if catalog is not None and ctx.has_metrics_module:
            for name, line in sorted(catalog.names.items()):
                if name not in declared:
                    yield Finding(
                        catalog.display_path, line, 0, self.id,
                        f"documented series {name} is not created anywhere "
                        f"in the analyzed code — fix the catalog row or "
                        f"restore the series")
        yield from self._monotonicity(ctx)

    def _monotonicity(self, ctx) -> Iterator[Finding]:
        from tools.ba3cwire.model import counter_bindings
        for path, mod in sorted(ctx.project.by_path.items()):
            bindings = counter_bindings(mod)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                series = self._counter_series(node.func.value, bindings)
                if node.func.attr in ("set", "dec") and series is not None:
                    yield _finding(
                        self, path, node,
                        f"counter {series} is {node.func.attr}() — "
                        f"counters are monotonic; only inc() with a "
                        f"non-negative value (PR 5 class)")
                elif node.func.attr == "inc" and node.args:
                    yield from self._inc_arg(ctx, path, node)

    @staticmethod
    def _counter_series(recv: ast.AST,
                        bindings: Dict[str, str]) -> Optional[str]:
        dn = dotted_name(recv)
        if dn is not None and dn in bindings:
            return bindings[dn]
        if isinstance(recv, ast.Call) and \
                isinstance(recv.func, ast.Attribute) and \
                recv.func.attr == "counter" and recv.args and \
                isinstance(recv.args[0], ast.Constant) and \
                isinstance(recv.args[0].value, str):
            return recv.args[0].value
        return None

    def _inc_arg(self, ctx, path: str, node: ast.Call) -> Iterator[Finding]:
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and \
                isinstance(arg.value, (int, float)) and \
                not isinstance(arg.value, bool) and arg.value < 0:
            yield _finding(
                self, path, node,
                f"inc({arg.value}) decrements a counter — counters are "
                f"monotonic (PR 5 class)")
        elif isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub):
            operand = dotted_name(arg.operand)
            if operand is None or not sign_guarded(node, operand):
                yield _finding(
                    self, path, node,
                    f"negated increment inc(-{operand or '...'}) is not "
                    f"dominated by a `{operand or '...'} < 0` guard — a "
                    f"positive value here decrements the counter "
                    f"(PR 5 class)")


# --------------------------------------------------------------------------
# W6: CRC coverage
# --------------------------------------------------------------------------

_CODEC_ENTRY_NAMES = {"dumps", "pack_block", "pack_params", "pack_experience"}


class W6CrcCoverage(WireRule):
    """With ``wire_crc`` on, frame integrity holds only if every channel
    routes through the CRC-capable codec layer (utils/serialize and the
    codecs built on it). A raw msgpack call outside the codec modules — or
    an explicit ``crc=False`` at a non-codec call site — opens a channel
    the CRC deployment story silently does not cover.
    """

    id = "W6"
    name = "crc-coverage"
    summary = "wire channel bypasses the CRC-capable codec layer"

    def check(self, ctx) -> Iterator[Finding]:
        for path, mod in sorted(ctx.project.by_path.items()):
            if is_codec_module(path):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                dn = dotted_name(node.func)
                canon = mod.resolve(dn) if dn else None
                if canon and canon.split(".")[0] == "msgpack":
                    yield _finding(
                        self, path, node,
                        f"raw {canon} bypasses the CRC-capable codec "
                        f"layer — route through utils/serialize "
                        f"dumps/loads so wire_crc covers this channel")
                    continue
                last = dn.split(".")[-1] if dn else None
                if last in _CODEC_ENTRY_NAMES:
                    for kw in node.keywords:
                        if kw.arg == "crc" and \
                                isinstance(kw.value, ast.Constant) and \
                                kw.value.value is False:
                            yield _finding(
                                self, path, node,
                                f"{last}(crc=False) disables CRC framing "
                                f"outside the codec layer — only the "
                                f"codec modules may opt out (wire_crc "
                                f"must cover every channel)")


def all_wire_rules() -> List[WireRule]:
    return [
        W1CodecPairSymmetry(),
        W2HeaderVersioning(),
        W3RecvLoopResilience(),
        W4TypedRejectAccounting(),
        W5MetricsContract(),
        W6CrcCoverage(),
    ]
