"""ba3cwire engine: context building, rule driving, suppression filtering.

Same shape as ba3cflow's engine — whole-project rules over a shared
context, :class:`~tools.analyzer_core.Finding` output, and the
``# ba3cwire: disable=W3 — justification`` suppression spelling with the
family's exact semantics (trailing comment covers its line, standalone
comment covers the next line).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.analyzer_core import Finding, suppressions
from tools.ba3clint.engine import annotate_parents
from tools.ba3cflow.graph import CallGraph, local_types
from tools.ba3cflow.project import FunctionInfo, Project
from tools.ba3cwire.model import Catalog, WireFacts, collect_series, \
    load_catalog


class WireContext:
    """Everything a wire rule can ask about the project."""

    def __init__(self, project: Project, root: str = "."):
        self.project = project
        for mod in project.by_path.values():
            annotate_parents(mod.tree)
        self.graph = CallGraph(project)
        self.facts = WireFacts(project, self.graph)
        self.series = collect_series(project)
        self.catalog: Optional[Catalog] = load_catalog(root)
        self.has_metrics_module = any(
            mod.modname.endswith("telemetry.metrics")
            for mod in project.by_path.values())
        self._locals_cache: Dict[str, Dict[str, str]] = {}

    def locals_of(self, fn: FunctionInfo) -> Dict[str, str]:
        cached = self._locals_cache.get(fn.qualname)
        if cached is None:
            cached = local_types(self.project, fn)
            self._locals_cache[fn.qualname] = cached
        return cached


def build_context(paths: Sequence[str], root: str = ".") -> WireContext:
    return WireContext(Project.load(paths, root), root)


def run_rules(ctx: WireContext, rules: Iterable) -> List[Finding]:
    """All findings, unfiltered (suppressions NOT applied), sorted."""
    out: List[Finding] = []
    for path, err in sorted(ctx.project.broken.items()):
        out.append(Finding(path, err.lineno or 1, (err.offset or 1) - 1,
                           "E001", f"syntax error: {err.msg}"))
    seen: Set[tuple] = set()
    for rule in rules:
        for f in rule.check(ctx):
            key = (f.path, f.line, f.col, f.rule, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def filter_suppressed(ctx: WireContext,
                      findings: Sequence[Finding]) -> List[Finding]:
    sup_by_path: Dict[str, Dict[int, Set[str]]] = {}
    out: List[Finding] = []
    for f in findings:
        mod = ctx.project.by_path.get(f.path)
        if mod is None:
            out.append(f)
            continue
        sup = sup_by_path.get(f.path)
        if sup is None:
            sup = suppressions(mod.source, tool="ba3cwire")
            sup_by_path[f.path] = sup
        disabled = sup.get(f.line, set())
        if "ALL" in disabled or f.rule.upper() in disabled:
            continue
        out.append(f)
    return out


def analyze_paths(paths: Sequence[str], rules: Optional[Iterable] = None,
                  root: str = ".") -> List[Finding]:
    from tools.ba3cwire.rules import all_wire_rules
    ctx = build_context(paths, root)
    return filter_suppressed(ctx, run_rules(ctx, rules or all_wire_rules()))
