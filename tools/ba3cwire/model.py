"""ba3cwire wire-surface model: protocol facts over the ba3cflow symbol table.

Everything the W-rules ask about lives here:

- **decode classification**: which calls decode wire bytes (``loads``,
  ``unpack_*``/``decode_*`` codec entries, raw ``msgpack`` calls,
  ``np.frombuffer`` fed directly from a socket ``recv``).
- **raising-decode closure**: which project functions can let a typed decode
  error (``CorruptFrameError``, msgpack/header ``ValueError``/``KeyError``)
  escape to their caller — seeded from uncontained decode calls and explicit
  ``CorruptFrameError`` raises, propagated over the call graph with witness
  chains.
- **receive loops + protection**: socket receive loops, and whether a decode
  inside one is wrapped by a try that catches decode errors and CONTINUES
  the loop (a handler that re-raises/returns/breaks still kills it).
- **length-guard analysis**: per-function floors established by
  validate-or-bail ``len(...)`` checks and guards established by enclosing
  ``if len(...) > k`` tests — the "length-versioned, positions pinned"
  header convention, made checkable.
- **metrics facts**: every literal ``counter/gauge/histogram("name")``
  creation, counter-variable bindings for monotonicity checks, and the
  parsed docs/observability.md series catalog.

Heuristics over proofs, like the siblings: unknown receivers and dynamic
series names resolve to nothing, so rules stay quiet rather than guess.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.ba3clint.engine import dotted_name
from tools.ba3cflow.graph import CallGraph, resolve_call
from tools.ba3cflow.project import FunctionInfo, ModuleSyms, Project

# --------------------------------------------------------------------------
# scope walking (never cross into a nested function/class scope)
# --------------------------------------------------------------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def walk_scope(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` restricted to ``root``'s own scope: nested function and
    class bodies are opaque (they execute later, under their own rules)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)


def walk_stmts(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in stmts:
        yield from walk_scope(stmt)


# --------------------------------------------------------------------------
# codec modules + decode classification
# --------------------------------------------------------------------------

#: the four codec planes: the only modules allowed to touch msgpack or to
#: opt out of CRC framing — everything else must route through them.
CODEC_MODULE_SUFFIXES = (
    "utils/serialize.py",
    "pod/wire.py",
    "telemetry/wire.py",
    "telemetry/tracing.py",
)


def is_codec_module(path: str) -> bool:
    norm = path.replace("\\", "/")
    return norm.endswith(CODEC_MODULE_SUFFIXES)


#: modules participating in the wire protocol: the codec planes themselves,
#: plus anything importing them (or msgpack). W1/W2 stay inside this scope
#: so array-layout packers (ops/, models/) and CLI argv parsing never
#: read as protocol surfaces.
_WIRE_IMPORT_MARKERS = (
    "utils.serialize", "pod.wire", "telemetry.wire", "telemetry.tracing",
)


def wire_scope(mod: ModuleSyms) -> bool:
    if is_codec_module(mod.path):
        return True
    for origin in mod.imports.values():
        if origin == "msgpack" or origin.startswith("msgpack."):
            return True
        if any(marker in origin for marker in _WIRE_IMPORT_MARKERS):
            return True
    return False


#: struct.unpack/unpack_from parse fixed binary layouts, not codec payloads
_UNPACK_EXCLUDE = {"unpack_from"}

#: stdlib codecs whose failure modes are NOT the wire classes W3 tracks
_FOREIGN_LOADS_HEADS = ("json.", "pickle.", "yaml.", "marshal.", "tomllib.")

_MSGPACK_DECODE_ATTRS = {"unpackb", "unpack", "loads", "load"}


def decode_label(mod: ModuleSyms, call: ast.Call) -> Optional[str]:
    """Short label when ``call`` decodes wire bytes, else None."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "loads":
            canon = mod.resolve(func.id)
            if canon.startswith(_FOREIGN_LOADS_HEADS):
                return None
            return "loads"
        if func.id.startswith(("unpack_", "decode_")) and \
                func.id not in _UNPACK_EXCLUDE:
            return func.id
        return None
    if isinstance(func, ast.Attribute):
        attr = func.attr
        dn = dotted_name(func)
        canon = mod.resolve(dn) if dn else None
        if attr.startswith(("unpack_", "decode_")) and \
                attr not in _UNPACK_EXCLUDE:
            if canon is not None and canon.startswith("struct."):
                return None
            return attr
        if attr in _MSGPACK_DECODE_ATTRS and canon is not None:
            if canon.split(".")[0] == "msgpack":
                return canon
            if attr == "loads" and canon.endswith("serialize.loads"):
                return "loads"
            return None
        if attr == "frombuffer" and _feeds_from_recv(call):
            return "frombuffer(recv())"
    return None


def _feeds_from_recv(call: ast.Call) -> bool:
    """True when an argument of ``call`` contains an inline ``.recv*`` —
    decoding straight off the socket with no validation in between."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr.startswith("recv"):
                return True
    return False


# --------------------------------------------------------------------------
# try/except shape analysis
# --------------------------------------------------------------------------

#: exception names (last dotted segment) that cover the typed decode-failure
#: classes: CorruptFrameError(ValueError), msgpack's UnpackException family,
#: header KeyError/ValueError/IndexError, struct.error, or a blanket catch.
DECODE_EXC_NAMES = {
    "Exception", "BaseException", "ValueError", "TypeError", "KeyError",
    "IndexError", "CorruptFrameError", "UnpackException", "ExtraData",
    "OutOfData", "FormatError", "StackError", "error",
}


def _exc_names(node: Optional[ast.AST]) -> List[str]:
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for el in node.elts:
            out.extend(_exc_names(el))
        return out
    dn = dotted_name(node)
    return [dn.split(".")[-1]] if dn else []


def handler_catches_decode(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare except
    return any(n in DECODE_EXC_NAMES for n in _exc_names(handler.type))


def handler_kills_loop(handler: ast.ExceptHandler) -> bool:
    """A handler that raises, returns, or breaks still terminates the
    receive loop — catching the decode error is not enough."""
    return any(isinstance(n, (ast.Raise, ast.Return, ast.Break))
               for n in walk_stmts(handler.body))


def handler_reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in walk_stmts(handler.body))


def contained_node_ids(fn_node: ast.AST) -> Set[int]:
    """ids of nodes inside a try BODY whose handlers catch decode errors
    without re-raising — a decode error there is contained in this function
    (the caller never sees it, whatever the handler returns)."""
    out: Set[int] = set()
    for t in walk_scope(fn_node):
        if not isinstance(t, ast.Try):
            continue
        if not any(handler_catches_decode(h) and not handler_reraises(h)
                   for h in t.handlers):
            continue
        for n in walk_stmts(t.body):
            out.add(id(n))
    return out


def loop_protected_ids(loop: ast.AST) -> Set[int]:
    """ids of nodes inside a try strictly within ``loop`` whose handlers
    catch decode errors AND continue the loop (no raise/return/break)."""
    out: Set[int] = set()
    for t in walk_scope(loop):
        if not isinstance(t, ast.Try) or t is loop:
            continue
        if not any(handler_catches_decode(h) and not handler_kills_loop(h)
                   for h in t.handlers):
            continue
        for n in walk_stmts(t.body):
            out.add(id(n))
    return out


# --------------------------------------------------------------------------
# receive loops
# --------------------------------------------------------------------------


def recv_loops(fn_node: ast.AST) -> List[ast.AST]:
    """For/While loops in ``fn_node``'s scope whose body performs a socket
    ``.recv*`` — the loops a single corrupt frame must not terminate."""
    out = []
    for node in walk_scope(fn_node):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for sub in walk_scope(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr.startswith("recv"):
                out.append(node)
                break
    return out


def first_recv_line(loop: ast.AST) -> Optional[int]:
    lines = [sub.lineno for sub in walk_scope(loop)
             if isinstance(sub, ast.Call) and
             isinstance(sub.func, ast.Attribute) and
             sub.func.attr.startswith("recv")]
    return min(lines) if lines else None


# --------------------------------------------------------------------------
# interprocedural wire facts
# --------------------------------------------------------------------------


class WireFacts:
    """Raising-decode closure + counter-increment closure over the project."""

    def __init__(self, project: Project, graph: CallGraph):
        self.project = project
        self.graph = graph
        self._contained: Dict[str, Set[int]] = {}
        for fn in project.functions.values():
            self._contained[fn.qualname] = contained_node_ids(fn.node)
        #: qualname -> witness chain (qualnames, innermost last) ending at
        #: the function whose decode can raise out
        self.raising: Dict[str, Tuple[str, ...]] = {}
        self._build_raising()
        #: qualnames that (transitively) increment a metrics counter
        self.incs: Set[str] = set()
        self._build_incs()

    def contained(self, fn: FunctionInfo) -> Set[int]:
        return self._contained.get(fn.qualname, set())

    def _build_raising(self) -> None:
        for fn in self.project.functions.values():
            mod = self.project.module_of(fn)
            contained = self._contained[fn.qualname]
            for n in walk_scope(fn.node):
                if id(n) in contained:
                    continue
                if isinstance(n, ast.Raise) and n.exc is not None:
                    dn = dotted_name(n.exc.func) if isinstance(n.exc, ast.Call) \
                        else dotted_name(n.exc)
                    if dn and "CorruptFrame" in dn:
                        self.raising.setdefault(fn.qualname, (fn.qualname,))
                elif isinstance(n, ast.Call):
                    label = decode_label(mod, n)
                    if label and not resolve_call(self.project, fn, n):
                        # external decode (msgpack itself, or a codec the
                        # analyzed slice doesn't include): assume it raises
                        self.raising.setdefault(fn.qualname, (fn.qualname,))
        changed = True
        while changed:
            changed = False
            for fn in self.project.functions.values():
                q = fn.qualname
                if q in self.raising:
                    continue
                contained = self._contained[q]
                for tgt, node in self.graph.callees(q):
                    chain = self.raising.get(tgt.qualname)
                    if chain is None or id(node) in contained:
                        continue
                    if q not in chain and len(chain) < 10:
                        self.raising[q] = (q,) + chain
                        changed = True
                        break

    def _build_incs(self) -> None:
        for fn in self.project.functions.values():
            for n in walk_scope(fn.node):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "inc":
                    self.incs.add(fn.qualname)
                    break
        changed = True
        while changed:
            changed = False
            for q, callees in self.graph.edges.items():
                if q in self.incs:
                    continue
                if any(t.qualname in self.incs for t, _ in callees):
                    self.incs.add(q)
                    changed = True

    def raising_chain(self, fn: FunctionInfo, call: ast.Call,
                      locals_: Optional[Dict[str, str]] = None
                      ) -> Optional[Tuple[Tuple[str, ...], str]]:
        """(witness chain, label) when ``call`` can raise a decode error
        into ``fn``, else None."""
        mod = self.project.module_of(fn)
        label = decode_label(mod, call)
        targets = resolve_call(self.project, fn, call, locals_)
        if label and not targets:
            return ((), label)
        for tgt in targets:
            chain = self.raising.get(tgt.qualname)
            if chain is not None:
                return (chain, label or tgt.name)
        return None

    def counts_reject(self, fn: FunctionInfo, handler: ast.ExceptHandler,
                      locals_: Optional[Dict[str, str]] = None) -> bool:
        """True when ``handler`` increments a counter, directly or through
        a callee (the typed-reject accounting W4 requires)."""
        for n in walk_stmts(handler.body):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute) and n.func.attr == "inc":
                return True
            for tgt in resolve_call(self.project, fn, n, locals_, duck=True):
                if tgt.qualname in self.incs:
                    return True
        return False


# --------------------------------------------------------------------------
# length-guard analysis (W2)
# --------------------------------------------------------------------------

#: (symbol, offset): symbol None for a literal bound
Bound = Tuple[Optional[str], int]


def _len_arg(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and \
            node.func.id == "len" and len(node.args) == 1 and not node.keywords:
        return dotted_name(node.args[0])
    return None


def _bound(expr: ast.AST) -> Optional[Bound]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int) and \
            not isinstance(expr.value, bool):
        return (None, expr.value)
    dn = dotted_name(expr)
    if dn:
        return (dn, 0)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, (ast.Add, ast.Sub)):
        dn = dotted_name(expr.left)
        if dn and isinstance(expr.right, ast.Constant) and \
                isinstance(expr.right.value, int):
            k = expr.right.value
            return (dn, k if isinstance(expr.op, ast.Add) else -k)
    return None


_SWAPPED = {ast.Lt: ast.Gt, ast.Gt: ast.Lt, ast.LtE: ast.GtE,
            ast.GtE: ast.LtE, ast.Eq: ast.Eq, ast.NotEq: ast.NotEq}


def _len_compare(node: ast.AST) -> Optional[Tuple[str, type, ast.AST]]:
    """(name, op type, bound expr) for ``len(name) OP bound`` (either
    operand order; op normalized so len() is on the left)."""
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        return None
    left, op, right = node.left, node.ops[0], node.comparators[0]
    nm = _len_arg(left)
    if nm is not None:
        return (nm, type(op), right)
    nm = _len_arg(right)
    if nm is not None and type(op) in _SWAPPED:
        return (nm, _SWAPPED[type(op)], left)
    return None


def _bail_floors(test: ast.AST) -> Dict[str, List[Bound]]:
    """Floors established when ``test`` is true => control bails.

    ``if len(n) < 3: raise`` => past this point len(n) >= 3.
    """
    out: Dict[str, List[Bound]] = {}
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        for v in test.values:
            for nm, bs in _bail_floors(v).items():
                out.setdefault(nm, []).extend(bs)
        return out
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        for nm, bs in _guard_floors(test.operand).items():
            out.setdefault(nm, []).extend(bs)
        return out
    cmp = _len_compare(test)
    if cmp is not None:
        nm, op, bexpr = cmp
        b = _bound(bexpr)
        if b is not None:
            sym, k = b
            if op is ast.Lt:          # bail when len < k  => len >= k
                out.setdefault(nm, []).append((sym, k))
            elif op is ast.LtE:       # bail when len <= k => len >= k+1
                out.setdefault(nm, []).append((sym, k + 1))
            elif op is ast.NotEq:     # bail when len != k => len == k
                out.setdefault(nm, []).append((sym, k))
        return out
    # `if len(n) not in (2, 3): raise` => len >= min(2, 3)
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.ops[0], ast.NotIn):
        nm = _len_arg(test.left)
        tup = test.comparators[0]
        if nm is not None and isinstance(tup, (ast.Tuple, ast.List, ast.Set)):
            ks = [e.value for e in tup.elts
                  if isinstance(e, ast.Constant) and isinstance(e.value, int)]
            if ks and len(ks) == len(tup.elts):
                out.setdefault(nm, []).append((None, min(ks)))
    return out


def _guard_floors(test: ast.AST) -> Dict[str, List[Bound]]:
    """Floors established when ``test`` is TRUE (guard form).

    ``len(n) > 4`` => len >= 5; ``len(n) >= 5`` => len >= 5;
    ``len(n) == 3`` => len >= 3.
    """
    out: Dict[str, List[Bound]] = {}
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            for nm, bs in _guard_floors(v).items():
                out.setdefault(nm, []).extend(bs)
        return out
    cmp = _len_compare(test)
    if cmp is not None:
        nm, op, bexpr = cmp
        b = _bound(bexpr)
        if b is not None:
            sym, k = b
            if op is ast.Gt:
                out.setdefault(nm, []).append((sym, k + 1))
            elif op is ast.GtE:
                out.setdefault(nm, []).append((sym, k))
            elif op is ast.Eq:
                out.setdefault(nm, []).append((sym, k))
    return out


def _bails(stmts: Sequence[ast.stmt]) -> bool:
    if not stmts:
        return False
    return isinstance(stmts[-1], (ast.Raise, ast.Return, ast.Continue,
                                  ast.Break))


class HeaderAnalysis:
    """Per-function view of length-versioned positional header access."""

    def __init__(self, fn_node: ast.AST):
        self.fn_node = fn_node
        #: name -> floors from validate-or-bail checks (len(name) >= bound)
        self.validated: Dict[str, List[Bound]] = {}
        #: name -> floors from plain guard tests seen anywhere (used to
        #: infer the author's base length when nothing validates)
        self.guards_seen: Dict[str, List[Bound]] = {}
        for node in walk_scope(fn_node):
            if isinstance(node, ast.If) and _bails(node.body):
                for nm, bs in _bail_floors(node.test).items():
                    self.validated.setdefault(nm, []).extend(bs)
            if isinstance(node, ast.Assert):
                for nm, bs in _guard_floors(node.test).items():
                    self.validated.setdefault(nm, []).extend(bs)
            if isinstance(node, (ast.If, ast.IfExp)):
                for nm, bs in _guard_floors(node.test).items():
                    self.guards_seen.setdefault(nm, []).extend(bs)

    def tracked(self, name: str) -> bool:
        return name in self.validated or name in self.guards_seen

    def base_floor(self, name: str) -> Optional[int]:
        """Indexes below this are the pinned base header — always present.

        Preference order: the strongest validate-or-bail literal floor,
        else the smallest literal guard threshold (the author's implied
        base length when reads are guarded but never validated).
        """
        lits = [k for sym, k in self.validated.get(name, []) if sym is None]
        if lits:
            return max(lits)
        lits = [k for sym, k in self.guards_seen.get(name, []) if sym is None]
        if lits:
            return min(lits)
        return None

    def symbolic_floors(self, name: str) -> List[Bound]:
        return [b for b in self.validated.get(name, []) if b[0] is not None]

    def guarded(self, sub: ast.Subscript, name: str, idx: Bound) -> bool:
        """Is this subscript dominated by a length guard that covers it?"""
        cur: ast.AST = sub
        while True:
            parent = getattr(cur, "_ba3c_parent", None)
            if parent is None or isinstance(parent, _SCOPE_NODES):
                return False
            if isinstance(parent, ast.If) and _in_stmts(parent.body, cur):
                if self._test_covers(parent.test, name, idx, cur):
                    return True
            elif isinstance(parent, ast.IfExp) and parent.body is cur:
                if self._test_covers(parent.test, name, idx, cur):
                    return True
            elif isinstance(parent, ast.BoolOp) and \
                    isinstance(parent.op, ast.And):
                j = next((k for k, v in enumerate(parent.values) if v is cur),
                         None)
                if j is not None:
                    for v in parent.values[:j]:
                        if self._test_covers(v, name, idx, cur):
                            return True
            cur = parent

    def _test_covers(self, test: ast.AST, name: str, idx: Bound,
                     exclude: ast.AST) -> bool:
        sym, off = idx
        for fsym, fk in _guard_floors(test).get(name, []):
            if sym is None and fsym is None and off < fk:
                return True
            if sym is not None and fsym == sym and off < fk:
                return True
        return False

    def positional_reads(self, name_filter=None):
        """(subscript node, container dotted name, Bound index) for every
        positional integer-indexed read in this function."""
        out = []
        for node in walk_scope(self.fn_node):
            if not isinstance(node, ast.Subscript):
                continue
            nm = dotted_name(node.value)
            if nm is None or (name_filter is not None and nm != name_filter):
                continue
            if isinstance(node.slice, ast.Slice):
                continue
            b = _bound(node.slice)
            if b is None:
                continue
            sym, k = b
            if sym is None and k < 0:
                continue  # negative indexes count from the tail by design
            out.append((node, nm, b))
        return out


def _in_stmts(stmts: Sequence[ast.stmt], node: ast.AST) -> bool:
    for stmt in stmts:
        for sub in ast.walk(stmt):
            if sub is node:
                return True
    return False


# --------------------------------------------------------------------------
# codec-pair symmetry (W1)
# --------------------------------------------------------------------------


def packer_frame_count(fn_node: ast.AST) -> Optional[int]:
    """Number of frames a packer emits, when statically certain; else None.

    Two shapes count: a single ``return [a, b, c]`` list literal, or a
    body-level ``frames = [...]`` followed only by body-level
    ``frames.append(x)`` statements and ``return frames``. Any starred
    element, conditional append, or loop append -> None (unknown), so
    variable-frame packers like pack_block are skipped, not mis-counted.
    """
    returns = [n for n in walk_scope(fn_node)
               if isinstance(n, ast.Return) and n.value is not None]
    if len(returns) == 1 and isinstance(returns[0].value, ast.List):
        lst = returns[0].value
        if any(isinstance(e, ast.Starred) for e in lst.elts):
            return None
        return len(lst.elts)
    var: Optional[str] = None
    count = 0
    body = getattr(fn_node, "body", [])
    toplevel_appends: Set[int] = set()
    for stmt in body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.List):
            if any(isinstance(e, ast.Starred) for e in stmt.value.elts):
                return None
            var = stmt.targets[0].id
            count = len(stmt.value.elts)
        elif var is not None and isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call) and \
                isinstance(stmt.value.func, ast.Attribute) and \
                stmt.value.func.attr == "append" and \
                isinstance(stmt.value.func.value, ast.Name) and \
                stmt.value.func.value.id == var:
            count += 1
            toplevel_appends.add(id(stmt.value))
    if var is None:
        return None
    if not (len(returns) == 1 and isinstance(returns[0].value, ast.Name)
            and returns[0].value.id == var):
        return None
    for n in walk_scope(fn_node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) and \
                n.func.attr in ("append", "extend", "insert") and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == var and id(n) not in toplevel_appends:
            return None  # conditional/looped growth: frame count is dynamic
    return count


def first_positional_param(fn_node: ast.AST) -> Optional[str]:
    args = fn_node.args
    names = [a.arg for a in args.posonlyargs + args.args if a.arg != "self"]
    return names[0] if names else None


def max_positional_index(fn_node: ast.AST,
                         param: str) -> Optional[Tuple[int, ast.Subscript]]:
    """Largest literal integer subscript on ``param`` in the function."""
    best: Optional[Tuple[int, ast.Subscript]] = None
    for node in walk_scope(fn_node):
        if not isinstance(node, ast.Subscript):
            continue
        if dotted_name(node.value) != param:
            continue
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, int) and \
                not isinstance(sl.value, bool) and sl.value >= 0:
            if best is None or sl.value > best[0]:
                best = (sl.value, node)
    return best


# --------------------------------------------------------------------------
# metrics facts (W5)
# --------------------------------------------------------------------------


class SeriesDecl:
    """One literal ``counter/gauge/histogram("name")`` creation."""

    __slots__ = ("name", "kind", "path", "node")

    def __init__(self, name: str, kind: str, path: str, node: ast.Call):
        self.name = name
        self.kind = kind
        self.path = path
        self.node = node


_METRIC_KINDS = {"counter", "gauge", "histogram"}


def collect_series(project: Project) -> List[SeriesDecl]:
    out: List[SeriesDecl] = []
    for path, mod in sorted(project.by_path.items()):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _METRIC_KINDS and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                out.append(SeriesDecl(node.args[0].value, node.func.attr,
                                      path, node))
    return out


def counter_bindings(mod: ModuleSyms) -> Dict[str, str]:
    """Dotted variable/attribute name -> counter series name, for every
    ``x = <reg>.counter("name")`` binding in the module."""
    out: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        else:
            continue
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Attribute) and \
                value.func.attr == "counter" and value.args and \
                isinstance(value.args[0], ast.Constant) and \
                isinstance(value.args[0].value, str):
            for t in targets:
                dn = dotted_name(t)
                if dn:
                    out[dn] = value.args[0].value
    return out


def sign_guarded(call: ast.Call, operand_name: str) -> bool:
    """True when ``call`` (an ``.inc(-x)``) is dominated by an ``x < 0`` /
    ``x <= 0`` test — the sign-split idiom that makes the negation safe."""
    cur: ast.AST = call
    while True:
        parent = getattr(cur, "_ba3c_parent", None)
        if parent is None or isinstance(parent, _SCOPE_NODES):
            return False
        if isinstance(parent, ast.If) and _in_stmts(parent.body, cur):
            if _tests_negative(parent.test, operand_name):
                return True
        elif isinstance(parent, ast.IfExp) and parent.body is cur:
            if _tests_negative(parent.test, operand_name):
                return True
        cur = parent


def _tests_negative(test: ast.AST, name: str) -> bool:
    if isinstance(test, ast.BoolOp):
        return any(_tests_negative(v, name) for v in test.values)
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return False
    left, op, right = test.left, test.ops[0], test.comparators[0]
    if dotted_name(left) == name and isinstance(op, (ast.Lt, ast.LtE)) and \
            isinstance(right, ast.Constant) and right.value == 0:
        return True
    if dotted_name(right) == name and isinstance(op, (ast.Gt, ast.GtE)) and \
            isinstance(left, ast.Constant) and left.value == 0:
        return True
    return False


# --------------------------------------------------------------------------
# docs/observability.md series catalog (W5)
# --------------------------------------------------------------------------

_SERIES_TOKEN_RE = re.compile(r"`([a-z][a-z0-9_<>]*)`")
_TEMPLATE_PART_RE = re.compile(r"<[a-z_]+>")


class Catalog:
    """Parsed series tables from docs/observability.md.

    Only rows of tables whose header's first column is ``series`` count —
    endpoint/hop tables and prose mentions never pollute the contract.
    """

    def __init__(self, path: str, display_path: str):
        self.display_path = display_path
        #: exact series name -> first docs line declaring it
        self.names: Dict[str, int] = {}
        #: (compiled template regex, docs line) for `hop_<name>_s` style rows
        self.templates: List[Tuple["re.Pattern[str]", int]] = []
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        in_series_table = False
        for i, line in enumerate(lines, start=1):
            stripped = line.strip()
            if not stripped.startswith("|"):
                in_series_table = False
                continue
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            if not cells:
                continue
            first = cells[0]
            if first.lower() == "series":
                in_series_table = True
                continue
            if not in_series_table or set(first) <= {"-", ":", " "}:
                continue
            for m in _SERIES_TOKEN_RE.finditer(first):
                tok = m.group(1)
                if "<" in tok:
                    pat = "^" + _TEMPLATE_PART_RE.sub(
                        "[a-z0-9_]+", re.escape(tok).replace(
                            r"\<", "<").replace(r"\>", ">")) + "$"
                    self.templates.append((re.compile(pat), i))
                else:
                    self.names.setdefault(tok, i)

    def documents(self, name: str) -> bool:
        if name in self.names:
            return True
        return any(pat.match(name) for pat, _ in self.templates)


def load_catalog(root: str) -> Optional[Catalog]:
    path = os.path.join(root, "docs", "observability.md")
    if not os.path.isfile(path):
        return None
    return Catalog(path, os.path.normpath(path))
