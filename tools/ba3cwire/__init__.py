"""ba3cwire: wire-protocol and failure-path conformance analyzer.

Where ba3clint reads lines, ba3cflow reads the call graph, and ba3caudit
reads jaxpr/HLO traces, ba3cwire reads the *protocol*: the codec planes
(``utils/serialize``, ``pod/wire``, ``telemetry/wire``,
``telemetry/tracing``), every socket receive path that decodes them, and
the metrics contract that makes drops visible. Rule catalog (details in
docs/static_analysis.md):

- **W1** codec-pair symmetry: every pack/encode half has its unpack/decode
  twin, and frame counts agree across the pair
- **W2** header versioning discipline: length-versioned headers are
  append-only with pinned positions; optional-element reads are guarded
- **W3** receive-loop resilience: a decode inside a socket receive loop
  must not let a corrupt frame kill the loop (PR 14 class)
- **W4** typed-reject accounting: every handler that discards a message
  increments a registered ``*_total`` reject/corrupt/stale counter
- **W5** metrics-contract cross-check: code series vs the
  docs/observability.md catalog, and counter monotonicity (PR 5 class)
- **W6** CRC coverage: no wire channel bypasses the CRC-capable codec
  layer when ``wire_crc`` is on

Usage: ``python -m tools.ba3cwire [--json] [--sarif out.sarif]``.
Suppress per line with ``# ba3cwire: disable=W3 — justification``.
"""

from tools.analyzer_core import Finding  # shared finding type
from tools.ba3cwire.engine import WireContext, analyze_paths, build_context, \
    filter_suppressed, run_rules


def all_rules():
    from tools.ba3cwire.rules import all_wire_rules
    return all_wire_rules()


__all__ = [
    "Finding",
    "WireContext",
    "all_rules",
    "analyze_paths",
    "build_context",
    "filter_suppressed",
    "run_rules",
]
