"""CLI: ``python -m tools.ba3clint [paths...]``.

Exit status: 0 = clean, 1 = findings, 2 = bad usage. CI gates on this
(scripts/check.sh is the pre-commit entry point).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from tools.analyzer_core import emit_findings, narrow_rules, \
    print_rule_catalog
from tools.ba3clint import all_rules, lint_paths
from tools.ba3clint.engine import check_suppressions


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ba3clint",
        description="Repo-specific static analysis for the BA3C stack "
        "(rule catalog: docs/static_analysis.md).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["distributed_ba3c_tpu"],
        help="files or directories to lint (default: distributed_ba3c_tpu)",
    )
    parser.add_argument(
        "--format",
        choices=("human", "json"),
        default="human",
        help="output format (default: human)",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="also write findings as SARIF 2.1.0 to FILE",
    )
    parser.add_argument(
        "--check-suppressions",
        action="store_true",
        help="flag '# ba3clint: disable=' comments that mask no finding",
    )
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        print_rule_catalog(rules)
        return 0
    if args.select:
        rules = narrow_rules(rules, args.select)
        if rules is None:
            return 2

    try:
        if args.check_suppressions:
            findings = check_suppressions(args.paths, rules)
        else:
            findings = lint_paths(args.paths, rules)
    except FileNotFoundError as e:
        print(f"ba3clint: {e}", file=sys.stderr)
        return 2
    return emit_findings(findings, "ba3clint", rules,
                         as_json=args.format == "json", sarif=args.sarif)


if __name__ == "__main__":
    sys.exit(main())
