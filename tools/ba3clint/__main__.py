"""CLI: ``python -m tools.ba3clint [paths...]``.

Exit status: 0 = clean, 1 = findings, 2 = bad usage. CI gates on this
(scripts/check.sh is the pre-commit entry point).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from tools.ba3clint import all_rules, lint_paths
from tools.ba3clint.engine import check_suppressions


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ba3clint",
        description="Repo-specific static analysis for the BA3C stack "
        "(rule catalog: docs/static_analysis.md).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["distributed_ba3c_tpu"],
        help="files or directories to lint (default: distributed_ba3c_tpu)",
    )
    parser.add_argument(
        "--format",
        choices=("human", "json"),
        default="human",
        help="output format (default: human)",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="also write findings as SARIF 2.1.0 to FILE",
    )
    parser.add_argument(
        "--check-suppressions",
        action="store_true",
        help="flag '# ba3clint: disable=' comments that mask no finding",
    )
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:4s} {r.name:32s} {r.summary}")
        return 0
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",") if s.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
        rules = [r for r in rules if r.id in wanted]

    try:
        if args.check_suppressions:
            findings = check_suppressions(args.paths, rules)
        else:
            findings = lint_paths(args.paths, rules)
    except FileNotFoundError as e:
        print(f"ba3clint: {e}", file=sys.stderr)
        return 2
    if args.sarif:
        from tools.sarif import write_sarif
        write_sarif(args.sarif, findings, "ba3clint", rules)
    if args.format == "json":
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] {f.message}")
        n = len(findings)
        print(f"ba3clint: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
