"""A-series rules: the actor plane's concurrency conventions, machine-checked.

``utils/concurrency.py`` asserts "message passing only, no shared mutable
state" in a docstring; these rules are that docstring as code. Rationale and
worked examples live in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set

from tools.ba3clint.engine import (
    FileContext,
    Finding,
    Rule,
    ancestors,
    chain_root,
    dotted_name,
    enclosing_functions,
    enclosing_statement,
    parent,
)

_THREAD_CTORS = {"threading.Thread"}
_PROC_CTORS = {"multiprocessing.Process", "multiprocessing.context.Process"}


class BareThreadRule(Rule):
    """A1: bare ``threading.Thread``/``mp.Process`` instantiation.

    A bare thread has no stop flag: shutdown can only kill it by exiting the
    interpreter, and a leaked thread wedges later in-process jit dispatch
    (the round-1 pytest deadlock). Use ``StoppableThread``/``LoopThread``
    from ``utils.concurrency`` (threads) or a process that is registered
    with ``ensure_proc_terminate`` — or suppress with the justification for
    why this thread's lifetime is otherwise bounded.
    """

    id = "A1"
    name = "bare-thread"
    summary = "bare threading.Thread/mp.Process where a stoppable wrapper is required"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.info.resolve(node.func)
            if resolved in _THREAD_CTORS:
                yield ctx.finding(
                    self, node,
                    "bare threading.Thread has no stop flag — use "
                    "StoppableThread/LoopThread (utils.concurrency) so "
                    "shutdown can be observed",
                )
            elif resolved in _PROC_CTORS:
                yield ctx.finding(
                    self, node,
                    "bare multiprocessing.Process — use a managed process "
                    "(ensure_proc_terminate + start_proc_mask_signal)",
                )


_QUEUEISH_EXACT = {"q", "_q", "_out", "out_q", "outq", "in_q", "inq"}


def _queueish(recv: ast.AST) -> bool:
    if isinstance(recv, ast.Attribute):
        last = recv.attr
    elif isinstance(recv, ast.Name):
        last = recv.id
    else:
        return False
    low = last.lower()
    return "queue" in low or low in _QUEUEISH_EXACT


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _nonblocking(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant):
            if kw.value.value is False:
                return True
    return False


class BlockingQueueOpRule(Rule):
    """A2: blocking ``get()``/``put()`` without a timeout on a queue.

    A get/put with no timeout blocks forever if the peer thread died — the
    stop flag is never re-checked and shutdown wedges. Every queue op in the
    actor plane must either carry a ``timeout=`` (and loop on the stop flag:
    see ``queue_get_stoppable``/``queue_put_stoppable``) or be the
    ``_nowait`` variant.
    """

    id = "A2"
    name = "blocking-queue-op"
    summary = "Queue.get()/put() with no timeout wedges shutdown"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr == "get":
                # dict.get(key) takes positional args; Queue.get() does not
                if node.args or _has_kw(node, "timeout") or _nonblocking(node):
                    continue
                if not _queueish(fn.value):
                    continue
                yield ctx.finding(
                    self, node,
                    "blocking Queue.get() with no timeout — pass timeout= "
                    "and re-check the stop flag (queue_get_stoppable)",
                )
            elif fn.attr == "put":
                if not node.args or _has_kw(node, "timeout") or _nonblocking(node):
                    continue
                if not _queueish(fn.value):
                    continue
                yield ctx.finding(
                    self, node,
                    "blocking Queue.put() with no timeout — pass timeout= "
                    "and re-check the stop flag (queue_put_stoppable)",
                )


_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "popitem", "add", "discard",
}


def _mentions_clients_subscript(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript):
            base = dotted_name(sub.value)
            if base and (base == "clients" or base.endswith(".clients")):
                return True
    return False


class CrossThreadClientMutationRule(Rule):
    """A3: shared client-table state mutated from a closure.

    Closures handed to the predictor (``put_task`` callbacks) run on a
    predictor worker thread. Mutating per-client state (``client.memory``,
    ``client.score``, the ``clients`` table itself) from there is only safe
    when the wire protocol serializes it (the simulator is blocked awaiting
    its action). That invariant lives outside the code — so every such
    mutation must either go through a lock/queue or carry a suppression
    whose justification states the serialization argument. The runtime
    sanitizer (utils/sanitizer.py, BA3C_SANITIZE=1) checks the table half
    of the claim in tests.
    """

    id = "A3"
    name = "cross-thread-client-mutation"
    summary = "client-table state mutated from a closure running on another thread"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not enclosing_functions(node):
                continue  # only closures (nested defs) run on foreign threads
            yield from self._check_closure(ctx, node)

    def _check_closure(self, ctx: FileContext, fn: ast.AST) -> Iterator[Finding]:
        tracked: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _mentions_clients_subscript(
                node.value
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tracked.add(t.id)

        def is_shared(expr: ast.AST) -> bool:
            root = chain_root(expr)
            if isinstance(root, ast.Name) and root.id in tracked:
                return True
            return _mentions_clients_subscript(expr)

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _MUTATORS
                    and is_shared(f.value)
                ):
                    yield ctx.finding(
                        self, node,
                        f".{f.attr}() on shared client state from a closure "
                        "(runs on a predictor/worker thread) — needs a "
                        "lock/queue handoff, or a suppression stating the "
                        "protocol-serialization argument",
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)) and is_shared(t):
                        yield ctx.finding(
                            self, node,
                            "write to shared client state from a closure "
                            "(runs on a predictor/worker thread) — needs a "
                            "lock/queue handoff, or a suppression stating "
                            "the protocol-serialization argument",
                        )
                        break


_SUSPECT_TARGET_FRAGMENTS = (
    "last", "t0", "deadline", "start", "seen", "now", "begin", "expire",
    "elapsed", "heartbeat",
)


class WallClockArithRule(Rule):
    """A4: ``time.time()`` used for interval/timeout arithmetic.

    The wall clock jumps (NTP slew, suspend/resume, leap smearing); a
    heartbeat or timeout computed from ``time.time()`` can mass-expire
    every actor on a clock step (`actors/simulator.py` did exactly this for
    ``last_seen``). Durations and deadlines must use ``time.monotonic()``;
    ``time.time()`` is only for timestamps that leave the process (logs,
    TensorBoard wall_time).
    """

    id = "A4"
    name = "wall-clock-arith"
    summary = "time.time() used for interval/timeout arithmetic instead of time.monotonic()"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.info.resolve(node.func) != "time.time":
                continue
            if self._in_arith(node) or self._assigned_to_suspect(node):
                yield ctx.finding(
                    self, node,
                    "time.time() in interval/timeout arithmetic — the wall "
                    "clock jumps; use time.monotonic()",
                )

    @staticmethod
    def _in_arith(node: ast.AST) -> bool:
        for cur in ancestors(node):
            if isinstance(cur, (ast.BinOp, ast.Compare)):
                return True
            # the value was swallowed by a call or container before reaching
            # any arithmetic (e.g. json.dumps({"ts": time.time()}) + "\n" is
            # string concat on the *serialized* value, not clock arithmetic)
            if isinstance(
                cur, (ast.Call, ast.Dict, ast.List, ast.Set, ast.Tuple, ast.stmt)
            ):
                return False
        return False

    @staticmethod
    def _assigned_to_suspect(node: ast.AST) -> bool:
        stmt = enclosing_statement(node)
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            name = t.attr if isinstance(t, ast.Attribute) else (
                t.id if isinstance(t, ast.Name) else None
            )
            if name and any(
                frag in name.lower() for frag in _SUSPECT_TARGET_FRAGMENTS
            ):
                return True
        return False


def _is_dunder(name: str) -> bool:
    return name.startswith("__") and name.endswith("__")


class PrivateImportRule(Rule):
    """A5: ``from <module> import _private`` — importing underscore names.

    A leading underscore is the module's statement that the name may be
    renamed, re-scoped, or deleted without notice; an external import turns
    that private detail into silent API surface (``scripts/ksweep_bench.py``
    depended on ``devicelock._stderr_print`` exactly this way — ADVICE r5).
    Promote the name to a public one (keep a private alias in the owning
    module if its history matters), or suppress with the justification for
    why the coupling is intended.
    """

    id = "A5"
    name = "private-import"
    summary = "from-import of an underscore-private name couples to another module's internals"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.module == "__future__":
                continue
            for alias in node.names:
                nm = alias.name
                if nm.startswith("_") and not _is_dunder(nm):
                    mod = ("." * node.level) + (node.module or "")
                    yield ctx.finding(
                        self, node,
                        f"importing private name {nm!r} from {mod!r} — "
                        "underscore names are the owning module's internals; "
                        "promote it to a public name (keep a private alias) "
                        "or suppress with the coupling justification",
                    )


_WIRE_OPS = {
    "send", "recv", "send_multipart", "recv_multipart", "send_pyobj",
    "recv_pyobj", "send_string", "recv_string", "send_json", "recv_json",
    "send_serialized", "recv_serialized",
}
_SOCKISH_FRAGMENTS = ("sock", "dealer", "router", "push", "pull", "zmq")


def _socket_ish(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and any(f in name.lower() for f in _SOCKISH_FRAGMENTS):
            return True
    return False


def _target_names(target: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


def _env_indexed_iter(it: ast.AST) -> bool:
    for sub in ast.walk(it):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and "env" in name.lower():
            return True
    return False


class PerEnvWireLoopRule(Rule):
    """A6: per-element socket send/recv inside a loop over env indices.

    The per-env wire — B sends and B drains per step where ``env.step``
    already produced the whole [B, ...] block — is what pinned the plane at
    2,128 env-steps/s/host (PERF.md round 4); the block wire replaced it
    with 2 socket ops per server per step (docs/actor_plane.md). A wire op
    executed once per env index regresses exactly that, so it must either
    become one batched multipart op outside the loop or carry a suppression
    naming why per-element is intended (the `--wire per-env` compat foil in
    ``envs/native.py`` is the only sanctioned case).
    """

    id = "A6"
    name = "per-env-wire-loop"
    summary = "per-element socket send/recv in a loop over env indices regresses the block wire"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        seen: Set[int] = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, ast.For):
                continue
            targets = _target_names(loop.target)
            env_iter = _env_indexed_iter(loop.iter)
            for node in ast.walk(loop):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not isinstance(fn, ast.Attribute) or fn.attr not in _WIRE_OPS:
                    continue
                if not _socket_ish(fn.value):
                    continue
                if (
                    env_iter
                    or self._loop_var_indexes(node, targets)
                    or self._receiver_is_loop_var(fn.value, targets)
                ):
                    seen.add(id(node))
                    yield ctx.finding(
                        self, node,
                        f"per-element .{fn.attr}() inside a loop over env "
                        "indices — batch the block into ONE multipart "
                        "message per step (see docs/actor_plane.md), or "
                        "suppress with the reason per-element is intended",
                    )

    @staticmethod
    def _loop_var_indexes(call: ast.Call, targets: Set[str]) -> bool:
        # the loop variable used as a subscript INDEX anywhere in the call
        # (`dealers[i].recv()`, `push.send(stacks[i])`) = a per-env element op
        for sub in ast.walk(call):
            if isinstance(sub, ast.Subscript):
                for n in ast.walk(sub.slice):
                    if isinstance(n, ast.Name) and n.id in targets:
                        return True
        return False

    @staticmethod
    def _receiver_is_loop_var(recv: ast.AST, targets: Set[str]) -> bool:
        # iterating the socket collection itself: `for s in dealers: s.send(..)`
        root = chain_root(recv)
        return isinstance(root, ast.Name) and root.id in targets


#: identifier TOKENS (underscore-split) that mark a statement as metric
#: accounting. Whole tokens, not substrings: "rate" must catch `msg_rate`
#: without firing on `learning_rate`-adjacent timestamps via `generate`/
#: `iterate`/`separate` — except learning_rate itself, which token
#: matching would also hit; it is a hyperparameter, not a metric, so it
#: is exempted explicitly below.
_METRIC_NAME_TOKENS = frozenset(
    ("fps", "rate", "throughput", "latency", "persec")
)
_NON_METRIC_NAMES = frozenset(("learning_rate", "lr_rate"))
#: literal-string fragments that mark a print as metric reporting
_METRIC_STRING_FRAGMENTS = (
    "fps", "steps/s", "steps/sec", "/sec", "per sec", "throughput",
    "latency", "qsize",
)


def _string_literals(call: ast.Call) -> Iterator[str]:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                yield sub.value
            elif isinstance(sub, ast.JoinedStr):
                for v in sub.values:
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        yield v.value


class AdhocMetricRule(Rule):
    """A7: ``time.time()``/``print``-based metric accounting outside
    ``telemetry/``.

    The telemetry plane (distributed_ba3c_tpu/telemetry/,
    docs/observability.md) is THE account of rates, latencies and queue
    depths: registry counters feed the scrape endpoint, the stat.json/TB
    bridge, and the fleet piggyback at once. A hand-rolled
    ``fps = n / (time.time() - t0)`` + ``print(...)`` is invisible to all
    three — and wall-clock-based on top (see A4). Route the number through
    ``telemetry.registry(role)`` (Counter/Gauge/Histogram) and let the
    exporters render it; ``print`` stays fine for non-metric output, and
    the rule does not apply inside ``telemetry/`` itself (something has to
    implement the plane).
    """

    id = "A7"
    name = "adhoc-metric"
    summary = "ad-hoc time.time()/print metric accounting bypasses the telemetry registry"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "telemetry" in ctx.path.replace(os.sep, "/").split("/"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and node.func.id == "print":
                for s in _string_literals(node):
                    low = s.lower()
                    if any(f in low for f in _METRIC_STRING_FRAGMENTS):
                        yield ctx.finding(
                            self, node,
                            "print-based metric reporting — route it "
                            "through telemetry.registry(...) so the scrape "
                            "endpoint / stat.json / fleet series see it",
                        )
                        break
            elif ctx.info.resolve(node.func) == "time.time":
                stmt = enclosing_statement(node)
                if stmt is not None and self._stmt_mentions_metric(stmt):
                    yield ctx.finding(
                        self, node,
                        "time.time()-based metric accounting — use a "
                        "telemetry registry Counter/Histogram (monotonic "
                        "inside) instead of hand-rolled rate math",
                    )

    @staticmethod
    def _stmt_mentions_metric(stmt: ast.stmt) -> bool:
        for sub in ast.walk(stmt):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if not name:
                continue
            low = name.lower()
            if low in _NON_METRIC_NAMES:
                continue
            tokens = low.split("_")
            if not _METRIC_NAME_TOKENS.isdisjoint(tokens):
                return True
            # "per_sec"/"persec" may straddle a token boundary
            if "persec" in low.replace("_", ""):
                return True
        return False


#: fleet-role process classes: anything whose lifecycle the supervisor owns
_FLEET_PROC_SUFFIXES = (".CppEnvServerProcess", ".SimulatorProcess")
_FLEET_PROC_BARE = {"CppEnvServerProcess", "SimulatorProcess"}

#: the multi-fleet assembly entry point (actors/fleet.py): ONE call stands
#: up K masters/predictors and hands K factories to K FleetSupervisors —
#: K fleets' worth of spawns behind one name, so a stray call outside
#: orchestrate/ bypasses K fleets' worth of lifecycle accounting
_FLEET_ASSEMBLY_SUFFIXES = (".build_fleet_planes",)
_FLEET_ASSEMBLY_BARE = {"build_fleet_planes"}

#: fleet-role entry points a subprocess spawn may name
_FLEET_ENTRY_FRAGMENTS = ("train.py", "launch_env_fleet")

_SUBPROCESS_SPAWNERS = {
    "subprocess.Popen", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
}

_RAW_FORKS = {"os.fork", "os.forkpty", "os.posix_spawn", "os.posix_spawnp"}


class UnsupervisedFleetSpawnRule(Rule):
    """A8: fleet-role process spawned outside ``orchestrate/``.

    The orchestration subsystem (distributed_ba3c_tpu/orchestrate/,
    docs/orchestration.md) owns the fleet lifecycle: respawn with backoff,
    the restart-budget circuit breaker, stale shm-ring reclaim, scale
    accounting as ``tele/orchestrator/*``. A ``CppEnvServerProcess``/
    ``SimulatorProcess`` constructed-and-started directly — or a
    ``subprocess.Popen`` of ``train.py``/``launch_env_fleet`` — bypasses
    all of it: the process that dies stays dead and nothing is accounted.
    The multi-fleet assembly ``build_fleet_planes`` (actors/fleet.py) is
    flagged the same way: one call stands up K fleets of spawns, so a
    stray call multiplies the bypass K-fold.
    Route fleet roles through ``FleetSupervisor``/``LearnerSupervisor``,
    or suppress with the justification for why this spawn's lifecycle is
    otherwise owned (a factory HANDED to the supervisor parameterizes the
    slot rather than spawning it — that is the sanctioned suppression,
    and the one cli.py's build_fleet_planes call site carries).
    ``os.fork`` and friends are flagged unconditionally: the repo is
    spawn-context-only (a fork from the threaded trainer can deadlock the
    child — envs/simulator.py).
    """

    id = "A8"
    name = "unsupervised-fleet-spawn"
    summary = "fleet-role process spawned outside orchestrate/ bypasses the supervisor"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "orchestrate" in ctx.path.replace(os.sep, "/").split("/"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.info.resolve(node.func)
            if resolved is None:
                continue
            if (
                resolved in _FLEET_PROC_BARE
                or resolved.endswith(_FLEET_PROC_SUFFIXES)
            ):
                yield ctx.finding(
                    self, node,
                    f"direct {resolved.rsplit('.', 1)[-1]} construction — "
                    "fleet-role processes belong to a FleetSupervisor "
                    "(respawn/backoff/scale accounting; "
                    "docs/orchestration.md)",
                )
            elif (
                resolved in _FLEET_ASSEMBLY_BARE
                or resolved.endswith(_FLEET_ASSEMBLY_SUFFIXES)
            ):
                yield ctx.finding(
                    self, node,
                    "multi-fleet assembly (build_fleet_planes) outside "
                    "orchestrate/ — K fleets of spawns need their "
                    "factories supervisor-owned; the sanctioned call "
                    "sites (cli.py's factory-only assembly) carry an "
                    "explicit suppression (docs/actor_plane.md)",
                )
            elif resolved in _RAW_FORKS:
                yield ctx.finding(
                    self, node,
                    f"{resolved}() — the repo is spawn-context-only, and "
                    "fleet roles belong to the orchestrate/ supervisors",
                )
            elif resolved in _SUBPROCESS_SPAWNERS and any(
                frag in s
                for s in _string_literals(node)
                for frag in _FLEET_ENTRY_FRAGMENTS
            ):
                yield ctx.finding(
                    self, node,
                    "subprocess spawn of a fleet-role entry point — a "
                    "supervised learner belongs to LearnerSupervisor "
                    "(checkpoint failover + accounting), a fleet to "
                    "FleetSupervisor (docs/orchestration.md)",
                )


#: queue constructors whose default is UNBOUNDED — in the serving plane an
#: unbounded queue converts overload into unbounded latency instead of the
#: fast typed rejection the SLO contract promises (docs/serving.md)
_QUEUE_CTOR_SUFFIXES = (
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "multiprocessing.Queue", "concurrency.FastQueue",
)
_QUEUE_CTOR_BARE = {"Queue", "LifoQueue", "PriorityQueue", "FastQueue"}

_BLOCKING_SLEEPS = {"time.sleep"}
_CONSOLE_FILE_IO = {"open", "print", "builtins.open", "builtins.print"}


class ServingHotPathBlockRule(Rule):
    """A9: blocking I/O or an unbounded queue inside the serving plane
    (``predict/``).

    The predictor's scheduler/callback path is the latency budget of every
    request the serving tier answers (docs/serving.md): a ``time.sleep``,
    file/console I/O, or a socket op on that path stalls EVERY in-flight
    request behind it, and an unbounded ``queue.Queue`` turns overload
    into unbounded queue latency instead of the fast typed rejection the
    SLO contract promises. Queues in ``predict/`` must be constructed with
    a positive bound (a computed bound like ``maxsize=queue_depth`` is
    accepted); waiting must go through bounded-timeout queue ops
    (``queue_get_stoppable``), never sleeps. The rule applies only to
    files under a ``predict/`` directory — everywhere else A2/A7 own the
    neighboring hazards.
    """

    id = "A9"
    name = "serving-hot-path-block"
    summary = "blocking I/O or unbounded queue inside the predict/ serving plane"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "predict" not in ctx.path.replace(os.sep, "/").split("/"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.info.resolve(node.func)
            if resolved and (
                resolved in _QUEUE_CTOR_BARE
                or resolved.endswith(_QUEUE_CTOR_SUFFIXES)
            ):
                if not self._bounded(node):
                    yield ctx.finding(
                        self, node,
                        "unbounded queue in the serving plane — overload "
                        "must become fast typed rejection, not unbounded "
                        "latency: construct with a positive maxsize "
                        "(docs/serving.md admission contract)",
                    )
            elif resolved in _BLOCKING_SLEEPS:
                yield ctx.finding(
                    self, node,
                    "time.sleep on the serving path stalls every in-flight "
                    "request behind it — wait via bounded-timeout queue ops "
                    "(queue_get_stoppable) instead",
                )
            elif resolved in _CONSOLE_FILE_IO:
                yield ctx.finding(
                    self, node,
                    f"{resolved}() is blocking file/console I/O on the "
                    "serving path — route diagnostics through telemetry/"
                    "logger outside predict/",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _WIRE_OPS
                and _socket_ish(node.func.value)
            ):
                yield ctx.finding(
                    self, node,
                    f"socket .{node.func.attr}() inside the serving plane — "
                    "wire I/O belongs to the masters (actors/), the "
                    "predictor only schedules device calls",
                )

    @staticmethod
    def _bounded(call: ast.Call) -> bool:
        bound = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "maxsize":
                bound = kw.value
        if bound is None:
            return False
        if isinstance(bound, ast.Constant):
            return isinstance(bound.value, int) and bound.value > 0
        # a computed bound (maxsize=queue_depth) is accepted: the rule
        # polices the unbounded DEFAULT, not the sizing policy
        return True


#: predictor policy-table internals whose direct access bypasses the
#: versioned publish path
_PARAMS_ATTRS = {"_params", "_policies"}
_PREDICTORISH_FRAGMENTS = ("pred", "serving")


def _predictorish(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and any(f in name.lower() for f in _PREDICTORISH_FRAGMENTS):
            return True
    return False


class UnversionedParamsReadRule(Rule):
    """A10: direct ``update_params``/params-table access on a predictor
    outside the versioned params plane (``pod/``, ``predict/``).

    The pod's staleness accounting (docs/pod.md) rests on ONE invariant:
    every parameter publish into a serving predictor goes through a
    versioned path — the learner's counted publish or the actor-host
    :class:`StaleParamsCache` — so each experience block's version stamp
    actually names the policy that produced it. A stray
    ``predictor.update_params(...)`` (or a poke at the ``_params``/
    ``_policies`` policy table) silently serves weights NO version names:
    the learner's measured ``params_lag`` becomes a lie and the
    ``--max_staleness`` bound guards the wrong quantity. The sanctioned
    call sites — the Trainer's synchronous single-host publish (its
    version IS the train step) and the FanoutPredictors fan-out facade —
    carry suppressions stating exactly that; everything else routes
    through the cache (pod/cache.py ``on_update``). ``predict/`` itself
    is exempt (the predictor owns its table), as is ``pod/`` (the plane
    being protected).
    """

    id = "A10"
    name = "unversioned-params-read"
    summary = "predictor params published/read outside the versioned pod params plane"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.path.replace(os.sep, "/").split("/")
        if "pod" in parts or "predict" in parts:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "update_params"
                    # predictor-ish receivers only (same filter as the
                    # attribute branch): an unrelated object with an
                    # update_params method must not force a bogus
                    # suppression that dilutes the audit trail
                    and _predictorish(fn.value)
                ):
                    yield ctx.finding(
                        self, node,
                        ".update_params() outside the versioned params "
                        "plane — publish through the pod cache "
                        "(pod/cache.py on_update) or a sanctioned "
                        "learner-publish site with a suppression naming "
                        "its version source (docs/pod.md)",
                    )
            elif isinstance(node, ast.Attribute):
                if (
                    node.attr in _PARAMS_ATTRS
                    and _predictorish(node.value)
                ):
                    yield ctx.finding(
                        self, node,
                        f"direct .{node.attr} access on a predictor — the "
                        "policy table is the predictor's own; readers go "
                        "through predict_batch/update_params so the "
                        "version accounting holds (docs/pod.md)",
                    )


#: span-constructor call names (telemetry/tracing.py): the class used as
#: `with tracing.span(...)` or explicitly `.finish()`ed
_SPAN_CTOR_SUFFIXES = ("tracing.span",)

#: metric-name tokens that mark a monotonic subtraction as latency math
#: (A7's token set plus the trace plane's own vocabulary)
_LATENCY_TOKENS = _METRIC_NAME_TOKENS | {"hop", "e2e"}

#: consuming attributes that make a monotonic pair SANCTIONED in place:
#: the value flows straight into the telemetry plane
_TELEMETRY_SINKS = {"observe", "record", "hop", "finish_span", "set"}


class OrphanSpanRule(Rule):
    """A11: a span started outside a context manager / without finish(),
    or ad-hoc ``time.monotonic()`` pair latency math outside ``telemetry/``.

    The trace plane (telemetry/tracing.py, docs/observability.md) only
    attributes wall-clock that actually reaches the span buffer: a
    ``tracing.span(...)`` constructed bare — not as a ``with`` item, not
    ``finish()``ed on every exit path — buffers NOTHING (its duration
    silently never lands, and the per-hop ``hop_<name>_s`` histogram the
    exporters serve stays empty), which is strictly worse than no
    instrumentation because the call site LOOKS covered. And a
    hand-rolled ``latency = time.monotonic() - t0`` that feeds a print or
    a local is A7's ad-hoc-metric hazard with the monotonic clock — right
    clock, wrong sink: route it through a Histogram ``observe`` or a span
    hop so every exporter sees it. Monotonic pairs flowing directly into
    ``.observe(...)``/``.hop(...)``/``record(...)`` in the same statement
    are the sanctioned shape; ``telemetry/`` itself is exempt (something
    has to implement the plane).
    """

    id = "A11"
    name = "orphan-span"
    summary = "span without context-manager/finish(), or ad-hoc monotonic-pair latency math"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_telemetry = (
            "telemetry" in ctx.path.replace(os.sep, "/").split("/")
        )
        if not in_telemetry:
            yield from self._check_monotonic_pairs(ctx)
        yield from self._check_orphan_spans(ctx)

    # -- half 1: tracing.span(...) lifecycle -------------------------------
    def _check_orphan_spans(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.info.resolve(node.func)
            if not resolved or not (
                resolved.endswith(_SPAN_CTOR_SUFFIXES) or resolved == "span"
            ):
                continue
            if self._is_with_item(node):
                continue
            stmt = enclosing_statement(node)
            var = self._assigned_name(stmt, node)
            if var is not None and self._finished_in_scope(node, var):
                continue
            yield ctx.finding(
                self, node,
                "span constructed outside a `with` and never .finish()ed "
                "on this path — its duration never reaches the span "
                "buffer or the hop_<name>_s histogram; use `with "
                "tracing.span(...) as s:` or finish() on every exit "
                "(telemetry/tracing.py)",
            )

    @staticmethod
    def _is_with_item(call: ast.Call) -> bool:
        p = parent(call)
        return isinstance(p, ast.withitem) and p.context_expr is call

    @staticmethod
    def _assigned_name(stmt, call) -> "str | None":
        if isinstance(stmt, ast.Assign) and stmt.value is call:
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    return t.id
        return None

    @staticmethod
    def _finished_in_scope(node: ast.AST, var: str) -> bool:
        scope: ast.AST = node
        for cur in ancestors(node):
            scope = cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        for sub in ast.walk(scope):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "finish"
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == var
            ):
                return True
        return False

    # -- half 2: monotonic pair latency math -------------------------------
    def _check_monotonic_pairs(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.info.resolve(node.func) != "time.monotonic":
                continue
            sub = self._enclosing_subtraction(node)
            if sub is None:
                continue
            if self._feeds_telemetry_sink(sub):
                continue
            stmt = enclosing_statement(node)
            if stmt is None or not self._stmt_mentions_latency(stmt):
                continue
            yield ctx.finding(
                self, node,
                "time.monotonic() pair latency math outside telemetry/ — "
                "feed the duration to a Histogram .observe() or a span "
                "hop in the same statement so the scrape endpoint / "
                "stat.json / trace plane all see it (A7's intent, "
                "monotonic edition)",
            )

    @staticmethod
    def _enclosing_subtraction(node: ast.AST) -> Optional[ast.BinOp]:
        for cur in ancestors(node):
            if isinstance(cur, ast.BinOp) and isinstance(cur.op, ast.Sub):
                return cur
            if isinstance(cur, ast.stmt):
                return None
        return None

    @staticmethod
    def _feeds_telemetry_sink(sub: ast.BinOp) -> bool:
        # the subtraction is an ARGUMENT of an .observe()/.hop()/record()
        # call in the same expression — the sanctioned in-place shape
        for cur in ancestors(sub):
            if isinstance(cur, ast.Call):
                fn = cur.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None
                )
                if name in _TELEMETRY_SINKS:
                    return True
            if isinstance(cur, ast.stmt):
                return False
        return False

    @staticmethod
    def _stmt_mentions_latency(stmt: ast.stmt) -> bool:
        for sub in ast.walk(stmt):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if not name:
                continue
            low = name.lower()
            if low in _NON_METRIC_NAMES:
                continue
            if not _LATENCY_TOKENS.isdisjoint(low.split("_")):
                return True
            if "persec" in low.replace("_", ""):
                return True
        return False


#: flag fragments that mark a send/recv as explicitly non-blocking
_NOBLOCK_FRAGMENTS = ("NOBLOCK", "DONTWAIT")

#: setsockopt names that give a socket's blocking ops a bounded timeout
_TIMEOUT_SOCKOPTS = ("RCVTIMEO", "SNDTIMEO")


def _has_noblock_flag(ctx: FileContext, call: ast.Call) -> bool:
    exprs = list(call.args) + [kw.value for kw in call.keywords]
    for e in exprs:
        for sub in ast.walk(e):
            nm = dotted_name(sub)
            if nm and any(f in nm for f in _NOBLOCK_FRAGMENTS):
                return True
    return False


def _scope_has_bounded_poll(node: ast.AST) -> bool:
    """The enclosing function contains a ``.poll(<timeout>)`` call — the
    Poller-guarded loop shape, where the recv only fires on POLLIN and
    the wait itself is bounded by the poll timeout."""
    fns = enclosing_functions(node)
    scope = fns[0] if fns else None
    if scope is None:
        return False
    for sub in ast.walk(scope):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute) and f.attr == "poll":
            if sub.args or any(kw.arg == "timeout" for kw in sub.keywords):
                return True
    return False


def _file_sets_socket_timeout(ctx: FileContext) -> bool:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "setsockopt"):
            continue
        for arg in node.args:
            nm = dotted_name(arg)
            if nm and any(nm.endswith(o) for o in _TIMEOUT_SOCKOPTS):
                return True
    return False


class UnboundedSocketWaitRule(Rule):
    """A12: blocking ZMQ recv/send with no Poller timeout and no
    RCVTIMEO/SNDTIMEO.

    A bare ``sock.recv()`` parks its thread until the peer speaks — and a
    partitioned peer never does. Every wedge netchaos reproduces reduces
    to exactly this shape: the wait has no bound, so neither the stop
    flag nor the link-state machine is ever consulted again and the
    thread is lost to the partition (docs/netchaos.md). A wire op must
    either (a) run inside a Poller-guarded loop whose ``poll(timeout)``
    bounds the wait, (b) pass ``zmq.NOBLOCK``/``DONTWAIT``, or (c) run on
    a socket the file configures with ``RCVTIMEO``/``SNDTIMEO``. The
    sanctioned exceptions are the lockstep env-server client loops —
    parking in recv awaiting the action reply IS their protocol, and the
    supervisor owns their lifetime — which carry suppressions saying so.
    """

    id = "A12"
    name = "unbounded-socket-wait"
    summary = "blocking ZMQ recv/send with no Poller timeout or RCVTIMEO/SNDTIMEO"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        file_timeout = _file_sets_socket_timeout(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute) or fn.attr not in _WIRE_OPS:
                continue
            if not _socket_ish(fn.value):
                continue
            if _has_noblock_flag(ctx, node):
                continue
            if _scope_has_bounded_poll(node):
                continue
            if file_timeout:
                continue
            yield ctx.finding(
                self, node,
                f"blocking .{fn.attr}() with no bound — a partitioned peer "
                "parks this thread forever: guard it with a Poller "
                "poll(timeout) loop, pass zmq.NOBLOCK, or set "
                "RCVTIMEO/SNDTIMEO (docs/netchaos.md); lockstep env-server "
                "clients suppress with the protocol justification",
            )


#: function-name fragments that mark a def as the train-ingest path: the
#: collates, the masters' flush/emit sites, the pod's block staging, and
#: the lazy views' materializations — where obs bytes move between the
#: wire/ring and the learner's staging
_INGEST_FN_FRAGMENTS = (
    "collate", "flush", "emit", "ingest", "to_block", "__array__",
    "stage_group",
)

#: the copy constructors the staging discipline replaces
_COPY_CALLS = {"numpy.stack", "numpy.ascontiguousarray", "numpy.concatenate"}

#: the ONE module allowed to copy obs bytes on the ingest path
_STAGING_MODULE = "data/staging.py"


class IngestExtraCopyRule(Rule):
    """A13: ``np.stack``/``np.ascontiguousarray``/``.copy()`` on the
    train-ingest path outside ``data/staging.py``.

    The ingest copy budget (docs/ingest.md) is ONE host pass per block:
    shm-ring/wire bytes → the staging write; ``plane_bench --ingest``
    gates ``ingest_copies_total / ingest_blocks_total == 1`` on it. A
    fresh stack/contiguous-copy/`.copy()` inside a collate, flush/emit,
    or block-staging function re-grows exactly the materialize→stack→
    transpose chain the staging subsystem retired — every byte it copies
    is a second pass the budget no longer accounts for. Route the bytes
    through the in-place collates (``collate_*_into``) / the stagers, or
    suppress with the justification for why this site is sanctioned (the
    per-env compat foil's stack, the legacy collate fallbacks, and the
    lazy views' ``__array__`` compat materializations carry exactly such
    suppressions). The rule scopes to functions whose names mark the
    ingest path — copies elsewhere are someone else's budget.
    """

    id = "A13"
    name = "ingest-extra-copy"
    summary = "obs-byte copy (stack/ascontiguousarray/.copy) on the train-ingest path outside data/staging.py"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        path = ctx.path.replace(os.sep, "/")
        if path.endswith(_STAGING_MODULE):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            low = fn.name.lower()
            if not any(f in low for f in _INGEST_FN_FRAGMENTS):
                continue
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: FileContext, fn: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # nested defs get their own scope decision (a non-ingest
            # closure inside a flush fn is still the flush path; keep it)
            resolved = ctx.info.resolve(node.func)
            if resolved in _COPY_CALLS:
                short = resolved.rsplit(".", 1)[-1]
                yield ctx.finding(
                    self, node,
                    f"np.{short} on the train-ingest path — the copy "
                    "budget is ONE staging write per block "
                    "(data/staging.py collate_*_into / BlockStager); a "
                    "sanctioned compat copy needs a suppression saying "
                    "why (docs/ingest.md)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "copy"
                and not node.args
                and not node.keywords
                and isinstance(node.func.value, (ast.Call, ast.Subscript))
            ):
                # array-expression .copy() (np.swapaxes(...).copy(),
                # arr[...].copy()) — dict/list .copy() on plain names
                # stays out of scope
                yield ctx.finding(
                    self, node,
                    ".copy() of an array expression on the train-ingest "
                    "path — write into the staging slot instead "
                    "(collate_*_into), or suppress with the sanction",
                )


#: the serving plane's front-door types; constructing one directly outside
#: predict/ (or dispatching at one so constructed) bypasses the router's
#: health/overflow/canary machinery and the sanctioned predictor factories
_PREDICTOR_CTOR_BARE = {"BatchedPredictor"}
_PREDICTOR_CTOR_SUFFIXES = (".BatchedPredictor",)
_PREDICTOR_DISPATCH_ATTRS = {"put_task", "put_block_task"}


class UnroutedPredictorDispatchRule(Rule):
    """A14: ``BatchedPredictor`` constructed — or dispatched at, when
    locally constructed — outside ``predict/`` and the sanctioned
    factories.

    The serving tier is ROUTED (predict/router.py, docs/serving.md): R
    replicas behind health-checked least-loaded dispatch with
    deadline-aware overflow, replica autoscaling and the canary
    promotion loop. A ``BatchedPredictor`` constructed ad hoc outside
    ``predict/`` is a serving plane nothing routes, nothing health-checks
    and nothing autoscales — its traffic bypasses the overflow path (so
    its overload sheds instead of failing over) and its policy table
    drifts from the router's (a promotion never reaches it). Construction
    belongs to the sanctioned factories — cli.py's ``make_predictor``
    (handed to the fleet assembly), the pod host's versioned-cache-fed
    predictor, orchestrate/serving.py's ``ReplicaSet`` factory — each of
    which carries the suppression naming why its lifecycle is owned
    (bench/test null planes are the raw measurand and suppress the same
    way). Dispatch (``put_task``/``put_block_task``) is flagged only on
    receivers ASSIGNED from a flagged construction in the same file:
    masters dispatching whatever predictor-or-router they were handed
    stay clean by construction — injection IS the sanctioned shape.
    """

    id = "A14"
    name = "unrouted-predictor-dispatch"
    summary = "BatchedPredictor constructed/dispatched outside predict/ bypasses the routed serving plane"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "predict" in ctx.path.replace(os.sep, "/").split("/"):
            return
        local_names: Set[str] = set()
        ctor_nodes = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.info.resolve(node.func)
            if resolved and (
                resolved in _PREDICTOR_CTOR_BARE
                or resolved.endswith(_PREDICTOR_CTOR_SUFFIXES)
            ):
                ctor_nodes.append(node)
                p = parent(node)
                if isinstance(p, ast.Assign):
                    for t in p.targets:
                        local_names |= _target_names(t)
                elif isinstance(p, ast.AnnAssign):
                    local_names |= _target_names(p.target)
        for node in ctor_nodes:
            yield ctx.finding(
                self, node,
                "direct BatchedPredictor construction outside predict/ — "
                "an unrouted serving plane (no health checks, no "
                "overflow, no canary reach); route through the sanctioned "
                "factories (cli.py make_predictor / ReplicaSet) or "
                "suppress naming who owns this plane's lifecycle "
                "(docs/serving.md)",
            )
        if not local_names:
            return
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _PREDICTOR_DISPATCH_ATTRS
            ):
                names = {
                    n.id
                    for n in ast.walk(node.func.value)
                    if isinstance(n, ast.Name)
                }
                if names & local_names:
                    yield ctx.finding(
                        self, node,
                        f".{node.func.attr}() at a locally-constructed "
                        "BatchedPredictor — serving traffic belongs on "
                        "the router (or an injected predictor handle); "
                        "this dispatch bypasses overflow/health/canary "
                        "routing (docs/serving.md)",
                    )


#: liveness probes a hand-rolled supervision loop polls
_LIVENESS_POLL_ATTRS = {"is_alive", "poll"}
#: respawn moves the same loop makes — .start() on a thread/process
#: handle, or a fresh subprocess
_RESPAWN_ATTRS = {"start"}


class AdhocLifecycleLoopRule(Rule):
    """A15: hand-rolled spawn/health-poll supervision loop outside
    ``orchestrate/``.

    The reconciler (orchestrate/reconcile.py, docs/topology.md) is the
    ONE loop that observes liveness and respawns: per-resource
    exponential backoff, the topology-wide restart-budget circuit
    breaker, ``tele/reconciler/*`` accounting and a flight-recorded
    decision trail for every heal. A ``while``/``for`` loop elsewhere
    whose body both polls liveness (``.is_alive()``/``.poll()``) and
    spawns (``.start()``/``subprocess.Popen``) is a shadow supervisor:
    its respawns are unbudgeted (a crash loop spins at poll speed with
    no breaker), uncounted (the drift gauge and heal counters never see
    them) and unexplainable post-hoc (no decision trail). Implement the
    lifecycle as a :class:`Reconcilable` resource driven by the
    Reconciler instead, or suppress with the justification for why this
    loop's respawns are otherwise budgeted and accounted (an acceptance
    bench that IS the measurand of supervision, a test double).
    Loops that only poll (a wait-for-exit) or only spawn (a launch
    fan-out) stay clean — the hazard is the closed observe+respawn
    cycle.
    """

    id = "A15"
    name = "adhoc-lifecycle-loop"
    summary = "spawn/health-poll supervision loop outside orchestrate/ shadows the reconciler"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "orchestrate" in ctx.path.replace(os.sep, "/").split("/"):
            return
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            polls = spawns = False
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr in _LIVENESS_POLL_ATTRS:
                        polls = True
                    elif node.func.attr in _RESPAWN_ATTRS:
                        spawns = True
                resolved = ctx.info.resolve(node.func)
                if resolved and (
                    resolved in _SUBPROCESS_SPAWNERS
                    or resolved.endswith(".Popen")
                ):
                    spawns = True
            if polls and spawns:
                yield ctx.finding(
                    self, loop,
                    "loop both polls liveness and spawns — a shadow "
                    "supervisor with no backoff, no restart budget, no "
                    "heal accounting; make it a Reconcilable resource "
                    "driven by the orchestrate/ Reconciler, or suppress "
                    "naming who budgets these respawns "
                    "(docs/topology.md)",
                )


#: the two dtypes the quantized-rollout ladder owns end to end — a cast to
#: either outside the sanctioned sites IS a new serving numerics rung
_QUANT_CAST_DTYPES = {"bfloat16", "int8"}
#: path segments that carry the params-publish/actor-forward path
_QUANT_CAST_SEGMENTS = {"predict", "fused", "pod"}


def _quant_cast_dtype(node: Optional[ast.AST]) -> Optional[str]:
    """The bf16/int8 dtype a cast target names, else None — matches both
    the ``jnp.bfloat16`` attribute form and the ``"int8"`` string form."""
    if isinstance(node, ast.Attribute) and node.attr in _QUANT_CAST_DTYPES:
        return node.attr
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value in _QUANT_CAST_DTYPES
    ):
        return node.value
    return None


class UnauditedDtypeCastRule(Rule):
    """A16: ad-hoc bf16/int8 cast on the params-publish/actor-forward
    path outside ``quantize/``.

    The rollout-precision ladder is AUDIT-PINNED: every dtype the serving
    and actor forwards run at has a registered entry point
    (predict.server_bf16 / fused.actor_bf16 / predict.server_int8 /
    fused.actor_int8) whose compiled program the manifest's T1/T5 rows
    structurally pin. An ``astype(jnp.bfloat16)`` /
    ``lax.convert_element_type(..., jnp.int8)`` added ad hoc in
    ``predict/``, ``fused/`` or ``pod/`` is a serving-numerics change no
    audit sees: the program it produces has no entry, no byte census, no
    parity band — a precision regression (or an accidental double-cast)
    ships silently. Quantizing casts belong to ``quantize/`` (the int8
    rung's one home: per-channel scales, calibrated activation ranges,
    the audited epilogue) or to THE audited publish-cast site, which
    carries the suppression naming its entry. Everything else on this
    path hands dtype selection to ``rollout_dtype`` and the sanctioned
    cast hooks. f32 casts stay clean — the ladder's base rung is not a
    quantization.
    """

    id = "A16"
    name = "unaudited-dtype-cast"
    summary = "ad-hoc bf16/int8 cast on the publish/actor-forward path outside quantize/ dodges the audit"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        segs = set(ctx.path.replace(os.sep, "/").split("/"))
        if not segs & _QUANT_CAST_SEGMENTS or "quantize" in segs:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            dt = None
            via = None
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "astype"
                and node.args
            ):
                dt = _quant_cast_dtype(node.args[0])
                via = "astype"
            else:
                resolved = ctx.info.resolve(fn)
                if resolved and resolved.endswith("convert_element_type"):
                    target = node.args[1] if len(node.args) > 1 else next(
                        (
                            kw.value for kw in node.keywords
                            if kw.arg == "new_dtype"
                        ),
                        None,
                    )
                    dt = _quant_cast_dtype(target)
                    via = "convert_element_type"
            if dt:
                yield ctx.finding(
                    self, node,
                    f"ad-hoc {via} to {dt} on the publish/actor-forward "
                    "path — a serving-numerics change no audit entry "
                    "pins; route it through rollout_dtype + quantize/ "
                    "(or suppress naming the audited entry this cast "
                    "feeds — docs/static_analysis.md)",
                )


ACTOR_RULES = [
    BareThreadRule(),
    BlockingQueueOpRule(),
    CrossThreadClientMutationRule(),
    WallClockArithRule(),
    PrivateImportRule(),
    PerEnvWireLoopRule(),
    AdhocMetricRule(),
    UnsupervisedFleetSpawnRule(),
    ServingHotPathBlockRule(),
    UnversionedParamsReadRule(),
    OrphanSpanRule(),
    UnboundedSocketWaitRule(),
    IngestExtraCopyRule(),
    UnroutedPredictorDispatchRule(),
    AdhocLifecycleLoopRule(),
    UnauditedDtypeCastRule(),
]
