"""J-series rules: JAX hot-path hazards (host syncs, retraces, key reuse).

IMPALA-style stacks lose their throughput to silent host syncs and
recompiles long before they lose it to math; these rules flag the patterns
that have bitten this repo (see PERF.md: one device->host fetch costs ~135ms
on a tunneled TPU regardless of payload). Rationale and worked examples in
docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.ba3clint.engine import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    enclosing_functions,
    enclosing_loop,
    enclosing_statement,
)

_SYNC_FNS = {"jax.device_get", "jax.block_until_ready"}
_HOST_CAST_FNS = {"numpy.asarray", "numpy.array", "np.asarray", "np.array"}


def _in_jitted_scope(ctx: FileContext, node: ast.AST) -> bool:
    return any(
        fn.name in ctx.info.jitted_fn_defs for fn in enclosing_functions(node)
    )


def _contains_jitted_call(ctx: FileContext, node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            nm = dotted_name(sub.func)
            if nm and nm in ctx.info.jitted:
                return True
    return False


class HostSyncHotPathRule(Rule):
    """J1: host synchronization inside a per-step loop or a jitted function.

    ``jax.device_get``/``.block_until_ready()`` force the host to wait for
    the device; inside a step loop they serialize dispatch and execution
    (the async-dispatch overlap the trainer depends on disappears). Inside a
    function that gets jitted they either fail at trace time or silently
    bake a constant. ``np.asarray``/``float()`` on the result of a jitted
    call is the same sync wearing a numpy hat.
    """

    id = "J1"
    name = "host-sync-hot-path"
    summary = "device_get/block_until_ready/np.asarray-on-jitted inside a loop or jitted fn"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.info.resolve(node.func)
            is_sync = resolved in _SYNC_FNS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            )
            if is_sync:
                if enclosing_loop(node) is not None:
                    yield ctx.finding(
                        self, node,
                        "host sync inside a loop body serializes dispatch — "
                        "hoist it out of the hot loop (fetch once per "
                        "epoch/window)",
                    )
                elif _in_jitted_scope(ctx, node):
                    yield ctx.finding(
                        self, node,
                        "host sync inside a function that gets jitted — "
                        "it fails at trace time or bakes a constant",
                    )
                continue
            is_cast = resolved in _HOST_CAST_FNS or (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int")
            )
            if not is_cast or not node.args:
                continue
            if _in_jitted_scope(ctx, node) and resolved in _HOST_CAST_FNS:
                yield ctx.finding(
                    self, node,
                    "np.asarray/np.array inside a function that gets jitted "
                    "— use jnp, or move the host conversion outside the "
                    "traced scope",
                )
            elif enclosing_loop(node) is not None and _contains_jitted_call(
                ctx, node.args[0]
            ):
                yield ctx.finding(
                    self, node,
                    "host cast of a jitted call's result inside a loop — "
                    "this blocks on the device every iteration",
                )


class JitInLoopRule(Rule):
    """J2: ``jax.jit`` constructed inside a loop body.

    Each ``jax.jit(f)`` call creates a fresh compilation cache; inside a
    loop that means retracing (and often recompiling) every iteration.
    Construct the jitted callable once, outside the loop.
    """

    id = "J2"
    name = "jit-in-loop"
    summary = "jax.jit(...) constructed inside a loop body retraces every iteration"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.info.resolve(node.func) not in (
                "jax.jit", "jax.pjit", "jit", "pjit"
            ):
                continue
            if enclosing_loop(node) is not None:
                yield ctx.finding(
                    self, node,
                    "jax.jit constructed inside a loop — each call makes a "
                    "fresh cache and retraces; hoist the jit out of the loop",
                )


class NonStaticJitArgRule(Rule):
    """J3: dict/list/set/str literal passed to a jitted callable.

    Container literals passed positionally to a jitted function are traced
    as pytrees — fine for arrays, but a literal of Python scalars/strings
    retraces on every distinct value, and an intended-static string arg
    raises unless marked ``static_argnums``. Passing the literal inline is
    the tell that the call site thinks it is passing configuration.
    """

    id = "J3"
    name = "nonstatic-jit-arg"
    summary = "dict/list/str literal passed to a jitted fn (retrace/static_argnums hazard)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = dotted_name(node.func)
            if not nm or nm not in ctx.info.jitted:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Dict, ast.List, ast.Set)) or (
                    isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                ):
                    yield ctx.finding(
                        self, arg,
                        f"literal {type(arg).__name__.lower()} passed to "
                        f"jitted `{nm}` — non-array/config args retrace per "
                        "value or need static_argnums; build arrays outside "
                        "the call",
                    )


_KEY_DERIVE_FNS = {"split", "fold_in", "clone", "key_data", "wrap_key_data"}


class PRNGKeyReuseRule(Rule):
    """J4: a PRNGKey used by more than one sampler (or in a loop) unsplit.

    Passing the same key to two sampling calls produces *identical*
    randomness — silently correlated exploration, identical dropout masks.
    Every consumption must go through ``jax.random.split``/``fold_in``.
    """

    id = "J4"
    name = "prngkey-reuse"
    summary = "PRNGKey consumed more than once (or in a loop) without split/fold_in"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: FileContext, fn: ast.AST) -> Iterator[Finding]:
        keys: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                resolved = ctx.info.resolve(node.value.func)
                if resolved in ("jax.random.PRNGKey", "jax.random.key"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            keys.add(t.id)
        if not keys:
            return

        derived: Set[str] = set()
        uses: Dict[str, List[ast.Call]] = {k: [] for k in keys}
        looped: Dict[str, List[ast.Call]] = {k: [] for k in keys}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if attr is None:
                continue
            arg_names = {
                a.id for a in node.args if isinstance(a, ast.Name)
            } | {
                kw.value.id
                for kw in node.keywords
                if isinstance(kw.value, ast.Name)
            }
            hit = arg_names & keys
            if not hit:
                continue
            if attr in _KEY_DERIVE_FNS:
                derived |= hit
                continue
            resolved = ctx.info.resolve(f) or ""
            if not resolved.startswith("jax.random."):
                continue  # passing the key onward is the callee's problem
            for k in hit:
                uses[k].append(node)
                if enclosing_loop(node) is not None:
                    looped[k].append(node)

        for k in sorted(keys):
            if k in derived:
                continue
            if looped[k]:
                yield ctx.finding(
                    self, looped[k][0],
                    f"PRNGKey `{k}` consumed inside a loop without "
                    "jax.random.split — identical randomness every iteration",
                )
            elif len(uses[k]) >= 2:
                yield ctx.finding(
                    self, uses[k][1],
                    f"PRNGKey `{k}` consumed by multiple sampling calls "
                    "without jax.random.split — the draws are identical",
                )


class ReadAfterDonateRule(Rule):
    """J5: reading an argument after passing it to a donating jit.

    ``donate_argnums`` hands the buffer to XLA for reuse; a later host read
    of the donated array returns garbage or crashes in native code
    (the trainer copies params before publishing for exactly this reason).
    """

    id = "J5"
    name = "read-after-donate"
    summary = "variable read after being donated to a jitted call (donate_argnums)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        donating = {
            name: pos for name, pos in ctx.info.jitted.items() if pos
        }
        if not donating:
            return
        seen: Set[Tuple[int, int]] = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # the same call can appear in several nested blocks — report a
            # given read site once
            for block in self._blocks(fn):
                for f in self._check_block(ctx, donating, block):
                    key = (f.line, f.col)
                    if key not in seen:
                        seen.add(key)
                        yield f

    @staticmethod
    def _blocks(fn: ast.AST) -> Iterator[List[ast.stmt]]:
        yield fn.body
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.While, ast.If, ast.With)):
                yield node.body
                if getattr(node, "orelse", None):
                    yield node.orelse

    def _check_block(
        self,
        ctx: FileContext,
        donating: Dict[str, Tuple[int, ...]],
        block: List[ast.stmt],
    ) -> Iterator[Finding]:
        for i, stmt in enumerate(block):
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                nm = dotted_name(call.func)
                if not nm or nm not in donating:
                    continue
                # rebinds are judged at the call's OWN assignment (the call
                # may sit inside a compound statement within this block)
                rebound = self._stmt_targets(enclosing_statement(call) or stmt)
                for pos in donating[nm]:
                    if pos >= len(call.args):
                        continue
                    arg = call.args[pos]
                    if not isinstance(arg, ast.Name) or arg.id in rebound:
                        continue
                    use = self._later_read(block[i + 1:], arg.id)
                    if use is not None:
                        yield ctx.finding(
                            self, use,
                            f"`{arg.id}` was donated to jitted `{nm}` "
                            "(donate_argnums) and read afterwards — the "
                            "buffer may already be reused; jnp.copy before "
                            "the call or rebind the result",
                        )

    @staticmethod
    def _stmt_targets(stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for el in elts:
                if isinstance(el, ast.Name):
                    out.add(el.id)
        return out

    def _later_read(
        self, rest: List[ast.stmt], name: str
    ) -> Optional[ast.AST]:
        for stmt in rest:
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Name)
                    and node.id == name
                    and isinstance(node.ctx, ast.Load)
                ):
                    return node
            if name in self._stmt_targets(stmt):
                return None  # rebound before any read
        return None


_J6_SYNC_ATTRS = {"block_until_ready", "item"}
_J6_CAST_NAMES = {"float", "int", "bool"}


class OverlapSyncHazardRule(Rule):
    """J6: host sync on actor-program outputs between the two dispatches.

    The overlap schedule (fused/overlap.py, docs/overlap.md) exists so the
    runtime can execute rollout k+1 concurrently with learner k. A
    ``block_until_ready``/``device_get``/``.item()``/``np.asarray``/
    ``float()`` on the ACTOR program's outputs after the actor dispatch and
    before the learner dispatch forces the rollout to complete before the
    learner is even enqueued — it re-serializes the two programs and
    silently refutes the whole split, while every test stays green.

    Heuristic, tuned to the repo idiom: inside a function that calls both
    an actor-named callable (last dotted segment contains ``actor``) and a
    learner-named one (contains ``learner``), any sync-consuming use of a
    name bound from the actor call, positioned after that actor call and
    before a later learner call, is flagged. The one sanctioned site is
    the measurement probe (``probe_overlap``), which exists to measure the
    serialization this rule forbids — its suppressions carry the
    justification.
    """

    id = "J6"
    name = "overlap-sync-hazard"
    summary = "host sync on actor-program outputs between the actor and learner dispatches"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_fn(ctx, fn)

    @staticmethod
    def _last_segment(call: ast.Call) -> str:
        nm = dotted_name(call.func)
        return nm.rsplit(".", 1)[-1].lower() if nm else ""

    def _check_fn(self, ctx: FileContext, fn: ast.AST) -> Iterator[Finding]:
        actor_calls: List[ast.Call] = []
        learner_lines: List[int] = []
        # nested defs get their own _check_fn pass — only look at calls
        # whose innermost enclosing function is THIS one
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            encl = enclosing_functions(node)
            if not encl or encl[0] is not fn:
                continue
            seg = self._last_segment(node)
            if "actor" in seg:
                actor_calls.append(node)
            elif "learner" in seg:
                learner_lines.append(node.lineno)
        if not actor_calls or not learner_lines:
            return

        # names bound from an actor call (tuple unpack included)
        actor_outputs: Dict[str, int] = {}  # name -> actor call line
        for call in actor_calls:
            stmt = enclosing_statement(call)
            if not isinstance(stmt, ast.Assign) or stmt.value is not call:
                continue
            for t in stmt.targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for el in elts:
                    if isinstance(el, ast.Name):
                        actor_outputs[el.id] = call.lineno
        if not actor_outputs:
            return
        last_learner = max(learner_lines)

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            encl = enclosing_functions(node)
            if not encl or encl[0] is not fn:
                continue
            hit = self._synced_actor_output(ctx, node, actor_outputs)
            if hit is None:
                continue
            name, actor_line = hit
            # "between the two dispatches": after the actor call that
            # bound the name, before the last learner dispatch
            if actor_line < node.lineno <= last_learner:
                yield ctx.finding(
                    self, node,
                    f"host sync on actor-program output `{name}` between "
                    "the actor and learner dispatches — this forces the "
                    "rollout to finish before the learner is enqueued, "
                    "re-serializing the overlapped programs; sync after "
                    "both dispatches (or once per window)",
                )

    @staticmethod
    def _synced_actor_output(
        ctx: FileContext, call: ast.Call, actor_outputs: Dict[str, int]
    ) -> Optional[Tuple[str, int]]:
        """(name, actor line) if ``call`` host-syncs an actor output."""

        def names_in(expr: ast.AST) -> Iterator[str]:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name):
                    yield sub.id

        f = call.func
        resolved = ctx.info.resolve(f)
        is_sync_fn = resolved in _SYNC_FNS or resolved in _HOST_CAST_FNS or (
            isinstance(f, ast.Name) and f.id in _J6_CAST_NAMES
        )
        if is_sync_fn:
            for arg in call.args:
                for nm in names_in(arg):
                    if nm in actor_outputs:
                        return nm, actor_outputs[nm]
            return None
        if isinstance(f, ast.Attribute) and f.attr in _J6_SYNC_ATTRS:
            for nm in names_in(f.value):
                if nm in actor_outputs:
                    return nm, actor_outputs[nm]
        return None


JAX_RULES = [
    HostSyncHotPathRule(),
    JitInLoopRule(),
    NonStaticJitArgRule(),
    PRNGKeyReuseRule(),
    ReadAfterDonateRule(),
    OverlapSyncHazardRule(),
]
