"""ba3clint engine: AST plumbing, suppression parsing, file walking.

The framework is deliberately tiny: a rule is a class with an ``id`` and a
``check(ctx)`` generator; the engine parses each file once, annotates parent
links, precomputes module facts every rule needs (import aliases, names bound
to ``jax.jit(...)`` results, donated-argument positions), runs every rule,
and filters findings through per-line ``# ba3clint: disable=RULE`` comments.

Heuristics over proofs: rules are tuned to this repo's idioms (see
docs/static_analysis.md). When a rule is wrong about a specific line, the
fix is an inline suppression WITH a justification comment — that is a
feature: the invariant becomes visible at the use site.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# The suppression grammar, the S001 audit, and Finding itself are shared by
# the whole analyzer family and live in tools.analyzer_core; they are
# re-exported here because this module is their historical home (every rule
# module and test imports them from tools.ba3clint.engine).
from tools.analyzer_core import (  # noqa: F401  (re-exports)
    Finding,
    comment_tokens as _comment_tokens,
    suppress_re as _suppress_re,
    stale_suppressions,
    suppressions,
)


class Rule:
    """Base class: subclasses set ``id``/``name``/``summary`` and ``check``."""

    id: str = ""
    name: str = ""
    summary: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------------------
# AST helpers
# --------------------------------------------------------------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def annotate_parents(tree: ast.AST) -> ast.AST:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._ba3c_parent = node  # type: ignore[attr-defined]
    return tree


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_ba3c_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_loop(node: ast.AST) -> Optional[ast.AST]:
    """Nearest For/While ancestor within the same function scope, else None."""
    for cur in ancestors(node):
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        if isinstance(cur, _SCOPE_NODES):
            return None
    return None


def enclosing_statement(node: ast.AST) -> Optional[ast.stmt]:
    if isinstance(node, ast.stmt):
        return node
    for cur in ancestors(node):
        if isinstance(cur, ast.stmt):
            return cur
    return None


def enclosing_functions(node: ast.AST) -> List[ast.AST]:
    """All FunctionDef/AsyncFunctionDef ancestors, innermost first."""
    return [
        cur
        for cur in ancestors(node)
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def chain_root(node: ast.AST) -> ast.AST:
    """Descend Attribute/Subscript/Call chains to the base expression."""
    while True:
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return node


# --------------------------------------------------------------------------
# per-module facts shared by rules
# --------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jax.pjit", "jit", "pjit"}

#: ``audit.tripwire_jit(name, fn, **jit_kwargs)`` — the repo's hot-path jit
#: wrapper (distributed_ba3c_tpu/audit.py). Jit-like for every J-series
#: purpose (donation, traced body, retrace hazards), with the function at
#: positional index 1 instead of 0. Without this entry, switching a site
#: from jax.jit to tripwire_jit would silently blind J5/J3/J1 to exactly
#: the five sites the gate most needs to watch.
_TRIPWIRE_JIT_NAMES = {
    "tripwire_jit",
    "audit.tripwire_jit",
    "distributed_ba3c_tpu.audit.tripwire_jit",
}


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        out.append(el.value)
                return tuple(out)
    return ()


class ModuleInfo:
    """Import aliases + jit bookkeeping computed once per file."""

    def __init__(self, tree: ast.AST):
        #: local alias -> canonical dotted origin ("mp" -> "multiprocessing")
        self.imports: Dict[str, str] = {}
        #: dotted name of a variable/attr bound to a jax.jit(...) result
        #: -> donated positional indices (possibly empty)
        self.jitted: Dict[str, Tuple[int, ...]] = {}
        #: plain function names passed to jax.jit / decorated with it —
        #: their bodies are traced, so host ops inside them are hazards
        self.jitted_fn_defs: Set[str] = set()

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = a.name
                    else:
                        # `import jax.numpy` binds the NAME `jax`, not
                        # `jax.numpy` — mapping the head to the full dotted
                        # path would make jax.jit resolve as jax.numpy.jit
                        head = a.name.split(".")[0]
                        self.imports.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = f"{node.module}.{a.name}"

        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and self._is_jit_call(node.value):
                call = node.value
                donate = _donate_positions(call)
                for t in node.targets:
                    nm = dotted_name(t)
                    if nm:
                        self.jitted[nm] = donate
                fn_idx = self._jit_fn_arg_index(call)
                if len(call.args) > fn_idx:
                    fn = dotted_name(call.args[fn_idx])
                    if fn and "." not in fn:
                        self.jitted_fn_defs.add(fn)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        donate = (
                            _donate_positions(dec)
                            if isinstance(dec, ast.Call)
                            else ()
                        )
                        self.jitted[node.name] = donate
                        self.jitted_fn_defs.add(node.name)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name with the first segment resolved through imports:
        ``_time.time`` -> ``time.time``, ``random.split`` -> ``jax.random.split``
        (for ``from jax import random``)."""
        nm = dotted_name(node)
        if nm is None:
            return None
        head, _, rest = nm.partition(".")
        origin = self.imports.get(head)
        if origin is None:
            return nm
        return f"{origin}.{rest}" if rest else origin

    def _is_jit_call(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and self._is_jit_expr(node)

    def _is_jit_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            node = node.func
        resolved = self.resolve(node)
        return resolved in _JIT_NAMES or resolved in _TRIPWIRE_JIT_NAMES

    def _jit_fn_arg_index(self, node: ast.AST) -> int:
        """Positional index of the traced function in a jit-like call:
        0 for jax.jit/pjit, 1 for tripwire_jit(name, fn, ...)."""
        if isinstance(node, ast.Call):
            node = node.func
        return 1 if self.resolve(node) in _TRIPWIRE_JIT_NAMES else 0


@dataclasses.dataclass
class FileContext:
    path: str
    source: str
    tree: ast.AST
    info: ModuleInfo

    def finding(self, rule: Rule, node: ast.AST, message: str) -> Finding:
        return Finding(
            self.path,
            getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0),
            rule.id,
            message,
        )


# --------------------------------------------------------------------------
# running
# --------------------------------------------------------------------------

def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        if not os.path.isdir(p):
            # a gate must never pass green because its target was mistyped
            # or renamed — "0 findings over 0 files" is not a clean bill
            raise FileNotFoundError(f"lint path does not exist: {p!r}")
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if not d.startswith(".") and d != "__pycache__"
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_file(path: str, rules: Iterable[Rule],
              apply_suppressions: bool = True) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = annotate_parents(ast.parse(source, filename=path))
    except SyntaxError as e:
        return [
            Finding(path, e.lineno or 1, (e.offset or 1) - 1, "E001",
                    f"syntax error: {e.msg}")
        ]
    ctx = FileContext(path, source, tree, ModuleInfo(tree))
    sup = suppressions(source) if apply_suppressions else {}
    out: List[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            disabled = sup.get(f.line, set())
            if "ALL" in disabled or f.rule.upper() in disabled:
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def check_suppressions(paths: Sequence[str],
                       rules: Iterable[Rule]) -> List[Finding]:
    """Stale ``# ba3clint: disable=`` comments across ``paths`` (rule S001)."""
    rules = list(rules)
    out: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        raw = lint_file(path, rules, apply_suppressions=False)
        out.extend(stale_suppressions(source, path, raw, "ba3clint"))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_paths(paths: Sequence[str], rules: Iterable[Rule]) -> List[Finding]:
    rules = list(rules)
    out: List[Finding] = []
    for path in iter_py_files(paths):
        out.extend(lint_file(path, rules))
    return out
