"""ba3clint: repo-specific static analysis for the BA3C actor/learner stack.

Usage:
    python -m tools.ba3clint [paths...] [--format json] [--select A1,J2]
    python -m tools.ba3clint --list-rules

Two rule families (catalog: docs/static_analysis.md):

* **J-series** — JAX hot-path hazards: host syncs in step loops or jitted
  functions (J1), ``jax.jit`` built inside a loop (J2), non-static literal
  args to jitted callables (J3), PRNGKey reuse without ``split`` (J4),
  reading a donated buffer after the call (J5), host syncs on actor-program
  outputs between the overlap schedule's two dispatches (J6).
* **A-series** — actor-plane and API-hygiene conventions: bare threads (A1),
  blocking queue ops without timeouts (A2), cross-thread client-state
  mutation from closures (A3), wall-clock timeout arithmetic (A4),
  from-imports of underscore-private names (A5).

Per-line suppression: ``# ba3clint: disable=A2`` (comma-separate ids;
``disable=all`` kills everything on the line). A standalone comment line
suppresses the following line. Always pair a suppression with the reason it
is safe — the suppression IS the documentation of the invariant.
"""

from __future__ import annotations

from typing import List

from tools.ba3clint.engine import (  # noqa: F401 (public API re-exports)
    FileContext,
    Finding,
    Rule,
    lint_file,
    lint_paths,
)


def all_rules() -> List[Rule]:
    from tools.ba3clint.rules_actor import ACTOR_RULES
    from tools.ba3clint.rules_jax import JAX_RULES

    return list(JAX_RULES) + list(ACTOR_RULES)
