"""Shared SARIF 2.1.0 export for ba3clint and ba3cflow.

One run per invocation, one result per finding. The output is the minimal
schema-valid document github/codeql-action/upload-sarif accepts, so CI can
surface findings as PR annotations without any extra mapping layer. Paths
are emitted repo-relative with ``%SRCROOT%`` as the base id — that is what
the upload action expects when it runs from the checkout root.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional, Sequence

_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"

#: rules that indicate the analysis itself is degraded, not the code
_ERROR_RULES = {"E001"}


def to_sarif(findings: Sequence, tool_name: str, rules: Iterable,
             info_uri: str = "docs/static_analysis.md") -> dict:
    """Build a SARIF dict from :class:`~tools.ba3clint.engine.Finding`s.

    ``rules`` is the rule catalog (objects with ``id``/``name``/``summary``);
    rule metadata is emitted even for rules with no findings so the viewer
    can render the full catalog.
    """
    rule_entries: List[dict] = []
    rule_index = {}
    for r in rules:
        rule_index[r.id] = len(rule_entries)
        rule_entries.append({
            "id": r.id,
            "name": r.name or r.id,
            "shortDescription": {"text": r.summary or r.id},
            "helpUri": info_uri,
        })
    results: List[dict] = []
    for f in findings:
        entry = {
            "ruleId": f.rule,
            "level": "error" if f.rule in _ERROR_RULES else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {
                        "startLine": max(1, f.line),
                        "startColumn": max(1, f.col + 1),
                    },
                },
            }],
        }
        idx = rule_index.get(f.rule)
        if idx is not None:
            entry["ruleIndex"] = idx
        results.append(entry)
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": tool_name,
                    "informationUri": info_uri,
                    "rules": rule_entries,
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, findings: Sequence, tool_name: str,
                rules: Iterable,
                info_uri: str = "docs/static_analysis.md") -> None:
    doc = to_sarif(findings, tool_name, rules, info_uri)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
