"""The compiled-program manifest: load/save audit_manifest.json.

The manifest is the repo's pinned record of what each hot-path entry point's
compiled program looks like at the canonical shapes — FLOPs, HBM bytes,
collective census, conv/dot counts, materialized aliases. CI diffs the live
measurement against it (rules.check_t5); `--update-manifest` rewrites it,
and the reviewed git diff of that rewrite is the change-control for the
compiled program.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

#: reserved manifest key recording the toolchain (jax version) the numbers
#: were measured under — T5 values are XLA outputs, so regenerating under a
#: different jax is expected to drift; CI pins jax to this version
META_KEY = "_meta"

DEFAULT_MANIFEST = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "audit_manifest.json",
)


def load(path: str = DEFAULT_MANIFEST) -> Optional[Dict[str, dict]]:
    """The manifest dict, or None when the file does not exist yet."""
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def save(entries: Dict[str, dict], path: str = DEFAULT_MANIFEST) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(entries, fh, indent=2, sort_keys=True)
        fh.write("\n")
