"""IR plumbing for ba3caudit: jaxpr walking, HLO alias parsing, cost metrics.

Everything here is mechanism; the invariants live in rules.py. The walkers
are deliberately structural — they descend into ANY eqn param that holds a
(Closed)Jaxpr (pjit bodies, scan/while bodies, cond branches, shard_map,
custom_vjp calls), so a collective or conv hiding three nesting levels deep
in the fused step is still seen.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Dict, Iterator, List, Tuple

# communicating collectives (primitive names as they appear in jaxprs)
COLLECTIVE_PRIMS = {
    "psum",
    "pmin",
    "pmax",
    "ppermute",
    "pbroadcast",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "psum_scatter",
}

# host-transfer / host-callback primitives: none may appear in a hot path
HOST_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "debug_print",
    "outside_call",
    "host_callback",
    "infeed",
    "outfeed",
}

CONV_PRIM = "conv_general_dilated"
DOT_PRIM = "dot_general"


def _subjaxprs(eqn) -> Iterator[Any]:
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "eqns"):  # open Jaxpr
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr  # ClosedJaxpr

def iter_eqns(jaxpr) -> Iterator[Any]:
    """Yield every eqn in ``jaxpr`` and, recursively, in all sub-jaxprs."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr -> Jaxpr
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn):
            yield from iter_eqns(sub)


def _in_avals(eqn) -> List[Any]:
    return [v.aval for v in eqn.invars if hasattr(v, "aval")]


def collective_census(jaxpr) -> Counter:
    """primitive name -> count, over every collective eqn in the program."""
    return Counter(
        e.primitive.name for e in iter_eqns(jaxpr)
        if e.primitive.name in COLLECTIVE_PRIMS
    )


def host_callback_census(jaxpr) -> Counter:
    return Counter(
        e.primitive.name for e in iter_eqns(jaxpr)
        if e.primitive.name in HOST_PRIMS
    )


def conv_operand_dtypes(jaxpr) -> List[Tuple[str, ...]]:
    """Per conv eqn: the tuple of operand dtype names (lhs, rhs)."""
    out = []
    for e in iter_eqns(jaxpr):
        if e.primitive.name == CONV_PRIM:
            out.append(tuple(str(a.dtype) for a in _in_avals(e)))
    return out


def dot_dtype_census(jaxpr) -> Counter:
    """dtype name of the lhs operand -> count, over every dot_general."""
    census: Counter = Counter()
    for e in iter_eqns(jaxpr):
        if e.primitive.name == DOT_PRIM:
            avals = _in_avals(e)
            if avals:
                census[str(avals[0].dtype)] += 1
    return census


def nonscalar_psum_shapes(jaxpr) -> List[Tuple[int, ...]]:
    """Operand shapes of every psum over a non-scalar array.

    The step's gradient all-reduce is one psum per param leaf; everything
    else the steps psum (metrics, episode counters) is scalar, so the
    non-scalar psum multiset IS the gradient-reduction census. (psum is
    variadic — one eqn may carry several operands.)
    """
    shapes: List[Tuple[int, ...]] = []
    for e in iter_eqns(jaxpr):
        if e.primitive.name == "psum":
            for a in _in_avals(e):
                if getattr(a, "ndim", 0) >= 1:
                    shapes.append(tuple(a.shape))
    return shapes


# --------------------------------------------------------------------------
# compiled-module facts
# --------------------------------------------------------------------------

_ALIAS_MARKER = "input_output_alias={"
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9, ]*\}:\s*\((\d+)\s*,")


def input_aliases(compiled_text: str) -> List[int]:
    """Parameter indices that alias an output, parsed from the compiled
    module header's ``input_output_alias={ {out}: (param, {}, may-alias) }``.

    XLA drops unusable donations silently at lowering (jax only warns), so
    the REQUESTED donation in the jaxpr proves nothing — this header is the
    materialized truth. The block nests braces (output indices, tuple
    paths), so it is extracted with a depth scan, not a regex.
    """
    start = compiled_text.find(_ALIAS_MARKER)
    if start < 0:
        return []
    i = start + len(_ALIAS_MARKER)
    depth = 1
    while i < len(compiled_text) and depth:
        depth += {"{": 1, "}": -1}.get(compiled_text[i], 0)
        i += 1
    block = compiled_text[start + len(_ALIAS_MARKER): i - 1]
    return sorted(int(g) for g in _ALIAS_ENTRY_RE.findall(block))


def cost_metrics(compiled) -> Dict[str, float]:
    """{'flops': ..., 'bytes_accessed': ...} from XLA's cost analysis."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }
