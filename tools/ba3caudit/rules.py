"""The T-series: IR-level invariants checked per registered entry point.

Rule catalog (rationale and worked regressions: docs/static_analysis.md):

    T1 conv-dtype-policy     every conv eqn computes in the declared dtype
    T2 donation-materialized every donated leaf aliases an output buffer
    T3 grad-allreduce-census each non-scalar param grad psum'd exactly once
    T4 no-host-callbacks     no callback/debug/infeed primitives in hot paths
    T5 manifest-drift        FLOPs/HBM bytes/censuses match audit_manifest.json

T1–T4 are absolute (they hold for ANY build of the entry point); T5 pins the
measured program against the checked-in manifest so silent cost/shape
regressions fail CI with a readable diff.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Dict, List, Optional

from tools.ba3caudit import ir


@dataclasses.dataclass(frozen=True)
class Finding:
    entry: str
    rule: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Measurement:
    """Everything the analyzer extracted for one entry point."""

    entry: str
    collectives: Dict[str, int]
    host_callbacks: Dict[str, int]
    conv_dtypes: List[tuple]
    dot_dtypes: Dict[str, int]
    nonscalar_psum_shapes: List[tuple]
    aliased_inputs: List[int]
    flops: float
    bytes_accessed: float

    def manifest_entry(self) -> dict:
        return {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "collectives": dict(sorted(self.collectives.items())),
            "conv_eqns": len(self.conv_dtypes),
            "dot_dtypes": dict(sorted(self.dot_dtypes.items())),
            "grad_psums": len(self.nonscalar_psum_shapes),
            "aliased_inputs": len(self.aliased_inputs),
        }


def measure(target) -> Measurement:
    """Trace + lower + compile one TraceTarget and extract the facts."""
    traced = target.jit_fn.trace(*target.args)
    jaxpr = traced.jaxpr
    compiled = traced.lower().compile()
    return Measurement(
        entry=target.name,
        collectives=dict(ir.collective_census(jaxpr)),
        host_callbacks=dict(ir.host_callback_census(jaxpr)),
        conv_dtypes=ir.conv_operand_dtypes(jaxpr),
        dot_dtypes=dict(ir.dot_dtype_census(jaxpr)),
        nonscalar_psum_shapes=ir.nonscalar_psum_shapes(jaxpr),
        aliased_inputs=ir.input_aliases(compiled.as_text()),
        **ir.cost_metrics(compiled),
    )


# --------------------------------------------------------------------------
# T1–T4: absolute invariants
# --------------------------------------------------------------------------


def check_t1(target, m: Measurement) -> List[Finding]:
    bad = [
        dts for dts in m.conv_dtypes
        if any(d != target.conv_dtype for d in dts)
    ]
    if not bad:
        return []
    return [Finding(
        m.entry, "T1",
        f"{len(bad)}/{len(m.conv_dtypes)} conv eqns compute outside the "
        f"{target.conv_dtype} policy (operand dtypes: "
        f"{sorted(set(bad))}) — f32 leaked into the conv stack halves MXU "
        "throughput; check the astype boundaries in models/a3c.py",
    )]


def check_t2(target, m: Measurement) -> List[Finding]:
    expected = set(target.donated_nonscalar_indices)
    got = set(m.aliased_inputs)
    if not expected:
        if got:
            return [Finding(
                m.entry, "T2",
                f"{len(got)} input buffers alias outputs but the entry "
                "declares no donation — an unintended alias can free a "
                "buffer a caller still reads",
            )]
        return []
    missing = sorted(expected - got)
    if not missing:
        return []
    return [Finding(
        m.entry, "T2",
        f"donation NOT fully materialized: {len(missing)}/{len(expected)} "
        f"donated non-scalar state leaves (input indices {missing[:8]}"
        f"{'…' if len(missing) > 8 else ''}) have no output alias in the "
        "compiled module. jax only WARNS when XLA drops a donation; every "
        "dropped leaf doubles its HBM footprint on each step",
    )]


def check_t3(target, m: Measurement) -> List[Finding]:
    out: List[Finding] = []
    if not target.allow_collectives:
        if m.collectives:
            out.append(Finding(
                m.entry, "T3",
                f"collectives in a single-device program: {m.collectives} — "
                "a mesh sharding leaked into this entry point",
            ))
        return out
    got = Counter(m.nonscalar_psum_shapes)
    want = Counter(tuple(s) for s in (target.grad_shapes or []))
    if got == want:
        return out
    missing = want - got
    extra = got - want
    if missing:
        out.append(Finding(
            m.entry, "T3",
            f"{sum(missing.values())} param grad(s) NEVER all-reduced on the "
            f"data axis (shapes {sorted(missing)}): each device applies a "
            "shard-local gradient and replicas silently diverge",
        ))
    if extra:
        out.append(Finding(
            m.entry, "T3",
            f"{sum(extra.values())} extra non-scalar psum(s) (shapes "
            f"{sorted(extra)}): a gradient reduced more than once is scaled "
            "by the axis size (the double-pmean bug class), or a non-grad "
            "tensor is paying an all-reduce it doesn't need",
        ))
    return out


def check_t4(_target, m: Measurement) -> List[Finding]:
    if not m.host_callbacks:
        return []
    return [Finding(
        m.entry, "T4",
        f"host callback primitives in a hot path: {m.host_callbacks} — "
        "every invocation is a device->host round trip inside the step "
        "(delete the debug print / move the callback outside the jit)",
    )]


# --------------------------------------------------------------------------
# T5: manifest drift
# --------------------------------------------------------------------------

#: fields compared exactly (integer program structure)
EXACT_FIELDS = ("collectives", "conv_eqns", "dot_dtypes", "grad_psums",
                "aliased_inputs")
#: fields compared within relative tolerance (XLA cost model outputs)
TOLERANT_FIELDS = ("flops", "bytes_accessed")


def check_t5(m: Measurement, manifest_entry: Optional[dict],
             tolerance: float) -> List[Finding]:
    if manifest_entry is None:
        return [Finding(
            m.entry, "T5",
            "entry point missing from audit_manifest.json — run "
            "`python -m tools.ba3caudit --update-manifest` and commit the "
            "diff (reviewing it IS the audit)",
        )]
    out: List[Finding] = []
    measured = m.manifest_entry()
    for field in EXACT_FIELDS:
        if measured[field] != manifest_entry.get(field):
            out.append(Finding(
                m.entry, "T5",
                f"{field} drifted: manifest {manifest_entry.get(field)!r} "
                f"-> measured {measured[field]!r} (exact field; if the "
                "change is intended, --update-manifest and commit)",
            ))
    for field in TOLERANT_FIELDS:
        want = float(manifest_entry.get(field, 0.0))
        have = measured[field]
        base = max(abs(want), 1.0)
        rel = abs(have - want) / base
        if rel > tolerance:
            out.append(Finding(
                m.entry, "T5",
                f"{field} drifted {rel:+.1%} (manifest {want:.6g} -> "
                f"measured {have:.6g}, tolerance {tolerance:.0%}) — a "
                "recompile-shape or cost regression; if intended, "
                "--update-manifest and commit",
            ))
    return out


def check_entry(target, m: Measurement, manifest_entry: Optional[dict],
                tolerance: float) -> List[Finding]:
    """Run every T-rule for one measured entry point."""
    out: List[Finding] = []
    out += check_t1(target, m)
    out += check_t2(target, m)
    out += check_t3(target, m)
    out += check_t4(target, m)
    out += check_t5(m, manifest_entry, tolerance)
    return out
