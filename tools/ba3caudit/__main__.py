"""CLI: ``python -m tools.ba3caudit``.

Exit status: 0 = every invariant holds, 1 = findings, 2 = bad usage.

The process pins itself to the CPU platform BEFORE importing jax:
 - the audit is an IR property, identical on every backend, and claiming
   the (exclusive) TPU pool for it would be the double-claim
   utils/devicelock.py exists to prevent;
 - the canonical mesh needs ≥2 devices, so a host-platform device count is
   forced when none is configured. The registry always builds its mesh from
   the FIRST two devices, so running under the 8-device pytest harness
   yields the same manifest numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _pin_cpu_platform() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    import jax

    # the container's sitecustomize force-registers the TPU plugin and
    # overrides the env var (same compensation as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ba3caudit",
        description="Trace-level (jaxpr/HLO) invariant audit of the "
        "registered hot-path entry points (rule catalog: "
        "docs/static_analysis.md).",
    )
    parser.add_argument(
        "--entries",
        help="comma-separated entry-point names (default: all registered)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine output: one JSON object on stdout",
    )
    parser.add_argument(
        "--update-manifest", action="store_true",
        help="rewrite audit_manifest.json from the live measurement "
        "(review + commit the diff)",
    )
    parser.add_argument(
        "--manifest", help="manifest path (default: repo-root audit_manifest.json)",
    )
    parser.add_argument(
        "--tolerance", type=float, default=0.25,
        help="relative tolerance for flops/bytes drift (default: 0.25)",
    )
    parser.add_argument(
        "--list-entries", action="store_true",
        help="print the registered entry points and exit",
    )
    args = parser.parse_args(argv)

    _pin_cpu_platform()

    from distributed_ba3c_tpu import audit
    from tools import ba3caudit

    registered = audit.entry_names()
    if args.list_entries:
        for name in registered:
            print(name)
        return 0

    entries = None
    if args.entries:
        entries = [s.strip() for s in args.entries.split(",") if s.strip()]
        unknown = sorted(set(entries) - set(registered))
        if unknown:
            print(
                f"unknown entry point(s): {', '.join(unknown)}; "
                f"registered: {registered}",
                file=sys.stderr,
            )
            return 2

    measurements, findings = ba3caudit.run_audit(
        entries=entries,
        manifest_path=args.manifest,
        update_manifest=args.update_manifest,
        tolerance=args.tolerance,
    )

    # diagnostic, not a gate: T5 values are XLA outputs, so a manifest
    # measured under a different jax is the FIRST thing to check when
    # drift findings look like nobody's change
    import jax

    from tools.ba3caudit import manifest as manifest_mod

    meta = (manifest_mod.load(args.manifest or manifest_mod.DEFAULT_MANIFEST)
            or {}).get(manifest_mod.META_KEY, {})
    if meta.get("jax") and meta["jax"] != jax.__version__:
        print(
            f"ba3caudit: note — manifest measured under jax {meta['jax']}, "
            f"running under {jax.__version__}; T5 drift may be toolchain, "
            "not code (CI pins jax for this reason)",
            file=sys.stderr,
        )

    if args.json:
        print(json.dumps({
            "entries": {
                name: m.manifest_entry() for name, m in measurements.items()
            },
            "findings": [f.to_dict() for f in findings],
        }, indent=2, sort_keys=True))
    else:
        for name, m in sorted(measurements.items()):
            entry_findings = [f for f in findings if f.entry == name]
            status = "FAIL" if entry_findings else "ok"
            print(
                f"{name:24s} {status:4s} flops={m.flops:.4g} "
                f"bytes={m.bytes_accessed:.4g} "
                f"collectives={dict(sorted(m.collectives.items()))} "
                f"convs={len(m.conv_dtypes)} aliased={len(m.aliased_inputs)}"
            )
        for f in findings:
            print(f"{f.entry}: [{f.rule}] {f.message}")
        n = len(findings)
        print(f"ba3caudit: {n} finding{'s' if n != 1 else ''}")
        if args.update_manifest:
            print("ba3caudit: manifest updated — review + commit the diff")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
