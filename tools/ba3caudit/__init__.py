"""ba3caudit: trace-level (jaxpr/HLO) invariant auditor for the BA3C stack.

Usage:
    python -m tools.ba3caudit [--entries a,b] [--json] [--update-manifest]

Where ``ba3clint`` reads the *source*, ba3caudit reads the *compiled
program*: it builds every entry point registered in
``distributed_ba3c_tpu/audit.py`` at canonical abstract shapes, traces it
(jaxpr), lowers and compiles it (HLO + cost analysis), and checks the
T-series invariants — bf16 conv policy (T1), materialized buffer donation
(T2), exactly-once gradient all-reduce (T3), no host callbacks (T4), and
FLOPs/HBM-bytes drift against the checked-in ``audit_manifest.json`` (T5).
Rule catalog: docs/static_analysis.md.

The runtime half lives in ``distributed_ba3c_tpu/audit.py``: ``BA3C_AUDIT=1``
arms a retrace tripwire on the same registered jit sites.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from tools.ba3caudit.rules import Finding, Measurement  # noqa: F401 (public API)


def run_audit(
    entries: Optional[Sequence[str]] = None,
    manifest_path: Optional[str] = None,
    update_manifest: bool = False,
    tolerance: float = 0.25,
) -> Tuple[Dict[str, "Measurement"], List["Finding"]]:
    """Measure the registered entry points and run every T-rule.

    Returns (measurements by entry name, findings). With
    ``update_manifest=True`` the measured values are written to the manifest
    and T5 is reported against the FRESH values (i.e. never fires).
    """
    import jax

    from distributed_ba3c_tpu import audit
    from tools.ba3caudit import manifest as manifest_mod
    from tools.ba3caudit import rules

    names = list(entries) if entries else audit.entry_names()
    path = manifest_path or manifest_mod.DEFAULT_MANIFEST
    stored = dict(manifest_mod.load(path) or {})
    stored_meta = stored.pop(manifest_mod.META_KEY, None)

    measurements: Dict[str, rules.Measurement] = {}
    findings: List[rules.Finding] = []
    for name in names:
        target = audit.build_entry(name)
        m = rules.measure(target)
        measurements[name] = m
        entry_manifest = (
            m.manifest_entry() if update_manifest else stored.get(name)
        )
        findings.extend(rules.check_entry(target, m, entry_manifest, tolerance))

    # a manifest key with no registered entry point is a pin that stopped
    # gating anything (renamed/deleted entry) — zombie pins mislead every
    # future manifest-diff review, so they are findings, not warnings
    for stale in sorted(set(stored) - set(audit.entry_names())):
        if update_manifest:
            continue  # pruned by the rewrite below
        findings.append(rules.Finding(
            stale, "T5",
            "manifest entry has no registered entry point (renamed or "
            "deleted?) — prune it with --update-manifest, or restore the "
            "registration",
        ))

    if update_manifest:
        # keep still-registered pins not re-measured this run (an
        # --entries subset), drop everything unregistered
        merged = {
            n: v for n, v in stored.items() if n in audit.entry_names()
        }
        merged.update({n: m.manifest_entry() for n, m in measurements.items()})
        # only a FULL re-measure may re-stamp the toolchain: a subset
        # update under a new jax would stamp the new version over entries
        # still holding old-toolchain numbers — suppressing the exact
        # mismatch hint built for that situation
        full = set(names) >= set(audit.entry_names())
        merged[manifest_mod.META_KEY] = (
            {"jax": jax.__version__} if full or not stored_meta
            else stored_meta
        )
        manifest_mod.save(merged, path)
    return measurements, findings
