"""ba3cflow call graph + interprocedural facts.

Built on the :mod:`tools.ba3cflow.project` symbol table:

- **call resolution**: each ``ast.Call`` in each function resolves to zero or
  more project functions. Receivers are typed from ``self``, annotated
  parameters, local ``x = Cls(...)`` assignments, and class attribute types
  (``self.pump.publish`` → ``LatestWinsPump.publish``). Unknown receivers
  resolve to nothing — rules never guess.
- **thread roots**: functions that execute on a non-main thread — ``run()``
  of ``threading.Thread`` subclasses, ``target=`` of thread ctors, and the
  first positional callable of ``LoopThread``.
- **lock regions**: ``with <lock>:`` blocks with a stable lock identity
  (``Class.attr`` via :meth:`Project.canonical_lock`).
- **blocking facts**: per-function direct blocking operations (unbounded
  queue ops, bare socket recv/send, ``time.sleep``, untimed ``.wait()``,
  subprocess waits, device puts/syncs) and their transitive closure over the
  call graph, with a witness path for diagnostics.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.ba3clint.engine import dotted_name
from tools.ba3cflow.project import (
    ClassInfo,
    FunctionInfo,
    LOCK_CTORS,
    Project,
    THREAD_CTORS,
)

# --------------------------------------------------------------------------
# receiver typing
# --------------------------------------------------------------------------


def local_types(project: Project, fn: FunctionInfo) -> Dict[str, str]:
    """Best-effort map of local/param name -> canonical dotted class.

    Sources: ``self``, annotated parameters, ``x = Cls(...)`` and
    ``x: Cls = ...`` assignments, and ``for x in self.<list-of-T>`` loops
    (element types recorded by list-literal ctor scans below).
    """
    mod = project.module_of(fn)
    out: Dict[str, str] = {}
    ci = project.class_of(fn)
    if ci is not None:
        out["self"] = ci.qualname
    args = fn.node.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        if a.annotation is not None:
            ty = _ann_dotted(a.annotation)
            if ty:
                out[a.arg] = mod.resolve(ty)
    for sub in ast.walk(fn.node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            ctor = dotted_name(sub.value.func)
            if not ctor:
                continue
            resolved = mod.resolve(ctor)
            if project.find_class(resolved) is None and \
                    resolved not in THREAD_CTORS and resolved not in LOCK_CTORS:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, resolved)
        elif isinstance(sub, ast.AnnAssign) and isinstance(sub.target,
                                                           ast.Name):
            ty = _ann_dotted(sub.annotation)
            if ty:
                out.setdefault(sub.target.id, mod.resolve(ty))
    return out


def _ann_dotted(ann: ast.AST) -> Optional[str]:
    from tools.ba3cflow.project import ann_to_dotted
    return ann_to_dotted(ann)


def receiver_class(project: Project, fn: FunctionInfo,
                   expr: ast.AST,
                   locals_: Optional[Dict[str, str]] = None
                   ) -> Optional[ClassInfo]:
    """Type of an expression used as a method receiver, or None.

    Handles ``self``, typed locals/params, and one level of typed attribute
    access (``self.pump`` / ``task._lock``'s owner, ``rep.pump``).
    """
    if locals_ is None:
        locals_ = local_types(project, fn)
    if isinstance(expr, ast.Name):
        return project.resolve_class(fn.modname, locals_.get(expr.id))
    if isinstance(expr, ast.Attribute):
        base = receiver_class(project, fn, expr.value, locals_)
        if base is not None:
            for c in project.mro(base):
                ty = c.attr_types.get(expr.attr)
                if ty:
                    return project.resolve_class(c.modname, ty)
    return None


# --------------------------------------------------------------------------
# call resolution
# --------------------------------------------------------------------------


#: method names too generic for closed-world duck resolution — resolving
#: ``anything.get(...)`` to every project ``get`` would drown the graph
_DUCK_BLACKLIST = {
    "get", "put", "run", "stop", "start", "close", "join", "send", "recv",
    "update", "reset", "step", "tick", "flush", "wait", "clear", "pop",
    "add", "remove", "append", "items", "values", "keys", "info", "warn",
    "error", "debug", "exception", "inc", "dec", "set", "record", "gauge",
    "observe", "write", "read", "next", "emit", "load", "save", "copy",
    "size", "count", "name", "result", "cancel", "submit", "done",
    "publish", "apply", "snapshot", "stopped", "main", "state", "render",
    "acquire", "release", "locked",  # lock protocol: never duck-resolve
}
_DUCK_MAX_DEFINERS = 3


def resolve_call(project: Project, fn: FunctionInfo, call: ast.Call,
                 locals_: Optional[Dict[str, str]] = None,
                 duck: bool = False) -> List[FunctionInfo]:
    """Resolve one call site to project functions (possibly empty).

    With ``duck=True``, a method call whose receiver type is unknown falls
    back to closed-world duck typing: if the method name is distinctive
    (not in the generic blacklist) and defined by at most
    ``_DUCK_MAX_DEFINERS`` project classes, the call resolves to ALL of
    them. Sound for may-analyses (blocking/join closures), too imprecise
    for must-style checks like F6 — callers opt in explicitly.
    """
    if locals_ is None:
        locals_ = local_types(project, fn)
    mod = project.module_of(fn)
    func = call.func

    if isinstance(func, ast.Name):
        resolved = mod.resolve(func.id)
        # module-local or imported function
        target = project.functions.get(resolved) or \
            project.functions.get(f"{fn.modname}.{func.id}")
        if target is not None:
            return [target]
        # class construction -> __init__
        ci = project.find_class(resolved) or \
            project.find_class(f"{fn.modname}.{func.id}")
        if ci is not None:
            init = project.find_method(ci, "__init__")
            return [init] if init is not None else []
        return []

    if isinstance(func, ast.Attribute):
        # module attribute call: logger.info(...), serving.welch_z(...)
        base_dotted = dotted_name(func.value)
        if base_dotted:
            canon = mod.resolve(base_dotted)
            m = project.find_module(canon)
            if m is not None:
                target = m.functions.get(f"{m.modname}.{func.attr}")
                if target is not None:
                    return [target]
                ci = m.classes.get(func.attr)
                if ci is not None:
                    init = project.find_method(ci, "__init__")
                    return [init] if init is not None else []
                return []
        # typed receiver: self.m(), task.cancel(), self.pump.publish()
        rc = receiver_class(project, fn, func.value, locals_)
        if rc is not None:
            target = project.find_method(rc, func.attr)
            if target is not None:
                return [target]
            return []
        if duck and func.attr not in _DUCK_BLACKLIST:
            definers = project.method_index.get(func.attr, [])
            if 0 < len(definers) <= _DUCK_MAX_DEFINERS:
                return list(definers)
    return []


class CallGraph:
    """Forward call graph over a :class:`Project`, with call-site nodes."""

    def __init__(self, project: Project):
        self.project = project
        #: caller qualname -> [(callee FunctionInfo, ast.Call node)]
        self.edges: Dict[str, List[Tuple[FunctionInfo, ast.Call]]] = {}
        for fn in project.functions.values():
            locals_ = local_types(project, fn)
            out: List[Tuple[FunctionInfo, ast.Call]] = []
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Call):
                    for tgt in resolve_call(project, fn, sub, locals_,
                                            duck=True):
                        out.append((tgt, sub))
            self.edges[fn.qualname] = out

    def callees(self, qual: str) -> List[Tuple[FunctionInfo, ast.Call]]:
        return self.edges.get(qual, [])

    def reachable(self, roots: Sequence[str],
                  max_depth: int = 64) -> Set[str]:
        seen: Set[str] = set()
        frontier = list(roots)
        depth = 0
        while frontier and depth < max_depth:
            nxt: List[str] = []
            for q in frontier:
                if q in seen:
                    continue
                seen.add(q)
                nxt.extend(t.qualname for t, _ in self.callees(q))
            frontier = nxt
            depth += 1
        return seen


# --------------------------------------------------------------------------
# thread roots
# --------------------------------------------------------------------------


class ThreadRoot:
    """A function that executes on a spawned thread."""

    __slots__ = ("fn", "via", "site")

    def __init__(self, fn: FunctionInfo, via: str, site: ast.AST):
        self.fn = fn        # the root function
        self.via = via      # "run-method" | "target" | "loop-fn"
        self.site = site    # node to report against


def thread_roots(project: Project, graph: CallGraph) -> List[ThreadRoot]:
    roots: List[ThreadRoot] = []
    seen: Set[str] = set()

    def add(fn: Optional[FunctionInfo], via: str, site: ast.AST) -> None:
        if fn is not None and fn.qualname not in seen:
            seen.add(fn.qualname)
            roots.append(ThreadRoot(fn, via, site))

    # run() of Thread subclasses
    for ci in project.classes.values():
        if project.is_threadish(ci):
            run = ci.methods.get("run")
            add(run, "run-method", run.node if run else ci.node)

    # target= of thread-like ctors; LoopThread(func)
    for fn in project.functions.values():
        mod = project.module_of(fn)
        locals_ = local_types(project, fn)
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            ctor = dotted_name(sub.func)
            if not ctor:
                continue
            resolved = mod.resolve(ctor)
            ci = project.find_class(resolved)
            is_thread_ctor = resolved in THREAD_CTORS or (
                ci is not None and project.is_threadish(ci))
            if not is_thread_ctor:
                continue
            for kw in sub.keywords:
                if kw.arg == "target":
                    for tgt in _callable_targets(project, fn, kw.value,
                                                 locals_):
                        add(tgt, "target", sub)
            if ci is not None and ci.name == "LoopThread" and sub.args:
                for tgt in _callable_targets(project, fn, sub.args[0],
                                             locals_):
                    add(tgt, "loop-fn", sub)
    return roots


def _callable_targets(project: Project, fn: FunctionInfo, expr: ast.AST,
                      locals_: Dict[str, str]) -> List[FunctionInfo]:
    """Resolve a callable-valued expression (``self._loop``, ``fn_name``)."""
    if isinstance(expr, ast.Attribute):
        rc = receiver_class(project, fn, expr.value, locals_)
        if rc is not None:
            tgt = project.find_method(rc, expr.attr)
            return [tgt] if tgt is not None else []
    elif isinstance(expr, ast.Name):
        mod = project.module_of(fn)
        tgt = project.functions.get(mod.resolve(expr.id)) or \
            project.functions.get(f"{fn.modname}.{expr.id}")
        return [tgt] if tgt is not None else []
    return []


# --------------------------------------------------------------------------
# lock regions
# --------------------------------------------------------------------------


class LockRegion:
    """One ``with <lock>:`` block inside a function."""

    __slots__ = ("lock_id", "node", "fn")

    def __init__(self, lock_id: str, node: ast.With, fn: FunctionInfo):
        self.lock_id = lock_id
        self.node = node
        self.fn = fn


_LOCKISH_HINTS = ("lock", "mutex", "cond")


def _lock_identity(project: Project, fn: FunctionInfo, expr: ast.AST,
                   locals_: Dict[str, str]) -> Optional[str]:
    """Stable identity of a with-context expression that is a lock, or None.

    A receiver attribute is lock-like when its inferred type is a
    ``threading`` lock/condition ctor, or (fallback) when its name carries a
    lock-ish hint (``_lock``, ``_cond``). Identity is ``OwnerClass.attr``
    via :meth:`Project.canonical_lock` when the owner is known, else a
    module-scoped textual identity.
    """
    if isinstance(expr, ast.Attribute):
        owner = receiver_class(project, fn, expr.value, locals_)
        attr = expr.attr
        if owner is not None:
            ty = None
            real_attr = owner.lock_aliases.get(attr, attr)
            for c in project.mro(owner):
                ty = c.attr_types.get(real_attr)
                if ty:
                    break
            if ty in LOCK_CTORS or any(h in attr.lower()
                                       for h in _LOCKISH_HINTS):
                return project.canonical_lock(owner, attr)
            return None
        if any(h in attr.lower() for h in _LOCKISH_HINTS):
            nm = dotted_name(expr)
            return f"{fn.modname}:{nm or attr}"
        return None
    if isinstance(expr, ast.Name):
        ty = locals_.get(expr.id)
        if ty in LOCK_CTORS or any(h in expr.id.lower()
                                   for h in _LOCKISH_HINTS):
            return f"{fn.modname}:{expr.id}"
    return None


def lock_regions(project: Project, fn: FunctionInfo,
                 locals_: Optional[Dict[str, str]] = None) -> List[LockRegion]:
    if locals_ is None:
        locals_ = local_types(project, fn)
    out: List[LockRegion] = []
    for sub in ast.walk(fn.node):
        if not isinstance(sub, ast.With):
            continue
        for item in sub.items:
            lid = _lock_identity(project, fn, item.context_expr, locals_)
            if lid is not None:
                out.append(LockRegion(lid, sub, fn))
    return out


def nodes_under(region: ast.With) -> Iterator[ast.AST]:
    for stmt in region.body:
        yield from ast.walk(stmt)


# --------------------------------------------------------------------------
# blocking facts
# --------------------------------------------------------------------------


class BlockingOp:
    """One potentially unbounded blocking operation."""

    __slots__ = ("kind", "node", "detail")

    def __init__(self, kind: str, node: ast.AST, detail: str):
        self.kind = kind
        self.node = node
        self.detail = detail


_QUEUEISH = ("queue", "_queue", "q", "inq", "outq", "input_queue",
             "output_queue", "tasks", "results")
_SOCKISH = ("sock", "socket", "dealer", "router_sock", "pull", "push", "sub",
            "pub", "rep", "req")
_PROCISH = ("proc", "process", "popen", "child")
_WAITABLE_HINTS = ("evt", "event", "cond", "ready", "done", "stop")

#: canonical dotted calls that synchronize with a device (compile/transfer):
#: seconds-long under compilation, so "blocking" for lock-held purposes.
_DEVICE_CALLS = {
    "jax.device_put",
    "jax.block_until_ready",
    "jax.device_get",
}


def _last_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _has_bound(call: ast.Call) -> bool:
    """timeout= / block=False / zmq flags present -> bounded, not blocking."""
    for kw in call.keywords:
        if kw.arg in ("timeout", "flags"):
            return True
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def direct_blocking_ops(project: Project, fn: FunctionInfo) -> List[BlockingOp]:
    mod = project.module_of(fn)
    out: List[BlockingOp] = []
    for sub in ast.walk(fn.node):
        if not isinstance(sub, ast.Call):
            continue
        func = sub.func
        resolved = None
        nm = dotted_name(func)
        if nm:
            resolved = mod.resolve(nm)
        if resolved == "time.sleep":
            out.append(BlockingOp("sleep", sub, "time.sleep"))
            continue
        if resolved in _DEVICE_CALLS:
            out.append(BlockingOp("device", sub, resolved))
            continue
        if resolved and resolved.startswith("subprocess.") and \
                resolved.split(".")[-1] in ("run", "check_call",
                                            "check_output", "call") and \
                not _has_bound(sub):
            out.append(BlockingOp("subprocess", sub, resolved))
            continue
        if not isinstance(func, ast.Attribute):
            continue
        meth = func.attr
        recv = _last_name(func.value)
        recv_l = (recv or "").lower()
        if meth in ("get", "put") and not _has_bound(sub) and any(
                recv_l == h or recv_l.endswith(h) for h in _QUEUEISH):
            out.append(BlockingOp("queue", sub, f"{recv}.{meth} (untimed)"))
        elif meth in ("recv", "recv_multipart", "send", "send_multipart",
                      "recv_pyobj", "send_pyobj") and not sub.args and \
                not _has_bound(sub) and any(h in recv_l for h in _SOCKISH):
            out.append(BlockingOp("socket", sub, f"{recv}.{meth} (bare)"))
        elif meth == "wait" and not sub.args and not _has_bound(sub):
            if any(h in recv_l for h in _WAITABLE_HINTS):
                out.append(BlockingOp("wait", sub, f"{recv}.wait (untimed)"))
            elif any(recv_l == h or recv_l.endswith(h) for h in _PROCISH):
                out.append(BlockingOp("proc-wait", sub, f"{recv}.wait"))
        elif meth == "communicate" and not _has_bound(sub) and any(
                recv_l == h or recv_l.endswith(h) for h in _PROCISH):
            out.append(BlockingOp("proc-wait", sub, f"{recv}.communicate"))
        elif meth == "block_until_ready":
            out.append(BlockingOp("device", sub, f"{recv}.block_until_ready"))
        elif meth == "flush" and not _has_bound(sub) and not sub.args and \
                recv_l.endswith("pump"):
            out.append(BlockingOp("wait", sub, f"{recv}.flush (untimed)"))
    return out


class BlockingFacts:
    """Transitive may-block closure with witness paths."""

    def __init__(self, project: Project, graph: CallGraph):
        self.project = project
        self.graph = graph
        self.direct: Dict[str, List[BlockingOp]] = {}
        for fn in project.functions.values():
            ops = direct_blocking_ops(project, fn)
            if ops:
                self.direct[fn.qualname] = ops
        #: qualname -> (witness chain [qualnames], terminal BlockingOp)
        self.closure: Dict[str, Tuple[List[str], BlockingOp]] = {}
        self._fixpoint()

    def _fixpoint(self) -> None:
        for q, ops in self.direct.items():
            self.closure[q] = ([q], ops[0])
        changed = True
        while changed:
            changed = False
            for q, callees in self.graph.edges.items():
                if q in self.closure:
                    continue
                for tgt, _node in callees:
                    hit = self.closure.get(tgt.qualname)
                    if hit is not None:
                        chain, op = hit
                        if q not in chain and len(chain) < 12:
                            self.closure[q] = ([q] + chain, op)
                            changed = True
                            break
        # (paths are shortest-ish, not minimal — good enough for messages)

    def may_block(self, qual: str) -> Optional[Tuple[List[str], BlockingOp]]:
        return self.closure.get(qual)
