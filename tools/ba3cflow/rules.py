"""ba3cflow rules F1–F6: interprocedural concurrency & lifecycle hazards.

Each rule is a class with ``id``/``name``/``summary`` and a
``check(ctx)`` generator over a :class:`~tools.ba3cflow.engine.FlowContext`
(whole-project view), mirroring the ba3clint rule contract but at call-graph
granularity. False positives are handled at the use site with
``# ba3cflow: disable=Fn — justification``, never by widening a carve-out
here: the rules stay honest and the invariant becomes visible in the code.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.ba3clint.engine import Finding, dotted_name
from tools.ba3cflow.graph import (
    BlockingOp,
    lock_regions,
    local_types,
    nodes_under,
    receiver_class,
    resolve_call,
)
from tools.ba3cflow.project import ClassInfo, FunctionInfo


class FlowRule:
    """Base class: subclasses set ``id``/``name``/``summary`` and ``check``."""

    id: str = ""
    name: str = ""
    summary: str = ""

    def check(self, ctx) -> Iterator[Finding]:
        raise NotImplementedError


def _finding(rule: FlowRule, fn: FunctionInfo, node: ast.AST,
             message: str) -> Finding:
    return Finding(fn.path, getattr(node, "lineno", 1),
                   getattr(node, "col_offset", 0), rule.id, message)


def _short(qual: str) -> str:
    """Trim the package prefix for readable messages."""
    parts = qual.split(".")
    return ".".join(parts[-3:]) if len(parts) > 3 else qual


# --------------------------------------------------------------------------
# F1: blocking while a lock/condition is held + guarded-field discipline
# --------------------------------------------------------------------------


#: container-mutating method names: a call through a typed attribute counts
#: as a structural write for guard-discipline purposes
_MUTATOR_METHS = {
    "pop", "popitem", "append", "appendleft", "extend", "insert", "remove",
    "clear", "update", "setdefault", "add", "discard",
}


class F1BlockingUnderLock(FlowRule):
    """A lock-held region must stay O(microseconds): any operation that can
    park the thread — untimed queue get/put, bare socket recv/send,
    ``time.sleep``, untimed ``.wait()``, subprocess waits, device
    transfers/syncs — wedges every other thread contending on that lock
    (in this repo that is usually the health loop or the dispatch path).
    The check is interprocedural: a call whose *callee* transitively blocks
    is reported with the witness chain. The same rule owns lock *discipline*:
    an attribute written under ``self._lock`` in one method and bare in
    another is exactly the ``_try_admit`` decrement-race shape from PR 16,
    so inconsistently-guarded writes are flagged too."""

    id = "F1"
    name = "blocking-under-lock"
    summary = ("blocking op (or transitively blocking call) inside a "
               "lock-held region; or a lock-guarded attribute written "
               "without the lock")

    def check(self, ctx) -> Iterator[Finding]:
        yield from self._blocking(ctx)
        yield from self._guard_discipline(ctx)

    def _blocking(self, ctx) -> Iterator[Finding]:
        for fn in ctx.project.functions.values():
            regions = ctx.regions(fn)
            if not regions:
                continue
            locals_ = local_types(ctx.project, fn)
            direct = {id(op.node): op
                      for op in ctx.blocking.direct.get(fn.qualname, [])}
            for region in regions:
                seen_calls: Set[int] = set()
                for node in nodes_under(region.node):
                    op = direct.get(id(node))
                    if op is not None:
                        yield _finding(
                            self, fn, node,
                            f"{op.detail} while holding {region.lock_id} "
                            f"in {_short(fn.qualname)}")
                        continue
                    if not isinstance(node, ast.Call) or id(node) in seen_calls:
                        continue
                    seen_calls.add(id(node))
                    for tgt in resolve_call(ctx.project, fn, node, locals_,
                                            duck=True):
                        hit = ctx.blocking.may_block(tgt.qualname)
                        if hit is None:
                            continue
                        chain, op = hit
                        path = " -> ".join(_short(q) for q in chain)
                        yield _finding(
                            self, fn, node,
                            f"call to {_short(tgt.qualname)} may block "
                            f"({op.detail} via {path}) while holding "
                            f"{region.lock_id}")
                        break

    def _guard_discipline(self, ctx) -> Iterator[Finding]:
        callers = _reverse_edges(ctx)
        # class qual -> attr -> (locked write sites, unlocked write sites)
        writes: Dict[str, Dict[str, Tuple[list, list]]] = {}
        for fn in ctx.project.functions.values():
            if fn.name == "__init__":
                continue
            locals_ = local_types(ctx.project, fn)
            regions = ctx.regions(fn)
            fresh = _fresh_locals(fn)
            for sub in ast.walk(fn.node):
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                elif isinstance(sub, ast.Delete):
                    targets = sub.targets
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _MUTATOR_METHS and \
                        isinstance(sub.func.value, ast.Attribute):
                    # self._table.pop(...) mutates _table just like
                    # ``del self._table[k]`` — count it as a write
                    targets = [sub.func.value]
                else:
                    continue
                for t in targets:
                    base = t
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    if not isinstance(base, ast.Attribute):
                        continue
                    recv = base.value
                    if isinstance(recv, ast.Name) and recv.id in fresh:
                        continue  # freshly constructed, not yet shared
                    rc = receiver_class(ctx.project, fn, recv, locals_)
                    if rc is None:
                        continue
                    lock = _holding_lock_of(ctx, rc, regions, sub) or \
                        _always_called_under_lock(ctx, fn, rc, callers)
                    slot = writes.setdefault(rc.qualname, {}).setdefault(
                        base.attr, ([], []))
                    (slot[0] if lock else slot[1]).append((fn, sub, base.attr))
        for cq, attrs in sorted(writes.items()):
            for attr, (locked, unlocked) in sorted(attrs.items()):
                if not locked or not unlocked:
                    continue
                lfn = locked[0][0]
                for fn, node, _ in unlocked:
                    yield _finding(
                        self, fn, node,
                        f"{_short(cq)}.{attr} is written under the class "
                        f"lock in {_short(lfn.qualname)} but without it "
                        f"here — inconsistently guarded state")


def _fresh_locals(fn: FunctionInfo) -> Set[str]:
    """Names bound from a constructor call in this function: writes to their
    attributes are pre-publication initialization, not shared-state races."""
    out: Set[str] = set()
    for sub in ast.walk(fn.node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            fname = dotted_name(sub.value.func)
            last = (fname or "").split(".")[-1].lstrip("_")
            if last[:1].isupper():
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _reverse_edges(ctx) -> Dict[str, List[Tuple[FunctionInfo, ast.AST]]]:
    """callee qualname -> [(caller, call node)] over the whole graph."""
    out: Dict[str, List[Tuple[FunctionInfo, ast.AST]]] = {}
    for caller_q, callees in ctx.graph.edges.items():
        caller = ctx.project.functions.get(caller_q)
        if caller is None:
            continue
        for tgt, node in callees:
            out.setdefault(tgt.qualname, []).append((caller, node))
    return out


def _always_called_under_lock(ctx, fn: FunctionInfo, rc: ClassInfo,
                              callers) -> bool:
    """A private helper whose EVERY resolvable call site sits inside a
    with-region of a lock owned by ``rc`` effectively runs locked — its
    writes are guarded even though it takes no lock itself (e.g. the
    supervisor's ``_reap_retired``, called only from the locked tick)."""
    incoming = callers.get(fn.qualname, [])
    if not incoming or not fn.name.startswith("_"):
        return False
    mro_quals = {c.qualname for c in ctx.project.mro(rc)}
    for caller, node in incoming:
        under = False
        for region in ctx.regions(caller):
            if region.lock_id.rsplit(".", 1)[0] not in mro_quals:
                continue
            if any(n is node for n in nodes_under(region.node)):
                under = True
                break
        if not under:
            return False
    return True


def _holding_lock_of(ctx, rc: ClassInfo, regions, node: ast.AST
                     ) -> Optional[str]:
    """Is ``node`` inside a with-region of a lock OWNED by class ``rc``?"""
    mro_quals = {c.qualname for c in ctx.project.mro(rc)}
    for region in regions:
        owner = region.lock_id.rsplit(".", 1)[0]
        if owner not in mro_quals:
            continue
        for n in nodes_under(region.node):
            if n is node:
                return region.lock_id
    return None


# --------------------------------------------------------------------------
# F2: lock-order inversion
# --------------------------------------------------------------------------


class F2LockOrderInversion(FlowRule):
    """If one code path takes lock A then (directly or through calls) lock B
    while another takes B then A, two threads can each hold one and wait
    forever on the other. Edges are collected across the call graph:
    ``with A: self.helper()`` contributes A→B when the helper acquires B.
    Reported once per inverted pair with both witness sites."""

    id = "F2"
    name = "lock-order-inversion"
    summary = "lock A held while acquiring B on one path, B-then-A on another"

    def check(self, ctx) -> Iterator[Finding]:
        # acquired-locks closure per function
        acquired: Dict[str, Set[str]] = {}
        for fn in ctx.project.functions.values():
            acquired[fn.qualname] = {r.lock_id for r in ctx.regions(fn)}
        changed = True
        passes = 0
        while changed and passes < 32:
            changed = False
            passes += 1
            for q, callees in ctx.graph.edges.items():
                cur = acquired.setdefault(q, set())
                before = len(cur)
                for tgt, _n in callees:
                    cur |= acquired.get(tgt.qualname, set())
                if len(cur) != before:
                    changed = True
        # edges: (A, B) -> witness (fn, node)
        edges: Dict[Tuple[str, str], Tuple[FunctionInfo, ast.AST]] = {}
        for fn in ctx.project.functions.values():
            regions = ctx.regions(fn)
            if not regions:
                continue
            locals_ = local_types(ctx.project, fn)
            for region in regions:
                a = region.lock_id
                for node in nodes_under(region.node):
                    if isinstance(node, ast.With):
                        inner = [r for r in ctx.regions(fn)
                                 if r.node is node]
                        for r in inner:
                            if r.lock_id != a:
                                edges.setdefault((a, r.lock_id), (fn, node))
                    elif isinstance(node, ast.Call):
                        for tgt in resolve_call(ctx.project, fn, node,
                                                locals_):
                            for b in acquired.get(tgt.qualname, set()):
                                if b != a:
                                    edges.setdefault((a, b), (fn, node))
        reported: Set[Tuple[str, str]] = set()
        for (a, b), (fn, node) in sorted(
                edges.items(), key=lambda kv: (kv[1][0].path,
                                               kv[1][1].lineno)):
            if (b, a) not in edges or (b, a) in reported:
                continue
            reported.add((a, b))
            ofn, onode = edges[(b, a)]
            yield _finding(
                self, fn, node,
                f"lock order inversion: {a} -> {b} here, but {b} -> {a} in "
                f"{_short(ofn.qualname)} ({ofn.path}:{onode.lineno})")


# --------------------------------------------------------------------------
# F3: thread loop with no reachable stop check
# --------------------------------------------------------------------------

_STOPPISH = {
    "stopped", "stop", "stop_evt", "_stop_evt", "stop_event", "_stop_event",
    "is_set", "closed", "_closed", "shutdown", "_shutdown", "running",
    "_running", "exiting", "_exiting", "done", "_done", "stop_requested",
}


class F3UnstoppableLoop(FlowRule):
    """Every thread body must be able to observe shutdown: a ``while True``
    on a thread root with no ``break``/``return`` and no stop-flag check
    (directly or in a callee within two hops) runs until process exit,
    which turns clean shutdown into ``ensure_proc_terminate`` SIGKILLs and
    leaks the thread past ``stop()``/``join()``."""

    id = "F3"
    name = "unstoppable-thread-loop"
    summary = ("while-True on a thread root with no reachable "
               "stop-flag/stop-event check and no break/return")

    def check(self, ctx) -> Iterator[Finding]:
        seen_loops: Set[int] = set()
        for root in ctx.roots:
            reach = ctx.graph.reachable([root.fn.qualname], max_depth=8)
            for qual in sorted(reach):
                fn = ctx.project.functions.get(qual)
                if fn is None:
                    continue
                for loop in _const_true_loops(fn.node):
                    if id(loop) in seen_loops:
                        continue
                    seen_loops.add(id(loop))
                    if self._can_stop(ctx, fn, loop, depth=2):
                        continue
                    yield _finding(
                        self, fn, loop,
                        f"while-True in {_short(fn.qualname)} (thread root "
                        f"{_short(root.fn.qualname)}) has no reachable "
                        f"stop check, break, or return")

    def _can_stop(self, ctx, fn: FunctionInfo, loop: ast.While,
                  depth: int) -> bool:
        if _mentions_stoppish(loop.test):
            return True
        for stmt in loop.body:
            for node in _walk_same_function(stmt):
                if isinstance(node, ast.Return):
                    return True
                if isinstance(node, ast.Break) and \
                        _owner_loop(node, stmt, loop) is loop:
                    return True
                if _mentions_stoppish(node):
                    return True
                if depth > 0 and isinstance(node, ast.Call):
                    locals_ = local_types(ctx.project, fn)
                    for tgt in resolve_call(ctx.project, fn, node, locals_):
                        if self._callee_stops(ctx, tgt, depth - 1, set()):
                            return True
        return False

    def _callee_stops(self, ctx, fn: FunctionInfo, depth: int,
                      seen: Set[str]) -> bool:
        if fn.qualname in seen:
            return False
        seen.add(fn.qualname)
        for node in ast.walk(fn.node):
            if _mentions_stoppish(node):
                return True
            if isinstance(node, ast.Raise):
                return True  # raising unwinds out of the loop
        if depth > 0:
            for tgt, _n in ctx.graph.callees(fn.qualname):
                if self._callee_stops(ctx, tgt, depth - 1, seen):
                    return True
        return False


def _const_true_loops(fn_node: ast.AST) -> Iterator[ast.While]:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.While) and isinstance(node.test,
                                                      ast.Constant) \
                and bool(node.test.value):
            yield node


def _mentions_stoppish(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STOPPISH:
            return True
        if isinstance(sub, ast.Name) and sub.id in _STOPPISH:
            return True
    return False


def _walk_same_function(stmt: ast.AST) -> Iterator[ast.AST]:
    """Walk without descending into nested function/class definitions.
    When the root itself is a function def, its own body IS walked."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        stack = list(ast.iter_child_nodes(stmt))
    else:
        stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _owner_loop(brk: ast.AST, top_stmt: ast.AST,
                outer: ast.While) -> Optional[ast.AST]:
    """The loop a ``break`` belongs to, searching down from ``outer``."""
    # parents were annotated at parse time by the project loader
    cur = getattr(brk, "_ba3c_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        cur = getattr(cur, "_ba3c_parent", None)
    return None


# --------------------------------------------------------------------------
# F4: join-on-self / join-under-lock
# --------------------------------------------------------------------------


class F4BadJoin(FlowRule):
    """``self.join()`` reachable from a thread's own ``run()`` deadlocks the
    thread on itself; ``.join()`` while holding a lock deadlocks if the
    joined thread ever needs that lock to exit its loop (and stalls every
    contender even when it doesn't). Joins belong after locks are released,
    in the owner's ``stop()``/``close()`` epilogue."""

    id = "F4"
    name = "bad-join"
    summary = "join-on-self from run(), or .join() inside a lock-held region"

    def check(self, ctx) -> Iterator[Finding]:
        yield from self._join_on_self(ctx)
        yield from self._join_under_lock(ctx)

    def _join_on_self(self, ctx) -> Iterator[Finding]:
        for ci in ctx.project.classes.values():
            if not ctx.project.is_threadish(ci):
                continue
            run = ci.methods.get("run")
            if run is None:
                continue
            reach = ctx.graph.reachable([run.qualname], max_depth=8)
            for qual in sorted(reach):
                fn = ctx.project.functions.get(qual)
                if fn is None or fn.cls is None:
                    continue
                fci = ctx.project.class_of(fn)
                if fci is None or ci.qualname not in {
                        c.qualname for c in ctx.project.mro(fci)} and \
                        fci.qualname not in {
                            c.qualname for c in ctx.project.mro(ci)}:
                    continue
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "join" and \
                            isinstance(node.func.value, ast.Name) and \
                            node.func.value.id == "self":
                        yield _finding(
                            self, fn, node,
                            f"self.join() in {_short(fn.qualname)} is "
                            f"reachable from {_short(run.qualname)} — a "
                            f"thread joining itself deadlocks")

    def _join_under_lock(self, ctx) -> Iterator[Finding]:
        joins = _JoinClosure(ctx)
        for fn in ctx.project.functions.values():
            regions = ctx.regions(fn)
            if not regions:
                continue
            locals_ = local_types(ctx.project, fn)
            for region in regions:
                for node in nodes_under(region.node):
                    if not isinstance(node, ast.Call):
                        continue
                    if _is_untimed_join(node):
                        yield _finding(
                            self, fn, node,
                            f".join() while holding {region.lock_id} in "
                            f"{_short(fn.qualname)}")
                        continue
                    for tgt in resolve_call(ctx.project, fn, node, locals_,
                                            duck=True):
                        chain = joins.may_join(tgt.qualname)
                        if chain:
                            path = " -> ".join(_short(q) for q in chain)
                            yield _finding(
                                self, fn, node,
                                f"call to {_short(tgt.qualname)} reaches a "
                                f".join() ({path}) while holding "
                                f"{region.lock_id}")
                            break


class _JoinClosure:
    """qualname -> witness chain to a function containing an UNTIMED
    .join() call. Timed joins (``join(timeout=...)`` / ``join(0)``) are
    bounded reaps, not deadlock hazards; joins inside nested function defs
    (e.g. atexit handlers registered by ensure_proc_terminate) do not run
    at call time and are excluded."""

    def __init__(self, ctx):
        self.chains: Dict[str, List[str]] = {}
        for fn in ctx.project.functions.values():
            for node in _walk_same_function(fn.node):
                if _is_untimed_join(node):
                    self.chains[fn.qualname] = [fn.qualname]
                    break
        changed = True
        while changed:
            changed = False
            for q, callees in ctx.graph.edges.items():
                if q in self.chains:
                    continue
                for tgt, _n in callees:
                    hit = self.chains.get(tgt.qualname)
                    if hit is not None and q not in hit and len(hit) < 12:
                        self.chains[q] = [q] + hit
                        changed = True
                        break

    def may_join(self, qual: str) -> Optional[List[str]]:
        return self.chains.get(qual)


def _is_untimed_join(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and not node.args
            and not any(kw.arg == "timeout" for kw in node.keywords))


# --------------------------------------------------------------------------
# F5: lifecycle leak
# --------------------------------------------------------------------------

_STOP_METHS = {"stop", "close", "shutdown", "terminate", "kill", "cancel"}


class F5LifecycleLeak(FlowRule):
    """Whoever starts a thread owns its join. A class (or function) that
    constructs AND starts a thread-like object but never joins it leaks the
    thread past shutdown: ``stop()`` returns while the loop is mid-tick,
    state teardown races the still-running body, and process exit relies on
    daemon reaping. Matching is token-based (the attribute/variable the
    object is bound to), with ``for t in self.threads`` aliasing."""

    id = "F5"
    name = "lifecycle-leak"
    summary = ("thread-like object constructed and started but never "
               "joined (and/or never stopped) by its owner")

    def check(self, ctx) -> Iterator[Finding]:
        for ci in ctx.project.classes.values():
            yield from self._check_scope(
                ctx, list(ci.methods.values()), f"class {_short(ci.qualname)}")
        for fn in ctx.project.functions.values():
            if fn.cls is None:
                yield from self._check_scope(
                    ctx, [fn], f"function {_short(fn.qualname)}")

    def _check_scope(self, ctx, fns: List[FunctionInfo],
                     scope: str) -> Iterator[Finding]:
        created: Dict[str, Tuple[FunctionInfo, ast.AST, str]] = {}
        started: Set[str] = set()
        joined: Set[str] = set()
        stopped: Set[str] = set()
        aliases: Dict[str, str] = {}

        for fn in fns:
            mod = ctx.project.module_of(fn)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    ctor = dotted_name(node.value.func)
                    if ctor and ctx.is_threadish_ctor(mod.resolve(ctor)):
                        for t in node.targets:
                            tok = _token_of(t)
                            if tok:
                                created.setdefault(
                                    tok, (fn, node.value,
                                          mod.resolve(ctor)))
                elif isinstance(node, ast.For):
                    tok = _token_of(node.target)
                    src = _token_of(node.iter)
                    if tok and src:
                        aliases[tok] = src
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, (ast.Name, ast.Attribute)):
                    tok = None
                    for t in node.targets:
                        tok = tok or _token_of(t)
                    src = _token_of(node.value)
                    if tok and src and tok != src:
                        aliases[src] = tok  # self.X = local: join via X counts
                        aliases[tok] = src
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    tok = _token_of(node.func.value)
                    if tok is None:
                        continue
                    if node.func.attr == "start":
                        started.add(tok)
                    elif node.func.attr == "join":
                        joined.add(tok)
                    elif node.func.attr in _STOP_METHS:
                        stopped.add(tok)

        def expand(toks: Set[str]) -> Set[str]:
            out = set(toks)
            for t in toks:
                a = aliases.get(t)
                if a:
                    out.add(a)
            return out

        started = expand(started)
        joined = expand(joined)
        stopped = expand(stopped)
        for tok, (fn, site, ctor) in sorted(created.items()):
            if tok not in started:
                continue  # constructed here, started/owned elsewhere
            if tok in joined:
                continue
            if tok in stopped:
                yield _finding(
                    self, fn, site,
                    f"{scope} starts {ctor.split('.')[-1]} ({tok!r}) and "
                    f"stops it but never joins it — shutdown returns while "
                    f"the thread is still running")
            else:
                yield _finding(
                    self, fn, site,
                    f"{scope} starts {ctor.split('.')[-1]} ({tok!r}) but "
                    f"never stops or joins it")


def _token_of(expr: ast.AST) -> Optional[str]:
    """The identifying token of a receiver/target: the last attribute name
    of a self-chain, or a bare variable name."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, (ast.Tuple, ast.List)) and expr.elts:
        return _token_of(expr.elts[0])
    if isinstance(expr, ast.Call):
        return _token_of(expr.func) if isinstance(expr.func, ast.Attribute) \
            and expr.func.attr in ("values", "items", "keys") and \
            isinstance(expr.func.value, (ast.Name, ast.Attribute)) \
            else None
    return None


# --------------------------------------------------------------------------
# F6: project-API conformance
# --------------------------------------------------------------------------

#: attributes provided by external bases we model (threading/multiprocessing)
_EXTERNAL_ATTRS = {
    "threading.Thread": {
        "start", "join", "run", "is_alive", "daemon", "name", "ident",
        "native_id", "isDaemon", "setDaemon", "getName", "setName",
    },
    "multiprocessing.Process": {
        "start", "join", "run", "is_alive", "daemon", "name", "pid",
        "exitcode", "terminate", "kill", "close", "sentinel", "authkey",
    },
}

_OBJECT_ATTRS = {
    "__init__", "__class__", "__dict__", "__repr__", "__str__", "__eq__",
    "__hash__", "__reduce__", "__sizeof__", "__format__", "__dir__",
}


class F6ApiConformance(FlowRule):
    """A call against a project module or project-typed object must resolve
    statically: ``logger.exception(...)`` against a logger module that never
    defined ``exception`` raised AttributeError *inside the tick guard it
    was protecting* and sat latent from PR 7 to PR 16. Modules with
    ``__getattr__`` and classes with dynamic attribute machinery are
    exempt; classes with unmodeled external bases are only checked against
    the attribute tables we have."""

    id = "F6"
    name = "api-conformance"
    summary = ("attribute call on a project module/object that does not "
               "exist statically")

    def check(self, ctx) -> Iterator[Finding]:
        self._absorb_external_writes(ctx)
        for fn in ctx.project.functions.values():
            mod = ctx.project.module_of(fn)
            locals_ = local_types(ctx.project, fn)
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                attr = node.func.attr
                base = node.func.value
                # module attribute call
                base_dotted = dotted_name(base)
                if base_dotted and "." not in base_dotted or isinstance(
                        base, ast.Attribute):
                    canon = mod.resolve(base_dotted) if base_dotted else None
                    m = ctx.project.find_module(canon) if canon else None
                    if m is not None:
                        if not m.has_module_getattr and \
                                attr not in m.toplevel:
                            yield _finding(
                                self, fn, node,
                                f"module {m.modname} has no attribute "
                                f"{attr!r} (called from "
                                f"{_short(fn.qualname)})")
                        continue
                # typed-object method call (self.x() / task.x())
                if isinstance(base, ast.Name):
                    rc = receiver_class(ctx.project, fn, base, locals_)
                    if rc is None:
                        continue
                    if self._class_has(ctx, rc, attr):
                        continue
                    yield _finding(
                        self, fn, node,
                        f"{_short(rc.qualname)} has no attribute {attr!r} "
                        f"(called from {_short(fn.qualname)})")

    def _class_has(self, ctx, rc: ClassInfo, attr: str) -> bool:
        if attr in _OBJECT_ATTRS or (attr.startswith("__")
                                     and attr.endswith("__")):
            return True
        for c in ctx.project.mro(rc):
            if c.dynamic_attrs or attr in c.attrs or attr in c.methods:
                return True
        ext = ctx.project.external_bases(rc)
        for b in ext:
            allowed = _EXTERNAL_ATTRS.get(b)
            if allowed is None:
                return True  # unmodeled base: stand down
            if attr in allowed:
                return True
        return False

    def _absorb_external_writes(self, ctx) -> None:
        """``obj.attr = x`` on a typed receiver anywhere in the project makes
        ``attr`` a real attribute of that class (external wiring like
        ``router.latency_tap = tap`` must not read as nonexistence)."""
        if getattr(ctx, "_f6_absorbed", False):
            return
        ctx._f6_absorbed = True
        for fn in ctx.project.functions.values():
            locals_ = local_types(ctx.project, fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        rc = receiver_class(ctx.project, fn, t.value, locals_)
                        if rc is not None:
                            rc.attrs.add(t.attr)


def all_flow_rules() -> List[FlowRule]:
    return [
        F1BlockingUnderLock(),
        F2LockOrderInversion(),
        F3UnstoppableLoop(),
        F4BadJoin(),
        F5LifecycleLeak(),
        F6ApiConformance(),
    ]
