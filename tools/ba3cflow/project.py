"""ba3cflow project model: whole-repo symbol table.

ba3clint sees one file at a time; ba3cflow's rules need to answer questions
like "what class is ``task`` in this method?" and "does ``utils.logger``
define ``exception``?" — so this module parses every file under the analyzed
roots once and builds:

- a module table keyed by dotted name (``distributed_ba3c_tpu.pod.cache``),
  each with its import-alias map and top-level name set;
- a class table with resolved base chains, per-method nodes, ``self.x``
  attribute inventory, and best-effort attribute *types* (``self._pump =
  LatestWinsPump(...)`` records ``pump -> <qual of LatestWinsPump>``);
- a function table keyed by qualified name (``mod.Class.method`` /
  ``mod.func``).

Everything downstream (callgraph, rules) resolves names through this table
and degrades gracefully: an unresolvable receiver means "unknown", never a
guess. Heuristics over proofs, same contract as ba3clint.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.ba3clint.engine import annotate_parents, dotted_name, iter_py_files

#: bases (canonical dotted) that make a class "thread-like": instances own an
#: OS thread/process and must be stopped AND joined.
THREAD_BASES = {
    "threading.Thread",
    "multiprocessing.Process",
}

#: canonical dotted ctors that are thread-like regardless of the class table
#: (covers ``threading.Thread(target=...)`` style construction).
THREAD_CTORS = {
    "threading.Thread",
    "multiprocessing.Process",
}

#: canonical dotted names whose calls produce lock-like objects.
LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "multiprocessing.Lock",
    "multiprocessing.RLock",
    "multiprocessing.Condition",
}


class FunctionInfo:
    """One function or method definition."""

    __slots__ = ("qualname", "modname", "cls", "name", "node", "path")

    def __init__(self, qualname: str, modname: str, cls: Optional[str],
                 node: ast.FunctionDef, path: str):
        self.qualname = qualname
        self.modname = modname
        self.cls = cls  # simple class name, or None for module functions
        self.name = node.name
        self.node = node
        self.path = path

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<fn {self.qualname}>"


class ClassInfo:
    """One class definition plus facts mined from its methods."""

    def __init__(self, qualname: str, modname: str, node: ast.ClassDef,
                 path: str):
        self.qualname = qualname
        self.modname = modname
        self.name = node.name
        self.node = node
        self.path = path
        #: canonical dotted base names (resolved through imports)
        self.bases: List[str] = []
        self.methods: Dict[str, FunctionInfo] = {}
        #: every attribute name assigned as ``self.X = ...`` anywhere, plus
        #: __slots__ entries and class-body assignments
        self.attrs: Set[str] = set()
        #: attr -> canonical dotted type when inferable (ctor call or
        #: annotation); lock attrs map to the LOCK_CTORS entry
        self.attr_types: Dict[str, str] = {}
        #: attr aliases: ``self._ready = threading.Condition(self._lock)``
        #: makes _ready and _lock the SAME lock for ordering purposes
        self.lock_aliases: Dict[str, str] = {}
        #: True when the class body/methods use setattr/getattr/__getattr__
        #: on self — attribute conformance checks must stand down
        self.dynamic_attrs: bool = False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<class {self.qualname}>"


class ModuleSyms:
    """One module: imports, top-level names, functions, classes."""

    def __init__(self, modname: str, path: str, tree: ast.Module, source: str):
        self.modname = modname
        self.path = path
        self.tree = tree
        self.source = source
        #: local alias -> canonical dotted origin (same semantics as
        #: ba3clint.ModuleInfo, duplicated here so the project model does not
        #: require per-file ModuleInfo objects)
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        #: every name bound at module top level (defs, assigns, imports)
        self.toplevel: Set[str] = set()
        #: module defines __getattr__ → conformance checks stand down
        self.has_module_getattr: bool = False

    def resolve(self, name: str) -> str:
        """Canonicalize a dotted name's head through this module's imports."""
        head, _, rest = name.partition(".")
        origin = self.imports.get(head)
        if origin is None:
            return name
        return f"{origin}.{rest}" if rest else origin


def _module_name(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = rel.split(os.sep)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _collect_imports(mod: ModuleSyms) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    mod.imports[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    mod.imports.setdefault(head, head)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                mod.imports[a.asname or a.name] = f"{node.module}.{a.name}"


def _collect_toplevel(mod: ModuleSyms) -> None:
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            mod.toplevel.add(node.name)
            if node.name == "__getattr__":
                mod.has_module_getattr = True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    mod.toplevel.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            mod.toplevel.add(el.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            mod.toplevel.add(node.target.id)
        elif isinstance(node, ast.Import):
            for a in node.names:
                mod.toplevel.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                mod.toplevel.add(a.asname or a.name)
        elif isinstance(node, (ast.If, ast.Try)):
            # names bound under TYPE_CHECKING / try-import guards still exist
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.ClassDef)):
                    mod.toplevel.add(sub.name)
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            mod.toplevel.add(t.id)
                elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for a in sub.names:
                        mod.toplevel.add((a.asname or a.name).split(".")[0])


def ann_to_dotted(ann: ast.AST) -> Optional[str]:
    """``x: Foo`` / ``x: "Foo"`` / ``x: Optional[Foo]`` -> ``Foo``."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        # Optional[Foo] / list[Foo]: take the (first) parameter for Optional,
        # otherwise bail — container element types are handled separately.
        base = dotted_name(ann.value)
        if base and base.split(".")[-1] in {"Optional"}:
            return ann_to_dotted(ann.slice)
        return None
    return dotted_name(ann)


def _collect_class_facts(mod: ModuleSyms, cls: ClassInfo) -> None:
    node = cls.node
    for b in node.bases:
        nm = dotted_name(b)
        if nm:
            cls.bases.append(mod.resolve(nm))
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{mod.modname}.{cls.name}.{stmt.name}"
            fi = FunctionInfo(qual, mod.modname, cls.name, stmt, mod.path)
            cls.methods[stmt.name] = fi
            mod.functions[qual] = fi
            if stmt.name in ("__getattr__", "__getattribute__"):
                cls.dynamic_attrs = True
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    cls.attrs.add(t.id)
                    if t.id == "__slots__" and isinstance(
                            stmt.value, (ast.Tuple, ast.List)):
                        for el in stmt.value.elts:
                            if isinstance(el, ast.Constant) and isinstance(
                                    el.value, str):
                                cls.attrs.add(el.value)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                            ast.Name):
            cls.attrs.add(stmt.target.id)

    # mine methods for self.X facts
    for m in cls.methods.values():
        for sub in ast.walk(m.node):
            if isinstance(sub, ast.Call):
                fn = dotted_name(sub.func)
                if fn in ("setattr", "getattr") and sub.args and isinstance(
                        sub.args[0], ast.Name) and sub.args[0].id == "self":
                    cls.dynamic_attrs = True
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                value = sub.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    cls.attrs.add(t.attr)
                    ann = getattr(sub, "annotation", None)
                    if ann is not None:
                        ty = ann_to_dotted(ann)
                        if ty:
                            cls.attr_types.setdefault(t.attr, mod.resolve(ty))
                    if isinstance(value, ast.Call):
                        ctor = dotted_name(value.func)
                        if ctor:
                            resolved = mod.resolve(ctor)
                            cls.attr_types.setdefault(t.attr, resolved)
                            # Condition(self._lock) shares its lock: alias it
                            if (resolved.split(".")[-1] == "Condition"
                                    and value.args):
                                arg = dotted_name(value.args[0])
                                if arg and arg.startswith("self."):
                                    cls.lock_aliases[t.attr] = (
                                        arg.split(".", 1)[1])


class Project:
    """The whole-repo symbol table."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleSyms] = {}
        self.by_path: Dict[str, ModuleSyms] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        #: method name -> defining FunctionInfos (closed-world duck typing)
        self.method_index: Dict[str, List[FunctionInfo]] = {}
        #: files that failed to parse: path -> SyntaxError
        self.broken: Dict[str, SyntaxError] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def load(cls, paths: Sequence[str], root: str = ".") -> "Project":
        proj = cls()
        for path in iter_py_files(paths):
            proj._add_file(path, root)
        proj._link()
        return proj

    def _add_file(self, path: str, root: str) -> None:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = annotate_parents(ast.parse(source, filename=path))
        except SyntaxError as e:
            self.broken[path] = e
            return
        modname = _module_name(path, root)
        mod = ModuleSyms(modname, path, tree, source)
        _collect_imports(mod)
        _collect_toplevel(mod)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{modname}.{stmt.name}"
                mod.functions[qual] = FunctionInfo(qual, modname, None, stmt,
                                                   path)
            elif isinstance(stmt, ast.ClassDef):
                ci = ClassInfo(f"{modname}.{stmt.name}", modname, stmt, path)
                _collect_class_facts(mod, ci)
                mod.classes[stmt.name] = ci
        self.modules[modname] = mod
        self.by_path[path] = mod

    def _link(self) -> None:
        for mod in self.modules.values():
            self.functions.update(mod.functions)
            for ci in mod.classes.values():
                self.classes[ci.qualname] = ci
                for name, fi in ci.methods.items():
                    self.method_index.setdefault(name, []).append(fi)

    # -- lookup ------------------------------------------------------------

    def module_of(self, fn: FunctionInfo) -> ModuleSyms:
        return self.modules[fn.modname]

    def find_module(self, dotted: str) -> Optional[ModuleSyms]:
        return self.modules.get(dotted)

    def find_class(self, dotted: Optional[str]) -> Optional[ClassInfo]:
        """Resolve a canonical dotted name to a project class, tolerating
        both ``pkg.mod.Cls`` and re-export styles."""
        if not dotted:
            return None
        return self.classes.get(dotted)

    def resolve_class(self, modname: str, dotted: Optional[str]
                      ) -> Optional[ClassInfo]:
        """find_class with a fallback for module-local bare names: a base
        or annotation naming a sibling class resolves through no import, so
        try ``modname.dotted`` too."""
        if not dotted:
            return None
        return self.classes.get(dotted) or \
            self.classes.get(f"{modname}.{dotted}")

    def class_of(self, fn: FunctionInfo) -> Optional[ClassInfo]:
        if fn.cls is None:
            return None
        return self.modules[fn.modname].classes.get(fn.cls)

    def mro(self, ci: ClassInfo) -> Iterator[ClassInfo]:
        """Linearized project-class ancestry (self first, bases depth-first;
        external bases are skipped — use :meth:`external_bases`)."""
        seen: Set[str] = set()
        stack = [ci]
        while stack:
            cur = stack.pop(0)
            if cur.qualname in seen:
                continue
            seen.add(cur.qualname)
            yield cur
            for b in cur.bases:
                bi = self.resolve_class(cur.modname, b)
                if bi is not None:
                    stack.append(bi)

    def external_bases(self, ci: ClassInfo) -> Set[str]:
        """Canonical dotted bases (transitively) that are NOT project classes."""
        out: Set[str] = set()
        for c in self.mro(ci):
            for b in c.bases:
                if self.resolve_class(c.modname, b) is None:
                    out.add(b)
        return out

    def find_method(self, ci: ClassInfo, name: str) -> Optional[FunctionInfo]:
        for c in self.mro(ci):
            m = c.methods.get(name)
            if m is not None:
                return m
        return None

    def is_threadish(self, ci_or_dotted) -> bool:
        """Does this class (or canonical dotted ctor name) own an OS thread?"""
        if isinstance(ci_or_dotted, str):
            if ci_or_dotted in THREAD_CTORS:
                return True
            ci = self.find_class(ci_or_dotted)
        else:
            ci = ci_or_dotted
        if ci is None:
            return False
        return bool(self.external_bases(ci) & THREAD_BASES)

    def canonical_lock(self, ci: ClassInfo, attr: str) -> str:
        """Stable identity for ``self.<attr>`` as a lock, following
        Condition-shares-lock aliases, keyed on the DEFINING class so
        subclasses agree."""
        attr = ci.lock_aliases.get(attr, attr)
        for c in self.mro(ci):
            if attr in c.attrs:
                return f"{c.qualname}.{attr}"
        return f"{ci.qualname}.{attr}"
