"""ba3cflow: interprocedural concurrency & lifecycle analyzer.

Where ba3clint reads one file at a time and ba3caudit reads jaxpr/HLO
traces, ba3cflow reads the *call graph*: it builds a whole-repo symbol
table over ``distributed_ba3c_tpu/`` and ``tools/``, discovers thread
roots, and propagates lock-held and blocking-op facts along call paths.
Rule catalog (details in docs/static_analysis.md):

- **F1** blocking op (or transitively blocking call) while a lock/condition
  is held; inconsistently lock-guarded attribute writes
- **F2** lock-order inversion across the call graph
- **F3** thread loop with no reachable stop-flag/stop-event check
- **F4** join-on-self, or ``.join()`` under a lock
- **F5** lifecycle leak: threads/pumps/servers started but never joined
- **F6** project-API conformance: calls on project modules/objects that do
  not exist statically

Usage: ``python -m tools.ba3cflow [--json] [--sarif out.sarif]``.
Suppress per line with ``# ba3cflow: disable=F1 — justification``.
"""

from tools.ba3clint.engine import Finding  # shared finding type
from tools.ba3cflow.engine import FlowContext, analyze_paths, build_context, \
    filter_suppressed, run_rules


def all_rules():
    from tools.ba3cflow.rules import all_flow_rules
    return all_flow_rules()


__all__ = [
    "Finding",
    "FlowContext",
    "all_rules",
    "analyze_paths",
    "build_context",
    "filter_suppressed",
    "run_rules",
]
