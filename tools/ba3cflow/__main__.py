"""CLI: ``python -m tools.ba3cflow [paths...]``.

Exit status: 0 = clean, 1 = findings, 2 = bad usage — same contract as
ba3clint, so scripts/check.sh and the CI ``flow`` job gate on it directly.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from tools.ba3clint.engine import stale_suppressions
from tools.ba3cflow import all_rules
from tools.ba3cflow.engine import build_context, filter_suppressed, run_rules

DEFAULT_PATHS = ["distributed_ba3c_tpu", "tools"]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ba3cflow",
        description="Interprocedural concurrency/lifecycle analysis for the "
        "BA3C stack (rule catalog: docs/static_analysis.md).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=DEFAULT_PATHS,
        help="files or directories to analyze "
        f"(default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit findings as JSON instead of human-readable lines",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="also write findings as SARIF 2.1.0 to FILE",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--check-suppressions",
        action="store_true",
        help="flag '# ba3cflow: disable=' comments that mask no finding",
    )
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:4s} {r.name:32s} {r.summary}")
        return 0
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",") if s.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
        rules = [r for r in rules if r.id in wanted]

    try:
        ctx = build_context(args.paths)
    except FileNotFoundError as e:
        print(f"ba3cflow: {e}", file=sys.stderr)
        return 2
    raw = run_rules(ctx, rules)

    if args.check_suppressions:
        findings = []
        for path, mod in sorted(ctx.project.by_path.items()):
            per_file = [f for f in raw if f.path == path]
            findings.extend(
                stale_suppressions(mod.source, path, per_file, "ba3cflow"))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    else:
        findings = filter_suppressed(ctx, raw)

    if args.sarif:
        from tools.sarif import write_sarif
        write_sarif(args.sarif, findings, "ba3cflow", rules)
    if args.json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] {f.message}")
        n = len(findings)
        print(f"ba3cflow: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
