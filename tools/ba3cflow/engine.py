"""ba3cflow engine: project loading, rule driving, suppression filtering.

The flow analyzer is whole-project: rules see a :class:`FlowContext` holding
the symbol table, call graph, blocking-facts closure, and thread roots, and
emit :class:`~tools.ba3clint.engine.Finding` objects (same dataclass as
ba3clint, so JSON/SARIF plumbing is shared). Suppression comments use the
``# ba3cflow: disable=F1 — justification`` spelling with the exact semantics
of ba3clint's (trailing comment covers its line, standalone comment covers
the next line).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.ba3clint.engine import Finding, suppressions
from tools.ba3cflow.graph import BlockingFacts, CallGraph, lock_regions, \
    thread_roots
from tools.ba3cflow.project import Project, THREAD_CTORS


class FlowContext:
    """Everything a flow rule can ask about the project."""

    def __init__(self, project: Project):
        self.project = project
        self.graph = CallGraph(project)
        self.blocking = BlockingFacts(project, self.graph)
        self.roots = thread_roots(project, self.graph)
        self._regions_cache: Dict[str, list] = {}

    def regions(self, fn) -> list:
        cached = self._regions_cache.get(fn.qualname)
        if cached is None:
            cached = lock_regions(self.project, fn)
            self._regions_cache[fn.qualname] = cached
        return cached

    def is_threadish_ctor(self, resolved: str) -> bool:
        if resolved in THREAD_CTORS:
            return True
        return self.project.is_threadish(resolved)


def build_context(paths: Sequence[str], root: str = ".") -> FlowContext:
    return FlowContext(Project.load(paths, root))


def run_rules(ctx: FlowContext, rules: Iterable) -> List[Finding]:
    """All findings, unfiltered (suppressions NOT applied), sorted."""
    out: List[Finding] = []
    for path, err in sorted(ctx.project.broken.items()):
        out.append(Finding(path, err.lineno or 1, (err.offset or 1) - 1,
                           "E001", f"syntax error: {err.msg}"))
    seen: Set[tuple] = set()
    for rule in rules:
        for f in rule.check(ctx):
            key = (f.path, f.line, f.col, f.rule, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def filter_suppressed(ctx: FlowContext,
                      findings: Sequence[Finding]) -> List[Finding]:
    sup_by_path: Dict[str, Dict[int, Set[str]]] = {}
    out: List[Finding] = []
    for f in findings:
        mod = ctx.project.by_path.get(f.path)
        if mod is None:
            out.append(f)
            continue
        sup = sup_by_path.get(f.path)
        if sup is None:
            sup = suppressions(mod.source, tool="ba3cflow")
            sup_by_path[f.path] = sup
        disabled = sup.get(f.line, set())
        if "ALL" in disabled or f.rule.upper() in disabled:
            continue
        out.append(f)
    return out


def analyze_paths(paths: Sequence[str], rules: Optional[Iterable] = None,
                  root: str = ".") -> List[Finding]:
    from tools.ba3cflow.rules import all_flow_rules
    ctx = build_context(paths, root)
    return filter_suppressed(ctx, run_rules(ctx, rules or all_flow_rules()))
