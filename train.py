#!/usr/bin/env python
"""Training entry point — the reference's ``src/train.py`` CLI surface
(SURVEY.md §1 L7) over the TPU-native stack. See ``python train.py --help``.
"""

import sys

from distributed_ba3c_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
