"""Seaquest / Q*bert / CoinRun jax envs: mechanics and procgen invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.envs.jaxenv import coinrun, get_env, qbert, seaquest


def test_registry_has_all_five():
    for name in ("pong", "breakout", "seaquest", "qbert", "coinrun"):
        assert get_env(name).num_actions >= 4


class TestSeaquest:
    def test_oxygen_depletes_and_kills(self):
        st = seaquest.reset(jax.random.PRNGKey(0))
        step = jax.jit(seaquest.step)
        key = jax.random.PRNGKey(1)
        lives0 = int(st.lives)
        # sit still underwater: oxygen (200 substeps / 4 per step = 50 steps)
        for i in range(60):
            key, k = jax.random.split(key)
            st, _, _, d = step(st, jnp.int32(0), k)
            if int(st.lives) < lives0:
                break
        assert int(st.lives) < lives0 or bool(d)

    def test_surfacing_refills_oxygen(self):
        st = seaquest.reset(jax.random.PRNGKey(0))
        step = jax.jit(seaquest.step)
        key = jax.random.PRNGKey(2)
        for _ in range(10):  # burn some oxygen
            key, k = jax.random.split(key)
            st, _, _, _ = step(st, jnp.int32(0), k)
        low = float(st.oxygen)
        for _ in range(30):  # swim up to the surface
            key, k = jax.random.split(key)
            st, _, _, _ = step(st, jnp.int32(2), k)
        assert float(st.oxygen) > low

    def test_torpedo_scores(self):
        """Random play with lots of firing should kill fish eventually."""
        st = seaquest.reset(jax.random.PRNGKey(3))
        step = jax.jit(seaquest.step)
        key = jax.random.PRNGKey(4)
        total = 0.0
        rng = np.random.default_rng(0)
        for _ in range(300):
            key, k = jax.random.split(key)
            a = int(rng.choice([1, 1, 2, 3, 4, 5]))
            st, _, r, _ = step(st, jnp.int32(a), k)
            total += float(r)
        assert total > 0.0


class TestQbert:
    def test_hop_flips_cube_and_scores(self):
        st = qbert.reset(jax.random.PRNGKey(0))
        step = jax.jit(qbert.step)
        st2, _, r, _ = step(st, jnp.int32(2), jax.random.PRNGKey(1))  # down-right
        assert float(r) >= qbert.CUBE_POINTS
        assert int(st2.flipped.sum()) == int(st.flipped.sum()) + 1

    def test_hop_off_pyramid_costs_life(self):
        st = qbert.reset(jax.random.PRNGKey(0))
        step = jax.jit(qbert.step)
        st2, _, _, _ = step(st, jnp.int32(1), jax.random.PRNGKey(1))  # up-right off top
        assert int(st2.lives) == qbert.LIVES - 1

    def test_noop_is_safe_hop_free(self):
        st = qbert.reset(jax.random.PRNGKey(0))
        step = jax.jit(qbert.step)
        st2, _, r, _ = step(st, jnp.int32(0), jax.random.PRNGKey(1))
        assert float(r) == 0.0
        np.testing.assert_array_equal(np.asarray(st2.pos), np.asarray(st.pos))


class TestCoinRun:
    def test_levels_are_procedural(self):
        a = coinrun.reset(jax.random.PRNGKey(0))
        b = coinrun.reset(jax.random.PRNGKey(1))
        assert not np.array_equal(np.asarray(a.heights), np.asarray(b.heights))

    def test_spawn_platform_protected(self):
        for seed in range(5):
            st = coinrun.reset(jax.random.PRNGKey(seed))
            h = np.asarray(st.heights)
            s = np.asarray(st.spikes)
            assert (h[:4] > 0).all() and (h[-4:] > 0).all()
            assert not s[:4].any() and not s[-4:].any()

    def test_right_jump_clears_some_levels(self):
        step = jax.jit(coinrun.step)
        wins = 0
        for seed in range(8):
            key = jax.random.PRNGKey(seed)
            st = coinrun.reset(key)
            for _ in range(600):
                key, k = jax.random.split(key)
                st, _, r, d = step(st, jnp.int32(4), k)
                if float(r) > 0:
                    wins += 1
                if bool(d):
                    break
        assert wins >= 1

    def test_render_scrolls_with_agent(self):
        st = coinrun.reset(jax.random.PRNGKey(0))
        step = jax.jit(coinrun.step)
        f0 = np.asarray(coinrun.render(st))
        key = jax.random.PRNGKey(1)
        for _ in range(10):
            key, k = jax.random.split(key)
            st, obs, _, _ = step(st, jnp.int32(2), k)
        assert not np.array_equal(f0, np.asarray(obs))
