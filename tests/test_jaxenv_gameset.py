"""Space Invaders / Boxing / Assault jax envs (BASELINE.md's full reference
game set: Breakout, Pong, Boxing, Seaquest, Space Invaders, Assault)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.envs.jaxenv import (
    assault,
    boxing,
    get_env,
    space_invaders,
)


def test_registry_has_full_gameset():
    for name in (
        "pong", "breakout", "seaquest", "qbert", "coinrun",
        "space_invaders", "boxing", "assault",
    ):
        env = get_env(name)
        assert env.num_actions >= 4
        assert env.obs_shape == (84, 84)


def _common_invariants(env, n_steps=50, seed=0):
    """step under jit: uint8 84x84 obs, finite reward, auto-restart works."""
    st = env.reset(jax.random.PRNGKey(seed))
    step = jax.jit(env.step)
    key = jax.random.PRNGKey(seed + 1)
    rng = np.random.default_rng(seed)
    for _ in range(n_steps):
        key, k = jax.random.split(key)
        a = int(rng.integers(0, env.num_actions))
        st, obs, r, d = step(st, jnp.int32(a), k)
        assert obs.shape == (84, 84) and obs.dtype == jnp.uint8
        assert np.isfinite(float(r))
    return st


class TestSpaceInvaders:
    def test_invariants(self):
        _common_invariants(space_invaders)

    def test_shooting_scores(self):
        """Fire-heavy random play must eventually destroy an alien."""
        st = space_invaders.reset(jax.random.PRNGKey(0))
        step = jax.jit(space_invaders.step)
        key = jax.random.PRNGKey(1)
        rng = np.random.default_rng(1)
        total = 0.0
        for _ in range(300):
            key, k = jax.random.split(key)
            a = int(rng.choice([1, 1, 4, 5, 2, 3]))
            st, _, r, _ = step(st, jnp.int32(a), k)
            total += float(r)
        assert total > 0.0

    def test_points_are_row_scaled(self):
        # ALE parity: top row 30 ... bottom row 5
        pts = np.asarray(space_invaders.ROW_POINTS)
        assert pts[0] == 30.0 and pts[-1] == 5.0
        assert (np.diff(pts) < 0).all()

    def test_fleet_marches_and_descends(self):
        st = space_invaders.reset(jax.random.PRNGKey(0))
        step = jax.jit(space_invaders.step)
        key = jax.random.PRNGKey(2)
        y0 = float(st.origin[1])
        for _ in range(200):
            key, k = jax.random.split(key)
            st, _, _, d = step(st, jnp.int32(0), k)
            if bool(d):
                break
        assert float(st.origin[1]) > y0 or bool(d)


class TestBoxing:
    def test_invariants(self):
        _common_invariants(boxing)

    def test_punching_in_range_scores_plus_one(self):
        st = boxing.reset(jax.random.PRNGKey(0))
        # teleport the opponent into range
        st = st._replace(opp=st.me + jnp.array([0.05, 0.0]))
        st2, _, r, _ = jax.jit(boxing.step)(
            st, jnp.int32(1), jax.random.PRNGKey(1)
        )
        assert int(st2.my_score) >= 1
        # reward is net punches (mine minus opponent's landed)
        assert float(r) >= 1.0 - 4.0  # opponent can land some in 4 substeps

    def test_opponent_pursues(self):
        st = boxing.reset(jax.random.PRNGKey(0))
        step = jax.jit(boxing.step)
        d0 = float(jnp.linalg.norm(st.me - st.opp))
        key = jax.random.PRNGKey(1)
        for _ in range(10):
            key, k = jax.random.split(key)
            st, _, _, _ = step(st, jnp.int32(0), k)
        assert float(jnp.linalg.norm(st.me - st.opp)) < d0

    def test_ko_ends_episode(self):
        st = boxing.reset(jax.random.PRNGKey(0))
        st = st._replace(my_score=jnp.int32(boxing.KO))
        _, _, _, d = jax.jit(boxing.step)(
            st, jnp.int32(0), jax.random.PRNGKey(1)
        )
        assert bool(d)


class TestAssault:
    def test_invariants(self):
        _common_invariants(assault)

    def test_random_fire_scores_21_point_quanta(self):
        st = assault.reset(jax.random.PRNGKey(0))
        step = jax.jit(assault.step)
        key = jax.random.PRNGKey(1)
        rng = np.random.default_rng(2)
        total = 0.0
        for _ in range(400):
            key, k = jax.random.split(key)
            a = int(rng.choice([1, 1, 3, 4, 5, 6, 2]))
            st, _, r, _ = step(st, jnp.int32(a), k)
            total += float(r)
        assert total > 0.0
        assert total % 21.0 == 0.0  # ALE Assault scores in 21-point quanta

    def test_sustained_fire_overheats(self):
        st = assault.reset(jax.random.PRNGKey(0))
        step = jax.jit(assault.step)
        key = jax.random.PRNGKey(3)
        jammed = False
        for _ in range(30):
            key, k = jax.random.split(key)
            st, _, _, _ = step(st, jnp.int32(1), k)
            jammed = jammed or bool(st.jammed)
        assert jammed

    def test_venting_clears_jam(self):
        st = assault.reset(jax.random.PRNGKey(0))
        st = st._replace(heat=jnp.float32(1.0), jammed=jnp.bool_(True))
        step = jax.jit(assault.step)
        key = jax.random.PRNGKey(4)
        for _ in range(6):
            key, k = jax.random.split(key)
            st, _, _, _ = step(st, jnp.int32(2), k)
        assert not bool(st.jammed)
