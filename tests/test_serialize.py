"""Serialization round-trips (utils/serialize.py)."""

import numpy as np

from distributed_ba3c_tpu.utils.serialize import dumps, loads


def test_scalar_roundtrip():
    obj = [b"ident-3", 1.5, True, None, "x", 7]
    assert loads(dumps(obj)) == obj


def test_ndarray_roundtrip():
    arr = np.arange(84 * 84 * 4, dtype=np.uint8).reshape(84, 84, 4)
    out = loads(dumps(arr))
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(out, arr)


def test_mixed_payload_roundtrip():
    state = np.random.default_rng(0).integers(0, 255, (84, 84), np.uint8)
    ident, reward, is_over = b"simulator-0", -1.25, False
    i2, s2, r2, o2 = loads(dumps([ident, state, reward, is_over]))
    assert i2 == ident and r2 == reward and o2 == is_over
    np.testing.assert_array_equal(s2, state)


def test_noncontiguous_and_float_arrays():
    arr = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
    out = loads(dumps(arr))
    np.testing.assert_array_equal(out, arr)


def test_numpy_scalars():
    assert loads(dumps([np.float32(1.5), np.int64(3), np.bool_(True)])) == [
        1.5,
        3,
        True,
    ]


def test_uint8_wire_overhead_is_small():
    arr = np.zeros((84, 84, 4), np.uint8)
    assert len(dumps(arr)) < arr.nbytes + 64
