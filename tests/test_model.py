"""Shape/dtype tests for the BA3C convnet."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models import BA3CNet


def test_forward_shapes_and_dtypes():
    cfg = BA3CConfig(num_actions=6)
    model = BA3CNet(num_actions=cfg.num_actions)
    params = model.init(jax.random.key(0), jnp.zeros((1, *cfg.state_shape), jnp.uint8))
    state = jnp.zeros((8, *cfg.state_shape), jnp.uint8)
    out = model.apply(params, state)
    assert out.logits.shape == (8, 6)
    assert out.value.shape == (8,)
    assert out.logits.dtype == jnp.float32
    assert out.value.dtype == jnp.float32


def test_params_are_float32():
    model = BA3CNet(num_actions=4)
    params = model.init(jax.random.key(0), jnp.zeros((1, 84, 84, 4), jnp.uint8))
    for leaf in jax.tree_util.tree_leaves(params):
        assert leaf.dtype == jnp.float32


def test_uint8_and_prescaled_inputs_agree():
    model = BA3CNet(num_actions=4)
    key = jax.random.key(1)
    params = model.init(key, jnp.zeros((1, 84, 84, 4), jnp.uint8))
    state_u8 = jax.random.randint(key, (2, 84, 84, 4), 0, 256, jnp.int32).astype(jnp.uint8)
    out_u8 = model.apply(params, state_u8)
    out_f = model.apply(params, state_u8.astype(jnp.bfloat16) / 255.0)
    np.testing.assert_allclose(
        np.asarray(out_u8.logits), np.asarray(out_f.logits), atol=2e-2
    )
