"""V-trace under REAL actor/learner lag, end-to-end (BASELINE config #4).

The entire reason the V-trace component exists: with ``--publish_every 8``
the behavior policy serving the simulators is up to 8 updates stale, so the
experience is genuinely off-policy. The importance-corrected learner must
still reach near-optimum on the FakeEnv MDP, and must do at least as well
as the uncorrected sync A2C learner under the identical lag.

The overlap split (fused/overlap.py) re-creates the same staleness ON
DEVICE — rollout k+1 runs at the policy of update k-1 — and leans on the
same correction; the device-free equivalence gate lives here with the
other lag tests.
"""

import json
import os

import pytest

from distributed_ba3c_tpu.cli import main


def _run(trainer: str, logdir: str) -> dict:
    rc = main(
        [
            "--trainer", trainer,
            "--env", "fake",
            "--publish_every", "8",
            "--simulator_procs", "4",
            "--batch_size", "32",
            "--image_size", "16",
            "--fc_units", "16",
            "--steps_per_epoch", "80",
            "--max_epoch", "2",
            "--nr_eval", "4",
            "--logdir", logdir,
        ]
    )
    assert rc == 0
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    return stats[-1]


@pytest.mark.slow
def test_vtrace_learns_under_lag_and_matches_or_beats_sync(tmp_path):
    vt = _run("tpu_vtrace_ba3c", str(tmp_path / "vtrace"))
    if vt["eval_mean_score"] < 0.75:
        # stochastic 2-epoch learning run at a tight threshold: a marginal
        # seed occasionally lands just short (observed ~1 in 3 full-suite
        # runs). One retry bounds the flake without loosening the bar —
        # TWO consecutive failures indicate a real regression.
        vt = _run("tpu_vtrace_ba3c", str(tmp_path / "vtrace_retry"))
    # the importance-corrected learner must solve the MDP despite the stale
    # behavior policy (greedy optimum = 1.0)
    assert vt["eval_mean_score"] >= 0.75, vt

    sync = _run("tpu_sync_ba3c", str(tmp_path / "sync"))
    # and be no worse than the uncorrected learner under identical lag
    # (small tolerance: both may saturate the easy MDP)
    assert vt["eval_mean_score"] >= sync["eval_mean_score"] - 0.1, (vt, sync)


def test_overlap_lag1_matches_fused_learning_milestone():
    """Overlap-vs-fused equivalence under REAL lag (ISSUE 8, tier-1/CPU):
    same seeds, same budget, the lag-1 V-trace overlap run must reach the
    fused run's learning milestone on jax Pong.

    The milestone is the strong, reproducible optimization signature the
    fused run exhibits in this CPU-sized budget (40 updates, 16 envs x 3
    rollout, fc16): the policy COMMITS (mean entropy collapses from
    log(6) = 1.79 to < 0.5) while the value function tracks the realized
    returns (final-window value_loss in a fixed band of the fused run's).
    The real Pong >= 18 milestone is an on-chip criterion (BENCH/RESULTS);
    this is its device-free proxy, and the bit-exact lag-0 + one-update
    math parity gates live in tests/test_overlap.py.
    """
    import jax
    import numpy as np

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import (
        create_fused_state,
        make_fused_step,
    )
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    mesh = make_mesh()
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    N = 40

    def run(make_step):
        step = make_step()
        state = step.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )
        ent, vl = [], []
        for _ in range(N):
            state, m = step(state, cfg.entropy_beta)
            ent.append(float(m["entropy"]))
            vl.append(float(m["value_loss"]))
        return ent, vl

    f_ent, f_vl = run(
        lambda: make_fused_step(model, opt, cfg, mesh, pong, rollout_len=3)
    )
    o_ent, o_vl = run(
        lambda: make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3)
    )

    # the fused run must itself reach the milestone (else the test budget
    # regressed and the comparison below means nothing)
    assert f_ent[0] > 1.5 and f_ent[-1] < 0.5, (f_ent[0], f_ent[-1])
    # overlap, trained on one-update-stale V-trace-corrected experience,
    # reaches the same policy-commitment milestone
    assert o_ent[-1] < max(0.5, 2.0 * f_ent[-1]), (o_ent[-1], f_ent[-1])
    # and its value function lands in the fused run's band
    f_final = float(np.mean(f_vl[-10:]))
    o_final = float(np.mean(o_vl[-10:]))
    assert abs(o_final - f_final) <= max(0.5 * f_final, 0.1), (
        o_final, f_final,
    )
