"""V-trace under REAL actor/learner lag, end-to-end (BASELINE config #4).

The entire reason the V-trace component exists: with ``--publish_every 8``
the behavior policy serving the simulators is up to 8 updates stale, so the
experience is genuinely off-policy. The importance-corrected learner must
still reach near-optimum on the FakeEnv MDP, and must do at least as well
as the uncorrected sync A2C learner under the identical lag.
"""

import json
import os

import pytest

from distributed_ba3c_tpu.cli import main


def _run(trainer: str, logdir: str) -> dict:
    rc = main(
        [
            "--trainer", trainer,
            "--env", "fake",
            "--publish_every", "8",
            "--simulator_procs", "4",
            "--batch_size", "32",
            "--image_size", "16",
            "--fc_units", "16",
            "--steps_per_epoch", "80",
            "--max_epoch", "2",
            "--nr_eval", "4",
            "--logdir", logdir,
        ]
    )
    assert rc == 0
    stats = json.load(open(os.path.join(logdir, "stat.json")))
    return stats[-1]


@pytest.mark.slow
def test_vtrace_learns_under_lag_and_matches_or_beats_sync(tmp_path):
    vt = _run("tpu_vtrace_ba3c", str(tmp_path / "vtrace"))
    if vt["eval_mean_score"] < 0.75:
        # stochastic 2-epoch learning run at a tight threshold: a marginal
        # seed occasionally lands just short (observed ~1 in 3 full-suite
        # runs). One retry bounds the flake without loosening the bar —
        # TWO consecutive failures indicate a real regression.
        vt = _run("tpu_vtrace_ba3c", str(tmp_path / "vtrace_retry"))
    # the importance-corrected learner must solve the MDP despite the stale
    # behavior policy (greedy optimum = 1.0)
    assert vt["eval_mean_score"] >= 0.75, vt

    sync = _run("tpu_sync_ba3c", str(tmp_path / "sync"))
    # and be no worse than the uncorrected learner under identical lag
    # (small tolerance: both may saturate the easy MDP)
    assert vt["eval_mean_score"] >= sync["eval_mean_score"] - 0.1, (vt, sync)
