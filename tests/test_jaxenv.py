"""Pure-JAX envs: physics invariants, rendering, ALE-parity semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.envs.jaxenv import breakout, get_env, pong


def test_get_env():
    assert get_env("pong") is pong
    with pytest.raises(ValueError):
        get_env("doom")


class TestPong:
    def test_reset_and_render(self):
        st = pong.reset(jax.random.PRNGKey(0))
        obs = pong.render(st)
        assert obs.shape == (84, 84) and obs.dtype == jnp.uint8
        assert int(obs.max()) == 255  # ball/paddles lit

    def test_step_shapes_and_types(self):
        st = pong.reset(jax.random.PRNGKey(0))
        st, obs, r, d = jax.jit(pong.step)(st, jnp.int32(2), jax.random.PRNGKey(1))
        assert obs.shape == (84, 84) and obs.dtype == jnp.uint8
        assert r.dtype == jnp.float32 and d.dtype == jnp.bool_

    def test_ball_stays_in_court(self):
        step = jax.jit(pong.step)
        st = pong.reset(jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(42)
        for i in range(200):
            key, k1, k2 = jax.random.split(key, 3)
            a = jax.random.randint(k1, (), 0, pong.num_actions)
            st, _, _, _ = step(st, a, k2)
            assert 0.0 <= float(st.ball_xy[0]) <= 1.0
            assert 0.0 <= float(st.ball_xy[1]) <= 1.0

    def test_action_moves_paddle(self):
        st = pong.reset(jax.random.PRNGKey(0))
        step = jax.jit(pong.step)
        key = jax.random.PRNGKey(0)
        up, _, _, _ = step(st, jnp.int32(2), key)
        down, _, _, _ = step(st, jnp.int32(3), key)
        hold, _, _, _ = step(st, jnp.int32(0), key)
        assert float(up.agent_y) < float(hold.agent_y) < float(down.agent_y)

    def test_match_to_21_terminates_with_correct_return(self):
        """A still agent against the tracking opponent loses points; the
        episode must end when a side reaches 21 and total reward == the
        score differential."""
        step = jax.jit(pong.step)
        st = pong.reset(jax.random.PRNGKey(3))
        key = jax.random.PRNGKey(7)
        total, done = 0.0, False
        for i in range(6000):
            key, k = jax.random.split(key)
            st, _, r, d = step(st, jnp.int32(0), k)
            total += float(r)
            if bool(d):
                done = True
                break
        assert done, "match never terminated"
        assert total <= -21 + 20  # still agent should lose decisively
        # auto-restart: scores cleared
        assert int(st.agent_score) == 0 and int(st.opp_score) == 0

    def test_frameskip_constant(self):
        assert pong.FRAME_SKIP == 4  # ALE parity (SURVEY.md §2.9)


class TestBreakout:
    def test_serve_rides_paddle_until_fire(self):
        step = jax.jit(breakout.step)
        st = breakout.reset(jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(0)
        st2, _, _, _ = step(st, jnp.int32(2), key)  # move right, no fire
        assert not bool(st2.in_play)
        assert abs(float(st2.ball_xy[0]) - float(st2.paddle_x)) < 1e-5
        st3, _, _, _ = step(st2, jnp.int32(1), key)  # fire
        assert bool(st3.in_play)

    def test_bricks_and_reward(self):
        """Play scripted: fire then track the ball with the paddle; bricks
        must break and reward must match the row-points of broken bricks."""
        step = jax.jit(breakout.step)
        st = breakout.reset(jax.random.PRNGKey(1))
        key = jax.random.PRNGKey(2)
        st, _, _, _ = step(st, jnp.int32(1), key)
        total = 0.0
        for i in range(400):
            key, k = jax.random.split(key)
            # follow the ball
            a = jnp.where(
                st.ball_xy[0] > st.paddle_x + 0.02,
                2,
                jnp.where(st.ball_xy[0] < st.paddle_x - 0.02, 3, 1),
            ).astype(jnp.int32)
            st, _, r, d = step(st, a, k)
            total += float(r)
        broken = 108 - int(st.bricks.sum())
        assert broken > 0 and total > 0
        assert int(st.lives) >= 1  # tracking paddle keeps the ball alive mostly

    def test_lives_deplete_and_done(self):
        step = jax.jit(breakout.step)
        st = breakout.reset(jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(5)
        done_seen = False
        for i in range(3000):
            key, k = jax.random.split(key)
            # fire to launch, then hold still: ball eventually drains 5 lives
            a = jnp.int32(1)
            st, _, _, d = step(st, a, k)
            if bool(d):
                done_seen = True
                break
        assert done_seen, "episode never ended"
        assert int(st.lives) == breakout.LIVES  # auto-restarted
