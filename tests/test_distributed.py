"""Multi-host helpers, profiling utils, actor failure detection."""

import time

import jax
import numpy as np
import pytest

from distributed_ba3c_tpu.parallel.distributed import (
    initialize_from_flags,
    is_chief,
    local_batch_slice,
    make_global_mesh,
)
from distributed_ba3c_tpu.utils.profiling import timed_operation


def test_initialize_single_host_noop():
    assert initialize_from_flags("", 0) is False
    assert initialize_from_flags("localhost:5000", 0) is False


def test_global_mesh_covers_all_devices():
    mesh = make_global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("data", "model")


def test_chief_and_batch_slice_single_process():
    assert is_chief()
    assert local_batch_slice(64) == slice(0, 64)


def test_timed_operation_runs():
    with timed_operation("noop"):
        time.sleep(0.01)


def _prune_master(tmp_path):
    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster

    class _P:
        def put_task(self, s, cb, **kw):
            pass

    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/c2s",
        f"ipc://{tmp_path}/s2c",
        _P(),
    )
    m.actor_timeout = 0.1
    return m


def test_master_prunes_dead_actors(tmp_path):
    m = _prune_master(tmp_path)
    try:
        c = m.clients[b"sim-0"]
        c.last_seen = time.monotonic() - 10.0
        m._last_prune = 0.0
        m._prune_dead_actors()
        assert b"sim-0" not in m.clients
        # fresh client survives
        c2 = m.clients[b"sim-1"]
        c2.last_seen = time.monotonic()
        m._last_prune = 0.0
        m._prune_dead_actors()
        assert b"sim-1" in m.clients
    finally:
        m.close()


def test_prune_immune_to_wall_clock_jump(tmp_path, monkeypatch):
    """Regression for the ba3clint-A4 finding: heartbeat arithmetic used
    ``time.time()``, so an NTP step / suspend-resume would mass-expire every
    live actor at once. ``last_seen`` must be monotonic — a forward wall
    clock jump of a million seconds must not prune a fresh client."""
    m = _prune_master(tmp_path)
    try:
        m.clients[b"sim-0"]  # fresh heartbeat at creation
        m._last_prune = 0.0
        real_time = time.time
        monkeypatch.setattr(time, "time", lambda: real_time() + 1e6)
        m._prune_dead_actors()
        assert b"sim-0" in m.clients, (
            "wall-clock jump expired a live actor — heartbeats must use "
            "time.monotonic()"
        )
    finally:
        m.close()
