"""The distributed trace plane (telemetry/tracing.py, docs/observability.md).

Span-buffer units (sharded append, bounded drop, sampling determinism),
the wire context codec (round trip + unknown-version tolerance + junk
posture), clock-offset handshake/alignment, the live block-wire e2e (one
sampled block's trace must be COMPLETE and CAUSALLY ORDERED across
master/predictor/learner spans), the 2-host pod e2e (cross-process spans
land clock-aligned on the learner's timeline), the /trace and filtered
/flight endpoints, and the trace_dump.py Chrome-trace-event smoke the CI
``tracing`` job gates on.
"""

import json
import queue
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.utils.serialize import pack_block

REPO_ROOT = __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
)


@pytest.fixture(autouse=True)
def _fresh_trace_plane():
    """Every test starts with a clean tracer and sampling DISARMED (the
    process default); nothing leaks into neighboring test files."""
    tracing.reset()
    tracing.set_sampling(0)
    yield
    tracing.reset()
    tracing.set_sampling(0)


# -- span buffer units -----------------------------------------------------


def test_span_buffer_sharded_append_thread_exact():
    buf = tracing.SpanBuffer(capacity=10_000)
    n_threads, per = 8, 500

    def writer(k):
        for i in range(per):
            buf.add((1, k * per + i, 0, "hop", "r", i, 1, None))

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(buf) == n_threads * per
    assert buf.dropped == 0
    spans = buf.snapshot()
    assert len(spans) == n_threads * per
    assert {s["span_id"] for s in spans} == set(range(n_threads * per))


def test_span_buffer_bounded_drop_oldest():
    buf = tracing.SpanBuffer(capacity=16)
    for i in range(50):
        buf.add((1, i, 0, "hop", "r", i, 1, None))
    assert len(buf) == 16
    assert buf.dropped == 34
    # drop-OLDEST: the newest spans survive
    assert {s["span_id"] for s in buf.snapshot()} == set(range(34, 50))


def test_sampling_deterministic():
    tracing.set_sampling(8)
    picks = [s for s in range(64) if tracing.sampled(s)]
    assert picks == [0, 8, 16, 24, 32, 40, 48, 56]
    tracing.set_sampling(0)
    assert not any(tracing.sampled(s) for s in range(64))
    assert not tracing.enabled()
    # the explicit-n form used by senders
    assert tracing.sampled(4, 2) and not tracing.sampled(5, 2)


def test_make_id_deterministic_and_63bit():
    a = tracing.make_id(b"cppsim-0*block", 128)
    assert a == tracing.make_id(b"cppsim-0*block", 128)
    assert a != tracing.make_id(b"cppsim-0*block", 129)
    assert 0 < a < (1 << 63)


# -- context codec ---------------------------------------------------------


def test_context_codec_roundtrip():
    ctx = tracing.encode_context(123, 456, send_us=789, origin_dur_us=42)
    dec = tracing.decode_context(ctx)
    assert (dec.trace_id, dec.span_id, dec.send_us, dec.origin_dur_us) == (
        123, 456, 789, 42,
    )
    assert dec.version == tracing.CTX_VERSION


def test_context_codec_unknown_newer_version_tolerated():
    # a future sender appends fields; this receiver reads its prefix
    dec = tracing.decode_context([99, 5, 6, 777, 10, "future-field", {"x": 1}])
    assert dec is not None
    assert (dec.version, dec.trace_id, dec.span_id, dec.send_us,
            dec.origin_dur_us) == (99, 5, 6, 777, 10)


@pytest.mark.parametrize("junk", [
    None, b"junk", "junk", 42, {}, [], [1], [1, 2, 3],
    [0, 1, 2, 3],          # version < 1
    ["x", 1, 2, 3],        # non-int version
    [1, "a", "b", "c"],    # non-int fields
])
def test_context_codec_junk_decodes_to_none(junk):
    assert tracing.decode_context(junk) is None


def test_context_survives_msgpack_header():
    from distributed_ba3c_tpu.utils.serialize import unpack_block

    meta = [b"id", 3, 2, {}, tracing.encode_context(9, 8, 7, 6)]
    frames = pack_block(meta, [np.zeros(2, np.float32)])
    meta2, _ = unpack_block([bytes(f) for f in frames])
    dec = tracing.decode_context(meta2[4])
    assert dec is not None and dec.trace_id == 9 and dec.origin_dur_us == 6


# -- clock alignment -------------------------------------------------------


def test_clock_offset_min_filter_and_align():
    t = tracing.Tracer()
    # first observation includes 5 ms transit; a later, luckier one 1 ms
    assert t.observe_remote_clock("peer", 1_000, local_us=6_000) == 5_000
    assert t.observe_remote_clock("peer", 10_000, local_us=11_000) == 1_000
    # min-filter: a slow observation never degrades the estimate
    assert t.observe_remote_clock("peer", 20_000, local_us=29_000) == 1_000
    assert t.clock_offset("peer") == 1_000
    assert t.align("peer", 2_000) == 3_000
    # unknown peer: identity (no handshake yet)
    assert t.align("stranger", 2_000) == 2_000


def test_receive_context_synthesizes_origin_and_wire_spans():
    tracing.set_sampling(1)
    skew_us = 5_000_000  # remote clock 5 s behind ours
    send_remote = tracing.now_us() - skew_us
    ctx = tracing.TraceContext(11, 22, send_remote, origin_dur_us=300)
    out = tracing.receive_context(ctx, "host-x", "master")
    assert out is not None
    trace_id, parent = out
    assert trace_id == 11
    spans = {s["name"]: s for s in tracing.tracer().spans.snapshot()}
    assert set(spans) == {"env_step", "wire"}
    # the env_step span landed on OUR timeline despite the 5 s skew:
    # aligned send ~= our receive time, so ts is recent, not 5 s ago
    assert tracing.now_us() - spans["env_step"]["ts_us"] < 2_000_000
    assert spans["env_step"]["dur_us"] == 300
    assert spans["wire"]["parent_id"] == spans["env_step"]["span_id"]
    assert spans["wire"]["span_id"] == parent
    # per-hop histograms folded into the role registry
    assert "hop_wire_s" in telemetry.registry("master").names()


def test_trace_ref_hop_chains_parents():
    ref = tracing.TraceRef(7, 100)
    r2 = ref.hop("a", "learner")
    r3 = r2.hop("b", "learner")
    spans = {s["name"]: s for s in tracing.tracer().spans.snapshot()}
    assert spans["a"]["parent_id"] == 100
    assert spans["b"]["parent_id"] == spans["a"]["span_id"]
    assert r3.trace_id == 7


def test_span_context_manager_and_flight_correlation():
    with tracing.trace_scope(4242):
        with tracing.span(4242, "collate", "learner") as s:
            pass
        telemetry.record("trace_test_event", foo=1)
    spans = tracing.tracer().spans.snapshot()
    assert spans and spans[-1]["span_id"] == s.span_id
    ev = [e for e in telemetry.flight_recorder().snapshot()
          if e["kind"] == "trace_test_event"][-1]
    assert ev["trace_id"] == 4242
    # outside the scope, events are unstamped
    telemetry.record("trace_test_event2", foo=2)
    ev2 = [e for e in telemetry.flight_recorder().snapshot()
           if e["kind"] == "trace_test_event2"][-1]
    assert "trace_id" not in ev2


# -- block-wire e2e: complete causal chain ---------------------------------


class _WireFrame:
    def __init__(self, buf):
        self.buffer = bytes(buf)


class _TraceAwarePredictor:
    """Duck-typed predictor that honors the trace kwarg like the real
    scheduler: dispatch/fetch attribution, then the callback."""

    num_actions = 4

    def put_block_task(self, states, cb, shed_callback=None, trace=None):
        k = len(states)
        if trace is not None:
            trace.hop("predict_dispatch", "predictor").hop(
                "predict_fetch", "predictor"
            )
        cb(np.zeros(k, np.int32), np.zeros(k, np.float32),
           np.zeros(k, np.float32))
        return True

    def put_task(self, state, cb, shed_callback=None, trace=None):
        if trace is not None:
            trace.hop("predict_dispatch", "predictor").hop(
                "predict_fetch", "predictor"
            )
        cb(0, 0.0, 0.0)
        return True


def _send_block_steps(master, ident, n_steps, b=2, h=8, w=8, hist=2):
    obs = np.zeros((hist, b, h, w), np.uint8)
    rew, dn = np.zeros(b, np.float32), np.zeros(b, np.uint8)
    for step in range(n_steps):
        meta = [ident, step, b]
        if tracing.enabled() and tracing.sampled(step):
            meta.append({})  # deltas slot pinned so positions never shift
            meta.append(tracing.encode_context(
                tracing.make_id(ident, step),
                tracing.make_id(ident, step, "origin"),
                origin_dur_us=150,
            ))
        master._on_block_frames(
            [_WireFrame(f) for f in pack_block(meta, [obs, rew, dn])]
        )


CHAIN = ["env_step", "wire", "master_ingest", "predict", "unroll_flush",
         "queue_wait", "collate", "ingest", "learner_step"]


def test_block_wire_trace_complete_and_causal(tmp_path):
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
    from distributed_ba3c_tpu.data.dataflow import RolloutFeed

    tracing.set_sampling(4)
    m = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _TraceAwarePredictor(),
        unroll_len=3, train_queue=queue.Queue(maxsize=64),
    )
    feed = RolloutFeed(m.queue, batch_size=2)
    try:
        _send_block_steps(m, b"x*block", 8)
        feed.start()
        batch = feed.next_batch(timeout=10)
        ref = batch.pop("_trace")
        # the learner side of the chain (what Trainer.run_step does)
        ref.hop("ingest", "learner").hop("learner_step", "learner")
        spans = [s for s in tracing.tracer().spans.snapshot()
                 if s["trace_id"] == ref.trace_id]
        by_name = {s["name"]: s for s in spans}
        # COMPLETE: every named hop present, plus the predictor branch
        for name in CHAIN + ["predict_dispatch", "predict_fetch"]:
            assert name in by_name, (name, sorted(by_name))
        # CAUSAL: the main chain is a strict parent chain...
        for prev, cur in zip(CHAIN, CHAIN[1:]):
            assert by_name[cur]["parent_id"] == by_name[prev]["span_id"], (
                prev, cur,
            )
        # ...the predictor branch parents onto the master_ingest span
        # (the backpressure-attribution hop — receive->dispatch time is
        # a master hop, never predictor latency)...
        assert by_name["predict_dispatch"]["parent_id"] == (
            by_name["master_ingest"]["span_id"]
        )
        # ...and start times are monotone along the chain
        ts = [by_name[n]["ts_us"] for n in CHAIN]
        assert ts == sorted(ts)
        # roles attribute each hop to its plane
        assert by_name["predict_fetch"]["role"] == "predictor"
        assert by_name["unroll_flush"]["role"] == "master"
        assert by_name["learner_step"]["role"] == "learner"
    finally:
        feed.stop()
        m.close()
        feed.join(timeout=2)


def test_block_wire_untraced_steps_carry_no_context(tmp_path):
    """Sampling off: headers stay at their pre-tracing length and no spans
    are buffered — the overhead gate's off arm runs the old wire."""
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster

    m = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _TraceAwarePredictor(),
        unroll_len=3, train_queue=queue.Queue(maxsize=64),
    )
    try:
        _send_block_steps(m, b"x*block", 8)
        seg = m.queue.get_nowait()
        assert "_trace" not in seg
        assert len(tracing.tracer().spans.snapshot()) == 0
    finally:
        m.close()


def test_ba3c_nstep_flush_carries_trace_rider(tmp_path):
    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.data.dataflow import claim_trace

    tracing.set_sampling(4)
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _TraceAwarePredictor(),
        gamma=0.5, local_time_max=3, train_queue=queue.Queue(maxsize=256),
    )
    try:
        _send_block_steps(m, b"x*block", 6)
        refs = []
        while True:
            try:
                item = m.queue.get_nowait()
            except queue.Empty:
                break
            ref = claim_trace(item)
            assert len(item) == 3  # the rider came OFF the datapoint
            if ref is not None:
                refs.append(ref)
        assert len(refs) == 1  # one trace per sampled block, claimed once
        names = {s["name"] for s in tracing.tracer().spans.snapshot()
                 if s["trace_id"] == refs[0].trace_id}
        assert "nstep_flush" in names and "env_step" in names
    finally:
        m.close()


def test_ba3c_per_env_trace_continues_past_wire(tmp_path):
    """The per-env wire's BA3C path must chain like the V-trace path:
    predict + nstep_flush hops and a rider on the emitted datapoint —
    not a 2-span stub that dies at the wire."""
    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.data.dataflow import claim_trace

    tracing.set_sampling(1)
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/a", f"ipc://{tmp_path}/b", _TraceAwarePredictor(),
        gamma=0.5, local_time_max=3, train_queue=queue.Queue(maxsize=256),
    )
    try:
        ident = b"simulator-0"
        state = np.zeros((8, 8, 4), np.uint8)
        trace_id = None
        for step in range(6):
            # what the receive loop does per sampled message: decode the
            # context element, park the ref
            ctx = tracing.encode_context(
                tracing.make_id(ident, step),
                tracing.make_id(ident, step, "o"), origin_dur_us=50,
            )
            client = m.clients[ident]
            client.pending_trace = m._recv_trace(ident, ctx)
            if trace_id is None:
                trace_id = client.pending_trace.trace_id
            m._on_message(ident, state, reward=1.0, is_over=False)
        refs = []
        while True:
            try:
                item = m.queue.get_nowait()
            except queue.Empty:
                break
            ref = claim_trace(item)
            assert len(item) == 3
            if ref is not None:
                refs.append(ref)
        assert refs, "no rider reached the train queue"
        names = {s["name"] for s in tracing.tracer().spans.snapshot()
                 if s["trace_id"] == refs[0].trace_id}
        assert {"env_step", "wire", "predict", "nstep_flush"} <= names, names
    finally:
        m.close()


# -- pod e2e: two hosts, one aligned timeline ------------------------------


class _StubPodStep:
    """Device-free pod learner step: real consume() path, no mesh."""

    state_sharding = None
    block_sharding = None

    def __call__(self, state, block, beta, lr):
        return state, {"value_lag_mae": 0.0}


def test_pod_two_host_trace_clock_aligned(tmp_path):
    """Two shipping hosts + the real zmq experience channel + the real
    gate/learner consume path: both hosts' traces must land complete on
    the LEARNER'S timeline, with a measured clock offset per host."""
    import zmq

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.pod.ingest import PodIngest
    from distributed_ba3c_tpu.pod.learner import PodLearner
    from distributed_ba3c_tpu.pod.wire import PodEndpoints, pack_experience, pod_role

    tracing.set_sampling(1)
    endpoints = PodEndpoints(
        params_pub=f"ipc://{tmp_path}/pub",
        params_fetch=f"ipc://{tmp_path}/fetch",
        experience=f"ipc://{tmp_path}/exp",
    )
    ingest = PodIngest(endpoints, depth=8)
    learner = PodLearner(
        _StubPodStep(), {"w": np.zeros(2, np.float32)}, BA3CConfig(),
        max_staleness=None,
    )
    ctx = zmq.Context()
    try:
        ingest.start()
        T, B = 2, 2
        batch = {
            "state": np.zeros((T, B, 8, 8, 4), np.uint8),
            "action": np.zeros((T, B), np.int32),
            "reward": np.zeros((T, B), np.float32),
            "done": np.zeros((T, B), np.float32),
            "behavior_log_probs": np.zeros((T, B), np.float32),
            "behavior_values": np.zeros((T, B), np.float32),
            "bootstrap_state": np.zeros((B, 8, 8, 4), np.uint8),
        }
        for host in (0, 1):
            # each "host" ships one traced block, exactly what
            # ExperienceShipper does after host_collate: context carries
            # the host's send stamp (the clock handshake)
            ref = tracing.TraceRef(
                tracing.make_id("pod", host), tracing.make_id("pod", host, "o")
            )
            frames = pack_experience(
                host, 0, batch, {"env_steps_total": 1.0},
                trace=tracing.encode_context(ref.trace_id, ref.parent_id),
            )
            push = ctx.socket(zmq.PUSH)
            push.connect(endpoints.experience)
            push.send_multipart(frames)
            push.close(1000)
        got = []
        deadline = time.monotonic() + 10
        while len(got) < 2 and time.monotonic() < deadline:
            sb = ingest.next_batch(timeout=1.0)
            if sb is not None:
                got.append(sb)
        assert len(got) == 2, "both hosts' blocks must arrive"
        for sb in got:
            assert sb.trace is not None
            out = learner.consume(sb)
            assert out is not None  # gated, staged, stepped
        doc = tracing.tracer().document()
        # a measured offset per host peer (the handshake)
        assert pod_role(0) in doc["clock_offsets_us"]
        assert pod_role(1) in doc["clock_offsets_us"]
        for host in (0, 1):
            spans = [s for s in doc["spans"]
                     if s["trace_id"] == tracing.make_id("pod", host)]
            names = [s["name"] for s in spans]
            for need in ("pod_wire", "staleness_gate", "pod_ingest_stage",
                         "pod_learner_step"):
                assert need in names, (host, names)
            # clock-aligned: every span sits on the learner's recent
            # monotonic timeline and starts are causally ordered
            ts = [s["ts_us"] for s in spans]
            assert ts == sorted(ts)
            assert all(tracing.now_us() - t < 60_000_000 for t in ts)
    finally:
        ingest.close()
        ctx.term()


def test_pod_params_publish_trace_reaches_cache(tmp_path):
    """The params leg: a sampled publish's context survives the params
    codec and produces the cache-side apply span + learner clock offset."""
    from distributed_ba3c_tpu.pod.cache import StaleParamsCache
    from distributed_ba3c_tpu.pod.wire import PodEndpoints, pack_params

    tracing.set_sampling(1)
    endpoints = PodEndpoints(
        params_pub=f"ipc://{tmp_path}/pub2",
        params_fetch=f"ipc://{tmp_path}/fetch2",
        experience=f"ipc://{tmp_path}/exp2",
    )
    cache = StaleParamsCache(endpoints, host=0)
    try:
        payload = pack_params(
            3, {"w": np.ones(2, np.float32)}, step=7, epoch=5,
            trace=tracing.encode_context(777, 888),
        )
        assert cache._apply_safe(payload)
        assert cache.version == 3
        spans = [s for s in tracing.tracer().spans.snapshot()
                 if s["trace_id"] == 777]
        names = {s["name"] for s in spans}
        assert "params_wire" in names and "params_apply" in names
        assert tracing.tracer().clock_offset("pod-learner") is not None
    finally:
        cache.close()


def test_epoch_mismatch_rejection_ends_trace_visibly():
    """The OTHER rejection path keeps the same contract: a block from a
    foreign publisher lifetime ends its trace with a verdict span."""
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.pod.ingest import StampedBatch
    from distributed_ba3c_tpu.pod.learner import PodLearner

    class _Pub:
        epoch = 7

        def publish(self, *a, **k):
            pass

    tracing.set_sampling(1)
    learner = PodLearner(
        _StubPodStep(), {"w": np.zeros(2, np.float32)}, BA3CConfig(),
    )
    # attach post-init (the init-time version-0 publish needs a real
    # TrainState; the epoch check only reads publisher.epoch)
    learner.publisher = _Pub()
    ref = tracing.TraceRef(66, 1)
    out = learner.consume(
        StampedBatch(host=0, version=0, batch={}, epoch=99, trace=ref)
    )
    assert out is None
    spans = [s for s in tracing.tracer().spans.snapshot()
             if s["trace_id"] == 66]
    assert [s["name"] for s in spans] == ["epoch_gate"]
    assert spans[0]["tags"] == {"rejected": True, "reason": "epoch_mismatch"}


def test_staleness_gate_rejection_ends_trace_visibly():
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.pod.ingest import StampedBatch
    from distributed_ba3c_tpu.pod.learner import PodLearner

    tracing.set_sampling(1)
    learner = PodLearner(
        _StubPodStep(), {"w": np.zeros(2, np.float32)}, BA3CConfig(),
        max_staleness=1,
    )
    learner.version = 10
    ref = tracing.TraceRef(55, 1)
    out = learner.consume(
        StampedBatch(host=0, version=2, batch={}, epoch=0, trace=ref)
    )
    assert out is None  # rejected — and the trace says so
    spans = [s for s in tracing.tracer().spans.snapshot()
             if s["trace_id"] == 55]
    assert [s["name"] for s in spans] == ["staleness_gate"]
    assert spans[0]["tags"]["rejected"] is True


# -- endpoints + dump smoke ------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def test_trace_endpoint_and_flight_filters():
    tracing.set_sampling(1)
    ref = tracing.TraceRef(99, 1)
    ref.hop("wire", "master")
    t_mid = time.monotonic()
    telemetry.record("prune", ident="x")
    telemetry.record("queue_wait", wait_s=0.1)
    srv = telemetry.TelemetryServer(port=0, host="127.0.0.1")
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        doc = _get(f"{base}/trace")
        assert doc["sample_n"] == 1
        assert any(s["trace_id"] == 99 for s in doc["spans"])
        assert {"anchor_monotonic_us", "anchor_wall",
                "clock_offsets_us"} <= set(doc)
        # the filtered flight endpoint: kind alone, since alone, both
        kinds = {e["kind"] for e in _get(f"{base}/flight?kind=prune")}
        assert kinds == {"prune"}
        since = _get(f"{base}/flight?since={t_mid}")
        assert {e["kind"] for e in since} == {"prune", "queue_wait"}
        both = _get(f"{base}/flight?since={t_mid}&kind=queue_wait")
        assert [e["kind"] for e in both] == ["queue_wait"]
        # junk params must not error the scrape
        assert isinstance(_get(f"{base}/flight?since=junk"), list)
        # the unfiltered ring still works
        assert len(_get(f"{base}/flight")) >= 2
    finally:
        srv.stop()
        srv.join(timeout=2)
        srv.close()


def test_trace_dump_merges_and_validates(tmp_path):
    """Two process documents (one with a wall-anchor skew) merge onto one
    timeline; the emitted JSON passes the CI schema validation."""
    tracing.set_sampling(1)
    ref = tracing.TraceRef(1234, 1)
    ref.hop("wire", "master").hop("predict", "master")
    doc_a = tracing.tracer().document()
    # a second "process": same spans, anchors shifted as if its monotonic
    # clock started 1000 s later but wall time agrees
    doc_b = json.loads(json.dumps(doc_a))
    # SAME os pid on purpose (two containers both pid 1): the merge must
    # keep the documents' tracks and alignment entries distinct
    shift = 1_000_000_000
    for s in doc_b["spans"]:
        s["ts_us"] += shift
    doc_b["anchor_monotonic_us"] += shift
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(doc_a))
    pb.write_text(json.dumps(doc_b))
    out = tmp_path / "chrome.json"
    r = subprocess.run(
        [sys.executable, "scripts/trace_dump.py", str(pa), str(pb),
         "-o", str(out), "--validate"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    # per-document alignment survives even with colliding OS pids
    assert set(doc["metadata"]["alignment"]) == {"doc0", "doc1"}
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(events) == 4  # 2 spans x 2 processes
    assert {e["pid"] for e in events} == {0, 1}  # distinct tracks
    # the two processes' copies of the same span landed within ~1 s of
    # each other on the merged timeline (wall-anchor alignment), not
    # 1000 s apart (raw monotonic)
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e["ts"])
    for name, ts in by_name.items():
        assert abs(ts[0] - ts[1]) < 2_000_000, (name, ts)
    # embedded-trace form (plane_bench --trace JSONs) loads too
    bench_like = tmp_path / "bench.json"
    bench_like.write_text(json.dumps({"metric": "x", "trace": doc_a}))
    r2 = subprocess.run(
        [sys.executable, "scripts/trace_dump.py", str(bench_like),
         "--validate"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert r2.returncode == 0, r2.stderr


def test_trace_disabled_paths_are_inert(tmp_path):
    """BA3C_TELEMETRY=0 semantics: with telemetry disabled, tracing
    reports disabled, the RECEIVE side refuses remotely-stamped
    contexts, and the span sink drops writes — the kill switch covers
    the whole plane, not just the sender."""
    tracing.set_sampling(16)
    telemetry.set_enabled(False)
    try:
        assert not tracing.enabled()
        # a remote sender's sampled context must not fill this process's
        # buffer when its telemetry is killed
        ctx = tracing.TraceContext(1, 2, tracing.now_us(), 100)
        assert tracing.receive_context(ctx, "peer", "master") is None
        tracing.TraceRef(1, 2).hop("wire", "master")
        assert len(tracing.tracer().spans.snapshot()) == 0
    finally:
        telemetry.set_enabled(True)
    assert tracing.enabled()
