"""tools/ba3cflow: per-rule fixtures, historical replays, CLI contract.

Mirrors the ba3clint test structure: every flow rule must (a) fire on its
``f*_flagged.py`` fixture and (b) stay quiet on its ``f*_clean.py``
fixture — the clean fixtures encode the concurrency idioms the real
codebase uses (stop-event loops, snapshot-then-join, timed queue ops), so
a rule regression that would spam the repo fails here first. The replay
fixtures pin the analyzer to two bugs that actually shipped in this repo:
the ``logger.exception`` latent AttributeError (F6) and the admission
decrement race (F1). The CLI tests pin the exit-status contract CI gates
on, and the SARIF test pins the schema the upload step consumes.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.ba3clint.engine import stale_suppressions
from tools.ba3cflow import all_rules
from tools.ba3cflow.engine import build_context, filter_suppressed, run_rules

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures", "flow")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULE_IDS = ["F1", "F2", "F3", "F4", "F5", "F6"]


def _analyze(*names, suppress=True):
    paths = [os.path.join(FIXTURES, n) for n in names]
    ctx = build_context(paths, root=REPO_ROOT)
    raw = run_rules(ctx, all_rules())
    return (filter_suppressed(ctx, raw) if suppress else raw), ctx


def _findings(name, rule_id=None, suppress=True):
    out, _ = _analyze(name, suppress=suppress)
    if rule_id is not None:
        out = [f for f in out if f.rule == rule_id]
    return out


def _cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.ba3cflow", *args],
        cwd=cwd, capture_output=True, text=True,
    )


def _fx(name):
    return os.path.join("tests", "lint_fixtures", "flow", name)


# -- rule registry ----------------------------------------------------------


def test_rule_registry_complete():
    assert [r.id for r in all_rules()] == RULE_IDS
    for r in all_rules():
        assert r.id and r.name and r.summary and r.__doc__


# -- fixture pairs ----------------------------------------------------------


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_flagged_fixture_fires(rule_id):
    name = f"{rule_id.lower()}_flagged.py"
    hits = _findings(name, rule_id)
    assert hits, f"{rule_id} produced no findings on {name}"


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_flagged_fixture_fires_only_its_own_rule(rule_id):
    """Cross-rule noise on a flagged fixture means a rule is over-broad."""
    name = f"{rule_id.lower()}_flagged.py"
    other = [f for f in _findings(name) if f.rule != rule_id]
    assert not other, other


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_clean_under_every_rule(rule_id):
    hits = _findings(f"{rule_id.lower()}_clean.py")
    assert not hits, hits


def test_expected_flag_counts():
    """Pin exact counts so rules don't silently widen or narrow: F1 sees
    the transitive sleep, the untimed put, and the unguarded write; F4
    sees the join-under-lock and the join-on-self."""
    assert len(_findings("f1_flagged.py", "F1")) == 3
    assert len(_findings("f2_flagged.py", "F2")) == 1
    assert len(_findings("f3_flagged.py", "F3")) == 1
    assert len(_findings("f4_flagged.py", "F4")) == 2
    assert len(_findings("f5_flagged.py", "F5")) == 1
    assert len(_findings("f6_flagged.py", "F6")) == 1


# -- historical replays -----------------------------------------------------


def test_replay_admission_decrement_race_is_an_f1():
    """PR 16's bug class: the shed path decremented the admission counter
    without the lock the admit path guards it with."""
    hits = _findings("replay_f1_try_admit.py", "F1")
    assert len(hits) == 1
    assert "on_shed" not in hits[0].message  # reported AT the bare write
    assert "_admitting" in hits[0].message
    assert "try_admit" in hits[0].message  # ...naming the guarded twin


def test_replay_logger_exception_is_an_f6():
    """PR 7's bug class: the except handler called a logger function the
    project logger module never defined."""
    out, _ = _analyze(
        os.path.join("replay_f6", "caller.py"),
        os.path.join("replay_f6", "minilog.py"),
    )
    assert [f.rule for f in out] == ["F6"]
    assert "exception" in out[0].message
    assert out[0].path.endswith("caller.py")


# -- suppressions -----------------------------------------------------------


def test_suppressions_silence_real_findings_both_forms():
    raw = _findings("suppressed.py", "F1", suppress=False)
    assert len(raw) == 2, raw  # trailing AND standalone form both land
    assert _findings("suppressed.py") == []


def test_docstring_mention_of_disable_is_not_a_suppression():
    """Only real comment tokens suppress — documentation text that quotes
    the syntax must neither mask findings nor read as stale."""
    src = '"""uses # ba3cflow: disable=F1 like this"""\nx = 1\n'
    from tools.ba3clint.engine import suppressions
    assert suppressions(src, tool="ba3cflow") == {}
    assert stale_suppressions(src, "d.py", [], "ba3cflow") == []


def test_check_suppressions_flags_stale_comment():
    _, ctx = _analyze("stale_suppressed.py", suppress=False)
    (path, mod), = ctx.project.by_path.items()
    out = stale_suppressions(mod.source, path, [], "ba3cflow")
    assert [f.rule for f in out] == ["S001"]
    assert "F2" in out[0].message


# -- whole-repo gate --------------------------------------------------------


def test_repo_is_flow_clean():
    """The acceptance bar: the analyzer runs over the real codebase and
    exits clean (true positives fixed, false positives suppressed with
    justifications)."""
    ctx = build_context(
        [os.path.join(REPO_ROOT, "distributed_ba3c_tpu"),
         os.path.join(REPO_ROOT, "tools")],
        root=REPO_ROOT,
    )
    assert not ctx.project.broken
    findings = filter_suppressed(ctx, run_rules(ctx, all_rules()))
    assert findings == [], findings


# -- engine behavior --------------------------------------------------------


def test_syntax_error_becomes_e001_not_a_crash(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    ctx = build_context([str(bad)], root=str(tmp_path))
    out = run_rules(ctx, all_rules())
    assert [f.rule for f in out] == ["E001"]


# -- CLI contract -----------------------------------------------------------


def test_cli_exit_one_on_findings_and_zero_on_clean():
    assert _cli(_fx("f5_flagged.py")).returncode == 1
    assert _cli(_fx("f5_clean.py")).returncode == 0


def test_cli_select_unknown_rule_is_usage_error():
    r = _cli("--select", "F99", _fx("f5_clean.py"))
    assert r.returncode == 2
    assert "F99" in r.stderr


def test_cli_select_narrows_rules():
    r = _cli("--select", "F2", _fx("f5_flagged.py"))
    assert r.returncode == 0, r.stdout


def test_cli_json_output_parses():
    r = _cli("--json", _fx("f3_flagged.py"))
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload and payload[0]["rule"] == "F3"
    assert payload[0]["line"] > 0


def test_cli_list_rules():
    r = _cli("--list-rules")
    assert r.returncode == 0
    for rid in RULE_IDS:
        assert rid in r.stdout


def test_cli_check_suppressions_exits_one_on_stale():
    r = _cli("--check-suppressions", _fx("stale_suppressed.py"))
    assert r.returncode == 1
    assert "S001" in r.stdout
    r = _cli("--check-suppressions", _fx("suppressed.py"))
    assert r.returncode == 0, r.stdout


def test_cli_sarif_output(tmp_path):
    sarif_path = tmp_path / "flow.sarif"
    r = _cli("--sarif", str(sarif_path), _fx("f4_flagged.py"))
    assert r.returncode == 1
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ba3cflow"
    rule_ids = {rd["id"] for rd in run["tool"]["driver"]["rules"]}
    assert set(RULE_IDS) <= rule_ids
    results = run["results"]
    assert results and all(res["ruleId"] == "F4" for res in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("f4_flagged.py")
    assert loc["region"]["startLine"] > 0
