"""Orchestration subsystem units: spec, supervisor, autoscaler, learner
failover, chaos — plus the stale-shm-ring regression.

The fast tests drive the supervisor with duck-typed fake processes (the
factory contract is explicitly process-LIKE), so respawn/backoff/circuit/
scale logic is exercised in milliseconds with no spawn in the loop. The
slow tests run the real thing: a supervised C++ block-wire fleet feeding a
live master (tests/test_actor_failure.py holds the full SIGKILL chain).
"""

from __future__ import annotations

import json
import os
import stat
import sys
import time

import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate import (
    Autoscaler,
    AutoscalerPolicy,
    ChaosMonkey,
    FleetSpec,
    FleetSupervisor,
    LearnerSupervisor,
    finalized_step,
)
from distributed_ba3c_tpu.telemetry import exporters
from distributed_ba3c_tpu.utils import shm


class FakeProc:
    """Duck-typed slot process: instant, killable, inspectable."""

    def __init__(self, idx):
        self.idx = idx
        self._alive = False
        self.exitcode = None
        self.started = 0
        self.pid = None  # no real pid: sigkill_slot falls back to .kill()

    def start(self):
        self._alive = True
        self.started += 1

    def is_alive(self):
        return self._alive

    def terminate(self):
        self._alive = False
        self.exitcode = -15

    def kill(self):
        self._alive = False
        self.exitcode = -9

    def join(self, timeout=None):
        pass


def _spec(**kw):
    base = dict(
        pipe_c2s="ipc:///tmp/t-c2s",
        pipe_s2c="ipc:///tmp/t-s2c",
        fleet_size=3,
        fleet_min=1,
        fleet_max=6,
        backoff_base_s=0.02,
        backoff_max_s=0.1,
        stable_after_s=10.0,
        restart_budget=32,
        budget_window_s=60.0,
    )
    base.update(kw)
    return FleetSpec(**base)


def _sup(spec=None, **kw):
    spec = spec or _spec()
    made = []

    def factory(i):
        p = FakeProc(i)
        made.append(p)
        return p

    sup = FleetSupervisor(
        spec, factory=factory, poll_interval_s=0.02, **kw
    )
    return sup, made


def _wait(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def _counter(name, role="orchestrator"):
    return telemetry.registry(role).counter(name).value()


# ---------------------------------------------------------------------------
# FleetSpec
# ---------------------------------------------------------------------------


def test_spec_json_round_trip():
    spec = _spec(game="breakout", wire="block-shm", envs_per_server=8)
    again = FleetSpec.from_json(spec.to_json())
    assert again == spec


def test_spec_rejects_unknown_field_and_bad_bounds():
    with pytest.raises(ValueError, match="unknown fleet spec fields"):
        FleetSpec.from_json(json.dumps({"fleet_maximum": 4}))
    with pytest.raises(ValueError, match="fleet_min"):
        _spec(fleet_min=5, fleet_max=3)
    with pytest.raises(ValueError, match="outside"):
        _spec(fleet_size=9, fleet_max=6)
    with pytest.raises(ValueError, match="wire"):
        _spec(wire="carrier-pigeon")


def test_spec_backoff_schedule_doubles_and_caps():
    spec = _spec(backoff_base_s=0.5, backoff_max_s=3.0)
    assert spec.backoff_s(1) == 0.5
    assert spec.backoff_s(2) == 1.0
    assert spec.backoff_s(3) == 2.0
    assert spec.backoff_s(4) == 3.0  # capped
    assert spec.backoff_s(50) == 3.0


# ---------------------------------------------------------------------------
# FleetSupervisor (fake processes)
# ---------------------------------------------------------------------------


def test_supervisor_spawns_respawns_and_accounts(tmp_path):
    telemetry.configure(str(tmp_path))
    sup, made = _sup()
    deaths0 = _counter("server_deaths_total")
    respawns0 = _counter("server_respawns_total")
    try:
        sup.start()
        assert sup.live_count() == 3
        assert len(made) == 3
        # SIGKILL one slot: reaped, accounted, respawned after backoff
        assert sup.sigkill_slot(1)
        _wait(lambda: sup.live_count() == 3, msg="respawn")
        assert _counter("server_deaths_total") == deaths0 + 1
        assert _counter("server_respawns_total") == respawns0 + 1
        assert len(made) == 4 and made[3].idx == 1
        reg = telemetry.registry("orchestrator")
        assert reg.gauge("fleet_target_size").value() == 3
        assert reg.gauge("fleet_live_size").value() == 3
        kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
        assert "server_death" in kinds and "server_respawn" in kinds
    finally:
        telemetry.configure(None)
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_scale_events_and_gauge_pair():
    sup, made = _sup()
    up0, down0 = _counter("scale_up_total"), _counter("scale_down_total")
    try:
        sup.start()
        sup.scale_to(5, "test growth")
        _wait(lambda: sup.live_count() == 5, msg="scale up")
        assert _counter("scale_up_total") == up0 + 1
        # clamped at the spec bounds, no event for a no-op
        assert sup.scale_to(99, "clamped") == 6
        _wait(lambda: sup.live_count() == 6, msg="scale to max")
        assert sup.scale_to(99, "noop") == 6
        assert _counter("scale_up_total") == up0 + 2
        sup.scale_to(1, "test shrink")
        _wait(lambda: sup.live_count() == 1, msg="scale down")
        assert _counter("scale_down_total") == down0 + 1
        # the scaled-down-on-purpose signature: target == live == 1
        reg = telemetry.registry("orchestrator")
        assert reg.gauge("fleet_target_size").value() == 1
        assert reg.gauge("fleet_live_size").value() == 1
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_gauges_reach_metrics_and_stat_json_shapes():
    """Satellite: fleet_target_size / fleet_live_size must be visible on
    BOTH export surfaces — /metrics (Prometheus text) and the stat.json
    bridge (export_scalars) — so a scrape can tell 'scaled down on
    purpose' from 'lost half the fleet'."""
    sup, _ = _sup()
    try:
        sup.start()
        text = exporters.prometheus_text()
        assert 'ba3c_fleet_target_size{role="orchestrator"} 3' in text
        assert 'ba3c_fleet_live_size{role="orchestrator"} 3' in text
        scalars = exporters.export_scalars()
        assert scalars["tele/orchestrator/fleet_target_size"] == 3.0
        assert scalars["tele/orchestrator/fleet_live_size"] == 3.0
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_circuit_breaker_opens_and_closes():
    # budget 3 respawns / 0.6 s window; a crash loop (factory whose procs
    # die instantly at the next tick) must trip the breaker
    spec = _spec(
        fleet_size=1, fleet_min=1, fleet_max=2,
        backoff_base_s=0.0, backoff_max_s=0.0,
        restart_budget=3, budget_window_s=0.6,
    )
    crashing = []

    def factory(i):
        p = FakeProc(i)
        crashing.append(p)
        return p

    sup = FleetSupervisor(spec, factory=factory, poll_interval_s=0.01)
    trips0 = _counter("circuit_trips_total")
    try:
        sup.start()
        # crash loop: kill whatever is alive as soon as it spawns
        deadline = time.monotonic() + 5
        while not sup.circuit_open and time.monotonic() < deadline:
            for p in crashing:
                if p.is_alive():
                    p.kill()
            time.sleep(0.005)
        assert sup.circuit_open, "circuit never opened under a crash loop"
        assert _counter("circuit_trips_total") == trips0 + 1
        n_at_trip = len(crashing)
        time.sleep(0.1)
        assert len(crashing) == n_at_trip, "respawns continued while open"
        # window drains -> breaker half-opens and respawns resume
        _wait(
            lambda: not sup.circuit_open, timeout=5, msg="circuit close"
        )
        _wait(lambda: sup.live_count() == 1, msg="respawn after close")
        kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
        assert "circuit_open" in kinds and "circuit_close" in kinds
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_restart_budget_zero_disables_respawn():
    spec = _spec(fleet_size=2, restart_budget=0, backoff_base_s=0.0)
    sup, made = _sup(spec)
    try:
        sup.start()
        assert sup.circuit_open  # permanently, by spec
        sup.sigkill_slot(0)
        time.sleep(0.2)
        assert sup.live_count() == 1
        assert len(made) == 2  # never respawned
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_kills_wedged_slot_on_master_prune_event():
    """The telemetry-registry liveness path: a prune event naming a slot
    whose process is still ALIVE means the master gave up on a wedged
    server — the supervisor must kill it and let the respawn path run."""
    sup, made = _sup()
    wedged0 = _counter("wedged_kills_total")
    try:
        sup.start()
        victim = made[2]
        assert victim.is_alive()
        # exactly what SimulatorMaster._prune_dead_actors records
        telemetry.record("prune", ident=repr(b"cppsim-2*block"), silent_s=12.0)
        _wait(
            lambda: _counter("wedged_kills_total") == wedged0 + 1,
            msg="wedged kill",
        )
        # the counter ticks when the SIGKILL is SENT; delivery + reaping
        # are async and can lag whole seconds on a loaded 1-core host —
        # wait for the death instead of asserting it already happened
        _wait(lambda: not victim.is_alive(), msg="victim death after kill")
        assert victim.exitcode == -9
        _wait(lambda: sup.live_count() == 3, msg="respawn after wedge")
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_ident_mapping_is_delimiter_exact():
    sup, _ = _sup(_spec(fleet_size=6, fleet_max=12, base_idx=0))
    try:
        sup.start()  # mapping covers the slots that exist
        assert sup._slot_for_ident(repr(b"cppsim-5*block")) == 5
        assert sup._slot_for_ident(repr(b"cppsim-5-3")) == 5
        # cppsim-5 must not match inside cppsim-50's ident
        assert sup._slot_for_ident(repr(b"cppsim-50*block")) is None
        assert sup._slot_for_ident(repr(b"someone-else")) is None
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_supervisor_prune_event_for_dead_slot_is_not_double_killed():
    """A prune recorded BEFORE the current incarnation started refers to
    its predecessor — it must not kill the healthy replacement."""
    sup, made = _sup()
    wedged0 = _counter("wedged_kills_total")
    try:
        sup.start()
        # stale prune: timestamped before every slot's started_t
        stale_t = time.monotonic() - 100
        sup._flight._ring.append((stale_t, "prune", {"ident": repr(b"cppsim-0*block")}))
        time.sleep(0.2)
        assert made[0].is_alive()
        assert _counter("wedged_kills_total") == wedged0
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------


def test_policy_baselines_then_scales_up_on_starvation():
    pol = AutoscalerPolicy(patience=2, cooldown_ticks=0)
    starved = {"queue_depth": 0, "queue_maxsize": 100, "blocked_puts_total": 0}
    assert pol.decide(starved) == (0, "")  # baseline tick
    assert pol.decide(starved)[0] == 0  # patience 1/2
    delta, reason = pol.decide(starved)
    assert delta == 1 and "starved" in reason


def test_policy_scales_down_on_blocked_put_delta_even_at_low_fill():
    pol = AutoscalerPolicy(patience=2, cooldown_ticks=0)
    s = {"queue_depth": 10, "queue_maxsize": 100, "blocked_puts_total": 0}
    pol.decide(s)  # baseline
    s = dict(s, blocked_puts_total=5)  # the master WAITED on a full queue
    assert pol.decide(s)[0] == 0
    s = dict(s, blocked_puts_total=9)
    delta, reason = pol.decide(s)
    assert delta == -1 and "backpressure" in reason


def test_policy_deadband_and_cooldown():
    pol = AutoscalerPolicy(
        low_fill=0.2, high_fill=0.8, patience=1, cooldown_ticks=2
    )
    mid = {"queue_depth": 50, "queue_maxsize": 100, "blocked_puts_total": 0}
    low = {"queue_depth": 0, "queue_maxsize": 100, "blocked_puts_total": 0}
    pol.decide(mid)  # baseline
    assert pol.decide(mid) == (0, "")  # inside the deadband: no move
    assert pol.decide(low)[0] == 1
    # cooldown: the next 2 ticks are ignored even though still starved
    assert pol.decide(low)[0] == 0
    assert pol.decide(low)[0] == 0
    assert pol.decide(low)[0] == 1


def test_autoscaler_drives_supervisor_between_bounds():
    sup, _ = _sup(_spec(fleet_size=2, fleet_min=1, fleet_max=4))
    signals = {"queue_depth": 0, "queue_maxsize": 100, "blocked_puts_total": 0}
    scaler = Autoscaler(
        sup,
        lambda: dict(signals),
        policy=AutoscalerPolicy(patience=1, cooldown_ticks=0),
        interval_s=60,  # ticks driven by hand below
    )
    try:
        sup.start()
        scaler.tick()  # baseline
        for _ in range(3):
            scaler.tick()
        assert sup.target == 4  # grew to max, clamped there
        _wait(lambda: sup.live_count() == 4, msg="autoscale growth")
        signals.update(queue_depth=95)
        for _ in range(4):
            scaler.tick()
        assert sup.target == 1  # shrank to min, clamped there
        kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
        assert "scale_decision" in kinds
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_policy_unknown_capacity_never_reads_as_starved():
    """Review regression: queue_maxsize 0 (unbounded queue, or a scrape
    target without the train_queue_capacity gauge) means the fill is
    UNKNOWN — the policy must not ratchet the fleet to fleet_max on a
    sentinel. Blocked-put deltas still drive scale-down capacity-free."""
    pol = AutoscalerPolicy(patience=1, cooldown_ticks=0)
    s = {"queue_depth": 0, "queue_maxsize": 0, "blocked_puts_total": 0}
    pol.decide(s)  # baseline
    for _ in range(5):
        assert pol.decide(s) == (0, "")
    s2 = dict(s, blocked_puts_total=7)
    delta, reason = pol.decide(s2)
    assert delta == -1 and "unknown" in reason


def test_scale_down_reaps_retiree_and_regrow_waits_for_it():
    """Review regression: a retired slot's process must be reaped (not
    left a zombie holding the slot's wire identity), and re-growing the
    slot must wait until the retiree is fully dead."""

    class SlowExit(FakeProc):
        def terminate(self):
            pass  # ignores SIGTERM: only kill() works

    sup = FleetSupervisor(
        _spec(fleet_size=2), factory=lambda i: SlowExit(i),
        poll_interval_s=0.02,
    )
    try:
        sup.start()
        made_before = sup.live_slots()
        retiree = dict(made_before)[1]
        sup.scale_to(1, "shrink")
        # the retiree ignored terminate(); the reaper must not SIGKILL it
        # before the grace — but must also not let a re-grown slot 1
        # spawn while it lives
        sup.scale_to(2, "regrow")
        time.sleep(0.2)
        with sup._lock:
            slot1 = sup._slots[1]
            assert slot1.proc is None or slot1.proc is not retiree
        if retiree.is_alive():
            # before the 5 s grace the slot must still be waiting
            with sup._lock:
                assert sup._slots[1].proc is None
            # close() must finish the retiree off
            sup.stop()
            sup.join(timeout=2)
            sup.close()
            assert not retiree.is_alive()
            return
        _wait(lambda: sup.live_count() == 2, msg="regrow after reap")
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_autoscaler_survives_signal_scrape_failure():
    sup, _ = _sup()
    err0 = _counter("autoscale_signal_errors_total")

    def broken():
        raise ConnectionError("endpoint gone")

    scaler = Autoscaler(sup, broken, interval_s=60)
    scaler.tick()
    assert _counter("autoscale_signal_errors_total") == err0 + 1
    sup.close()


# ---------------------------------------------------------------------------
# ChaosMonkey
# ---------------------------------------------------------------------------


def test_chaos_monkey_kill_sequence_is_seeded_and_accounted():
    kills0 = _counter("chaos_kills_total")
    seqs = []
    for _ in range(2):
        spec = _spec(fleet_size=4, restart_budget=0)  # no respawn: victims stay dead
        sup, made = _sup(spec)
        sup.start()
        monkey = ChaosMonkey(sup, max_kills=3, seed=7)
        victims = [monkey.kill_one() for _ in range(3)]
        seqs.append(victims)
        assert monkey.kills == 3
        assert all(v is not None for v in victims)
        sup.stop()
        sup.join(timeout=2)
        sup.close()
    assert seqs[0] == seqs[1], "same seed must replay the same kills"
    assert _counter("chaos_kills_total") == kills0 + 6
    kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
    assert "chaos_kill" in kinds


# ---------------------------------------------------------------------------
# stale shm-ring reclaim (satellite regression)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not shm.available(), reason="/dev/shm unavailable")
def test_spawn_reclaims_stale_ring_of_any_geometry():
    """Regression: a crashed fleet's leftover ring file — with a DIFFERENT
    cap than the new spec — plus an orphaned create temp must be reclaimed
    at spawn, not wedge the slot or leak /dev/shm space."""
    spec = _spec(wire="block-shm", fleet_size=1, pipe_c2s="ipc:///tmp/reclaim-c2s")
    name = shm.ring_name(spec.pipe_c2s, "cppsim-0")
    path = os.path.join(shm.SHM_DIR, name)
    with open(path, "wb") as fh:
        fh.truncate(123456)  # stale ring, wrong geometry
    with open(path + ".new-4242", "wb") as fh:
        fh.truncate(77)  # orphaned create temp from a dead creator
    rings0 = _counter("rings_reclaimed_total")
    sup, _ = _sup(spec)
    try:
        sup.start()
        assert not os.path.exists(path)
        assert not os.path.exists(path + ".new-4242")
        assert _counter("rings_reclaimed_total") == rings0 + 2
    finally:
        sup.stop()
        sup.join(timeout=2)
        sup.close()


def test_ring_name_is_stable_per_fleet_and_slot():
    a = shm.ring_name("tcp://10.0.0.1:5555", "cppsim-3")
    assert a == shm.ring_name("tcp://10.0.0.1:5555", "cppsim-3")
    assert a != shm.ring_name("tcp://10.0.0.1:5556", "cppsim-3")
    assert a != shm.ring_name("tcp://10.0.0.1:5555", "cppsim-4")


# ---------------------------------------------------------------------------
# LearnerSupervisor (stubbed train.py — jax-free, fast)
# ---------------------------------------------------------------------------

_STUB = r"""#!/usr/bin/env python3
import json, os, sys
logdir = sys.argv[sys.argv.index("--logdir") + 1]
calls_path = os.environ["STUB_CALLS"]
calls = json.load(open(calls_path)) if os.path.exists(calls_path) else []
calls.append(sys.argv[1:])
json.dump(calls, open(calls_path, "w"))
ck = os.path.join(logdir, "checkpoints")
os.makedirs(ck, exist_ok=True)
if len(calls) == 1:
    # first attempt: finalize a checkpoint, then 'crash'
    json.dump({"all": [40], "latest": 40},
              open(os.path.join(ck, "checkpoint.json"), "w"))
    sys.exit(1)
sys.exit(0)
"""


def _write_stub(tmp_path):
    stub = tmp_path / "train_stub.py"
    stub.write_text(_STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    return str(stub)


def test_learner_failover_resumes_from_finalized_checkpoint(
    tmp_path, monkeypatch
):
    calls_path = tmp_path / "calls.json"
    monkeypatch.setenv("STUB_CALLS", str(calls_path))
    logdir = str(tmp_path / "run")
    resumes0 = _counter("learner_resumes_total")
    sup = LearnerSupervisor(
        logdir,
        ["--logdir", logdir],
        max_restarts=3,
        train_py=_write_stub(tmp_path),
        python=sys.executable,
        poll_s=0.05,
    )
    assert sup.run() == 0
    calls = json.loads(calls_path.read_text())
    assert len(calls) == 2
    assert "--load" not in calls[0], "fresh launch must not --load"
    i = calls[1].index("--load")
    assert calls[1][i + 1] == os.path.join(logdir, "checkpoints")
    assert _counter("learner_resumes_total") == resumes0 + 1
    failovers = [
        f
        for _, k, f in telemetry.flight_recorder().events_since(0)
        if k == "learner_failover"
    ]
    assert failovers and failovers[-1]["resume_step"] == 40


def test_learner_gives_up_after_restart_budget(tmp_path, monkeypatch):
    stub = tmp_path / "always_dies.py"
    stub.write_text("import sys\nsys.exit(3)\n")
    monkeypatch.setenv("STUB_CALLS", str(tmp_path / "unused.json"))
    logdir = str(tmp_path / "run")
    sup = LearnerSupervisor(
        logdir, ["--logdir", logdir], max_restarts=2,
        train_py=str(stub), python=sys.executable, poll_s=0.05,
    )
    assert sup.run() == 3
    kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
    assert "learner_giveup" in kinds


def test_learner_rejects_explicit_load():
    with pytest.raises(ValueError, match="--load belongs to the supervisor"):
        LearnerSupervisor("x", ["--logdir", "x", "--load", "y"])


def test_learner_rejects_mismatched_or_missing_logdir():
    """Review regression: a train-args --logdir pointing elsewhere would
    make the watchdog stall-kill a healthy learner and resume from a
    directory the child never writes."""
    with pytest.raises(ValueError, match="does not match"):
        LearnerSupervisor("runs/a", ["--logdir", "runs/b"])
    with pytest.raises(ValueError, match="must include --logdir"):
        LearnerSupervisor("runs/a", ["--env", "fake"])


def test_learner_stall_watchdog_kills_silent_child(tmp_path):
    stub = tmp_path / "hangs.py"
    stub.write_text("import time\ntime.sleep(600)\n")
    logdir = str(tmp_path / "run")
    os.makedirs(logdir, exist_ok=True)
    sup = LearnerSupervisor(
        logdir, ["--logdir", logdir], max_restarts=0,
        stall_secs=0.5, startup_grace_s=0.0,
        train_py=str(stub), python=sys.executable, poll_s=0.05,
    )
    t0 = time.monotonic()
    rc = sup.run()
    assert rc != 0
    assert time.monotonic() - t0 < 30, "stall watchdog never fired"
    kinds = [e[1] for e in telemetry.flight_recorder().events_since(0)]
    assert "learner_stall_kill" in kinds


def test_finalized_step_gate(tmp_path):
    ck = tmp_path / "checkpoints"
    ck.mkdir()
    assert finalized_step(str(ck)) is None  # no metadata at all
    (ck / "checkpoint.json").write_text(json.dumps({"latest": None}))
    assert finalized_step(str(ck)) is None  # dir exists, nothing finalized
    (ck / "checkpoint.json").write_text(json.dumps({"latest": 120}))
    assert finalized_step(str(ck)) == 120
