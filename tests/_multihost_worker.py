"""Subprocess body for the 2-process jax.distributed integration test.

Run as:  python _multihost_worker.py <rank> <nprocs> <coordinator> [cli]

Bootstraps jax.distributed over localhost TCP (gloo CPU collectives), builds
the host-major global mesh, runs ONE sharded train step where each process
feeds a DIFFERENT local batch shard, and prints the resulting param digest.
Both ranks must print the identical digest (the psum makes the update global),
and it must match a single-process run over the concatenated batch — asserted
by the parent test.

With the optional ``cli`` argument it instead runs the full CLI entry
(`--worker_hosts` wiring) on FakeEnv for a short run, exercising
initialize_from_flags/make_global_mesh/is_chief end-to-end.
"""

import os
import sys

# NOTE: every side effect lives under __main__ — multiprocessing(spawn)
# children re-import this module and must NOT re-run jax.distributed.init.

os.environ.setdefault("XLA_FLAGS", "")

import numpy as np  # noqa: E402


def param_digest(params) -> str:
    import jax
    leaves = jax.tree_util.tree_leaves(jax.device_get(params))
    return " ".join(f"{np.float64(np.sum(l)):.10e}" for l in leaves)


def make_batch(global_batch: int, cfg):
    """Deterministic global batch; every rank builds the SAME one."""
    rng = np.random.default_rng(42)
    return {
        "state": rng.integers(
            0, 255, (global_batch, *cfg.state_shape), dtype=np.uint8
        ),
        "action": rng.integers(
            0, cfg.num_actions, (global_batch,), dtype=np.int32
        ),
        "return": rng.normal(size=(global_batch,)).astype(np.float32),
    }


def run_step_mode(rank: int, nprocs: int, coordinator: str) -> None:
    import jax

    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.distributed import (
        initialize_from_flags,
        local_batch_slice,
        make_global_mesh,
    )
    from distributed_ba3c_tpu.parallel.train_step import (
        create_train_state,
        make_train_step,
    )

    if nprocs > 1:
        hosts = ",".join([coordinator] + ["x:0"] * (nprocs - 1))
        assert initialize_from_flags(hosts, rank)
        assert jax.process_count() == nprocs

    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, batch_size=8)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    state = create_train_state(jax.random.PRNGKey(0), model, cfg, opt)
    mesh = make_global_mesh(num_model=1)
    step = make_train_step(model, opt, cfg, mesh)

    batch = make_batch(cfg.batch_size, cfg)
    if nprocs > 1:
        sl = local_batch_slice(cfg.batch_size)
        local = {k: v[sl] for k, v in batch.items()}
        put = lambda v: jax.make_array_from_process_local_data(  # noqa: E731
            step.batch_sharding, v
        )
    else:
        local = batch
        put = lambda v: jax.device_put(v, step.batch_sharding)  # noqa: E731

    state = jax.device_put(state, step.state_sharding)
    dbatch = {k: put(v) for k, v in local.items()}
    new_state, metrics = step(state, dbatch, cfg.entropy_beta)
    jax.block_until_ready(new_state)
    print(f"DIGEST {param_digest(new_state.params)}", flush=True)
    print(f"LOSS {float(metrics['loss']):.10e}", flush=True)


def run_fused_mode(rank: int, nprocs: int, coordinator: str, logdir: str) -> None:
    """Full fused trainer over 2 real processes: jax.distributed + global
    mesh + per-process env shards + collective checkpoint saves."""
    from distributed_ba3c_tpu.cli import main

    hosts = ",".join([coordinator] + [f"x{i}:0" for i in range(1, nprocs)])
    rc = main(
        [
            "--trainer", "tpu_fused_ba3c",
            "--env", "jax:pong",
            "--worker_hosts", hosts,
            "--task_index", str(rank),
            "--batch_size", "8",
            "--rollout_len", "2",
            "--fc_units", "16",
            "--steps_per_epoch", "2",
            "--max_epoch", "1",
            "--nr_eval", "2",
            "--eval_max_steps", "16",
            "--logdir", logdir,
        ]
    )
    print(f"CLI_RC {rc}", flush=True)


def run_soak_mode(
    rank: int, nprocs: int, coordinator: str, logdir: str, max_epoch: int,
    load: bool, rank_stall_timeout: float = 0.0,
) -> None:
    """Fused trainer soak: schedules + live hyper.txt + per-epoch param
    digests (BA3C_PARAM_DIGEST=1 set by the parent test). With ``load`` it
    resumes from the shared checkpoint dir mid-soak."""
    from distributed_ba3c_tpu.cli import main

    hosts = ",".join([coordinator] + [f"x{i}:0" for i in range(1, nprocs)])
    argv = [
        "--rank_stall_timeout", str(rank_stall_timeout),
        "--trainer", "tpu_fused_ba3c",
        "--env", "jax:pong",
        "--worker_hosts", hosts,
        "--task_index", str(rank),
        "--batch_size", "8",
        "--rollout_len", "2",
        "--fc_units", "16",
        "--steps_per_epoch", "2",
        "--max_epoch", str(max_epoch),
        "--nr_eval", "2",
        "--eval_every", "3",
        "--eval_max_steps", "8",
        "--learning_rate_final", "1e-4",
        "--entropy_beta_final", "1e-3",
        "--anneal", "exp",
        "--logdir", logdir,
    ]
    if load:
        argv += ["--load", os.path.join(logdir, "checkpoints")]
    rc = main(argv)
    print(f"CLI_RC {rc}", flush=True)


def run_cli_mode(
    rank: int, nprocs: int, coordinator: str, logdir: str, trainer=None
) -> None:
    from distributed_ba3c_tpu.cli import main

    hosts = ",".join(
        [coordinator] + [f"x{i}:0" for i in range(1, nprocs)]
    )
    rc = main(
        ([] if trainer is None else ["--trainer", trainer])
        + [
            "--env", "fake",
            "--worker_hosts", hosts,
            "--task_index", str(rank),
            "--simulator_procs", "2",
            "--batch_size", "16",
            "--image_size", "16",
            "--fc_units", "16",
            "--steps_per_epoch", "20",
            "--max_epoch", "1",
            "--nr_eval", "2",
            "--logdir", logdir,
        ]
    )
    print(f"CLI_RC {rc}", flush=True)


if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    rank = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    coordinator = sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "step"

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    if mode == "cli":
        run_cli_mode(rank, nprocs, coordinator, sys.argv[5])
    elif mode == "vtrace":
        run_cli_mode(
            rank, nprocs, coordinator, sys.argv[5], trainer="tpu_vtrace_ba3c"
        )
    elif mode == "fused":
        run_fused_mode(rank, nprocs, coordinator, sys.argv[5])
    elif mode == "soak":
        run_soak_mode(
            rank, nprocs, coordinator, sys.argv[5],
            max_epoch=int(sys.argv[6]), load=sys.argv[7] == "load",
            rank_stall_timeout=(
                float(sys.argv[8]) if len(sys.argv) > 8 else 0.0
            ),
        )
    else:
        run_step_mode(rank, nprocs, coordinator)
