"""The int8 rung (distributed_ba3c_tpu/quantize, docs/ingest.md).

Five contracts:

- **QuantSpec**: lossless JSON round-trip with unknown-field rejection,
  content-addressed hash, and validation that can never emit a spec the
  forward would divide by zero (or NaN) on — degenerate zero-range
  channels freeze to a VALID scale.
- **Calibration determinism**: the same traffic (same batch partition)
  freezes a bit-identical spec regardless of batch order — running maxima
  are permutation-invariant, so a re-run reproduces the committed hash.
- **Parity bands on real frames**: the int8 forward (both the int8-conv
  arm and the scale-folded fallback) stays inside the bf16 rung's own
  bands vs f32 on real jax-Pong AND jax-Seaquest observations — int8
  must not be a worse serving-numerics rung than the one below it.
- **End-to-end**: the overlap trainer's int8 actor learns in parity with
  f32 at lag 0, and the BatchedPredictor both serves a frozen spec
  immediately and calibrates one live (shadow tap → freeze → in-place
  switch) — with the usage errors exit-2-clean at every entry point.
- **Tap overhead**: calibration rides the serving plane inside a loose
  alternating-reps budget (the plane_bench --trace methodology — off/on
  interleaved so host drift cancels, medians compared).
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.quantize import (
    ActRangeAccumulator,
    CalibrationTap,
    QuantSpec,
    calibrate_from_env,
    calibrate_offline,
    make_quant_apply,
    quant_layer_names,
    quantize_params,
)
from distributed_ba3c_tpu.quantize.spec import QuantSpecError

#: the bf16 rung's own acceptance bands (test_staging.py) — the int8 rung
#: must sit inside them
BAND_LOG_MU = 0.1
BAND_VALUE = 0.05


@pytest.fixture(scope="module")
def pong_parts():
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    return cfg, model, opt, make_mesh(), pong


def _init_params(model, cfg, seed=0):
    return model.init(
        jax.random.PRNGKey(seed),
        np.zeros((1, *cfg.state_shape), np.uint8),
    )["params"]


def _real_frames(cfg, model, opt, env, n_envs=4, rollout_len=8, seed=0):
    """Real game frame stacks via the actor's own scan body — parity and
    calibration must be measured on the pixel distribution the rollout
    forward actually sees, not on white noise."""
    from jax import lax

    from distributed_ba3c_tpu.fused.loop import make_rollout_body

    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, n_envs)
    env_state = jax.vmap(env.reset)(keys)
    obs = jax.vmap(env.render)(env_state)
    stack = jnp.zeros(
        (n_envs, *obs.shape[1:], cfg.frame_history), jnp.uint8
    ).at[..., -1].set(obs)
    params = _init_params(model, cfg)
    body = make_rollout_body(model, cfg, env, params)
    carry = (
        env_state, stack, jax.random.fold_in(key, 1),
        jnp.zeros(n_envs, jnp.float32), jnp.zeros(n_envs, jnp.int32),
        jnp.zeros(n_envs, jnp.float32),
    )
    _, traj = jax.jit(
        lambda c: lax.scan(body, c, None, length=rollout_len)
    )(carry)
    return params, np.asarray(traj[0]).reshape(-1, *cfg.state_shape)


# -------------------------------------------------------------------------
# QuantSpec
# -------------------------------------------------------------------------


def _spec(**over):
    kw = dict(
        act_scales={"Conv_0": 0.5, "Dense_0": 1.25},
        method="absmax",
        calibration_batches=4,
        calibration_rows=64,
    )
    kw.update(over)
    return QuantSpec(**kw)


def test_spec_json_roundtrip_and_hash(tmp_path):
    spec = _spec()
    again = QuantSpec.from_json(spec.to_json())
    assert again == spec
    assert again.sha256() == spec.sha256()
    p = tmp_path / "spec.json"
    spec.save(str(p))
    assert QuantSpec.load(str(p)) == spec
    # the hash is content-addressed: a different scale is a different spec
    assert _spec(act_scales={"Conv_0": 0.5, "Dense_0": 1.5}).sha256() \
        != spec.sha256()


def test_spec_rejects_unknown_fields():
    doc = _spec().to_doc()
    doc["mystery_knob"] = 1
    with pytest.raises(QuantSpecError):
        QuantSpec.from_doc(doc)


@pytest.mark.parametrize("bad", [0.0, -1.0, float("nan"), float("inf")])
def test_spec_rejects_degenerate_scales(bad):
    """A spec the forward would divide by zero (or NaN) on can never be
    constructed, loaded or round-tripped."""
    with pytest.raises(QuantSpecError):
        QuantSpec(act_scales={"Conv_0": bad})


def test_spec_rejects_bad_method_and_empty():
    with pytest.raises(QuantSpecError):
        QuantSpec(act_scales={"Conv_0": 1.0}, method="vibes")
    with pytest.raises(QuantSpecError):
        QuantSpec(act_scales={})


def test_zero_range_freezes_to_valid_scale(pong_parts):
    """Degenerate calibration (all-zero traffic) must freeze a spec with
    finite positive scales — the no-signal fallback is scale 1.0, never
    a divide-by-zero shipped to the serving plane."""
    cfg, model, opt, _mesh, _pong = pong_parts
    params = _init_params(model, cfg)
    acc = ActRangeAccumulator(model, params)
    acc.observe(np.zeros((4, *cfg.state_shape), np.uint8))
    spec = acc.freeze()
    # Conv_0's input is the all-zero frame: zero range -> scale 1.0
    assert spec.act_scales["Conv_0"] == 1.0
    for v in spec.act_scales.values():
        assert np.isfinite(v) and v > 0


def test_zero_range_weight_channel_valid_scale(pong_parts):
    """An all-zero output channel quantizes with w_scale 1.0 (finite),
    and the quantized kernel stays all-zero — no NaN/inf in the table."""
    cfg, model, opt, _mesh, _pong = pong_parts
    params = jax.tree_util.tree_map(
        lambda a: np.array(a), jax.device_get(_init_params(model, cfg))
    )
    params["Conv_0"]["kernel"][..., 0] = 0.0
    spec = _full_spec(model)
    q = quantize_params(params, spec)
    assert np.all(np.asarray(q["Conv_0"]["kernel_q"][..., 0]) == 0)
    w_scale = np.asarray(q["Conv_0"]["w_scale"])
    assert np.isfinite(w_scale).all() and (w_scale > 0).all()


def _full_spec(model):
    return QuantSpec(act_scales={n: 1.0 for n in quant_layer_names(model)})


def test_quantize_params_table_shape(pong_parts):
    cfg, model, opt, _mesh, _pong = pong_parts
    params = _init_params(model, cfg)
    q = jax.device_get(quantize_params(params, _full_spec(model)))
    for name in quant_layer_names(model):
        assert q[name]["kernel_q"].dtype == np.int8
        assert q[name]["w_scale"].dtype == np.float32
        assert q[name]["w_scale"].shape == (params[name]["kernel"].shape[-1],)
        assert q[name]["act_scale"].shape == ()
    # the heads stay f32 and untouched
    np.testing.assert_array_equal(
        q["Dense_1"]["kernel"], jax.device_get(params["Dense_1"]["kernel"])
    )


def test_quantize_params_missing_layer_raises(pong_parts):
    cfg, model, opt, _mesh, _pong = pong_parts
    params = _init_params(model, cfg)
    with pytest.raises(ValueError):
        quantize_params(
            params, QuantSpec(act_scales={"Conv_99": 1.0})
        )


# -------------------------------------------------------------------------
# calibration determinism
# -------------------------------------------------------------------------


def test_calibration_deterministic_and_order_invariant(pong_parts):
    """Same traffic partition -> bit-identical spec (same JSON, same
    hash), in ANY batch order — the committed hash is reproducible."""
    cfg, model, opt, _mesh, pong = pong_parts
    params, frames = _real_frames(cfg, model, opt, pong)
    batches = [frames[i::3] for i in range(3)]
    a = calibrate_offline(model, params, batches)
    b = calibrate_offline(model, params, batches)
    c = calibrate_offline(model, params, list(reversed(batches)))
    assert a.to_json() == b.to_json() == c.to_json()
    assert a.sha256() == c.sha256()
    assert a.calibration_batches == 3
    assert a.calibration_rows == len(frames)


def test_offline_calibration_zero_batches_raises(pong_parts):
    cfg, model, opt, _mesh, _pong = pong_parts
    with pytest.raises(ValueError):
        calibrate_offline(model, _init_params(model, cfg), [])


def test_calibrate_from_env_produces_loadable_spec(pong_parts, tmp_path):
    """The fused trainer's --quant_calibrate path: env-rollout
    calibration freezes a spec that survives the file round-trip the pod
    hosts load it through."""
    cfg, model, opt, _mesh, pong = pong_parts
    params = _init_params(model, cfg)
    spec = calibrate_from_env(
        model, cfg, pong, params, jax.random.PRNGKey(3),
        n_envs=4, batches=2, rollout_len=4,
    )
    assert set(spec.layers) == set(quant_layer_names(model))
    p = tmp_path / "spec.json"
    spec.save(str(p))
    assert QuantSpec.load(str(p)).sha256() == spec.sha256()


# -------------------------------------------------------------------------
# parity bands on real frames
# -------------------------------------------------------------------------


def _parity(cfg, model, opt, env, arm):
    params, frames = _real_frames(cfg, model, opt, env)
    spec = calibrate_offline(model, params, [frames])
    q = quantize_params(params, spec)
    out32 = model.apply({"params": params}, jnp.asarray(frames))
    outq = make_quant_apply(model, arm=arm)(q, jnp.asarray(frames))
    lp32 = jax.nn.log_softmax(out32.logits, axis=-1)
    lpq = jax.nn.log_softmax(outq.logits, axis=-1)
    return (
        float(jnp.max(jnp.abs(lp32 - lpq))),
        float(jnp.max(jnp.abs(out32.value - outq.value))),
    )


@pytest.mark.parametrize("arm", ["int8", "folded"])
def test_int8_parity_band_on_pong(pong_parts, arm):
    """The rung's numeric claim on real Pong pixels: both arms inside
    the bf16 bands (log mu within 0.1, V within 0.05)."""
    cfg, model, opt, _mesh, pong = pong_parts
    d_logmu, d_value = _parity(cfg, model, opt, pong, arm)
    assert d_logmu < BAND_LOG_MU, d_logmu
    assert d_value < BAND_VALUE, d_value


def test_int8_parity_band_on_seaquest():
    """Second game, denser pixel statistics than Pong — the calibrated
    ranges must hold the band there too."""
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import seaquest
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer

    cfg = BA3CConfig(num_actions=seaquest.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    d_logmu, d_value = _parity(cfg, model, opt, seaquest, "int8")
    assert d_logmu < BAND_LOG_MU, d_logmu
    assert d_value < BAND_VALUE, d_value


# -------------------------------------------------------------------------
# overlap trainer end-to-end
# -------------------------------------------------------------------------


def test_overlap_int8_requires_spec(pong_parts):
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step

    cfg, model, opt, mesh, pong = pong_parts
    with pytest.raises(ValueError, match="quant_spec"):
        make_overlap_step(
            model, opt, cfg, mesh, pong, rollout_len=3,
            rollout_dtype="int8",
        )


def test_int8_lag0_learning_parity_on_pong(pong_parts):
    """Lag-0 overlap with the int8 actor vs f32: identical initial state
    and keys, only the rollout forward's precision differs — the first
    update optimizes the same objective inside the bf16 band, and both
    keep training finitely."""
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step

    cfg, model, opt, mesh, pong = pong_parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    params = _init_params(model, cfg)
    spec = calibrate_from_env(
        model, cfg, pong, params, jax.random.PRNGKey(5),
        n_envs=n_envs, batches=2, rollout_len=4,
    )

    def run(dtype, quant_spec=None):
        step = make_overlap_step(
            model, opt, cfg, mesh, pong, rollout_len=3, lag=0,
            rollout_dtype=dtype, quant_spec=quant_spec,
        )
        state = step.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )
        ms = []
        for _ in range(2):
            state, m = step(state, cfg.entropy_beta)
            ms.append({k: float(v) for k, v in m.items()})
        return ms

    f32 = run("float32")
    i8 = run("int8", quant_spec=spec)
    for ms in (f32, i8):
        for m in ms:
            for k, v in m.items():
                assert np.isfinite(v), k
    assert abs(f32[0]["loss"] - i8[0]["loss"]) < 0.05
    assert abs(f32[0]["pred_value"] - i8[0]["pred_value"]) < 0.05
    assert abs(f32[0]["entropy"] - i8[0]["entropy"]) < 0.05


# -------------------------------------------------------------------------
# BatchedPredictor end-to-end
# -------------------------------------------------------------------------


def test_predictor_int8_immediate_table_and_band(pong_parts):
    """rollout_dtype=int8 with a frozen spec: the table is quantized at
    construction, serving works, values inside the band of the f32
    server, and a fresh f32 publish lands re-quantized."""
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg, model, opt, _mesh, pong = pong_parts
    params, frames = _real_frames(cfg, model, opt, pong)
    spec = calibrate_offline(model, params, [frames])
    states = frames[:4]
    p32 = BatchedPredictor(model, params, batch_size=4, greedy=True)
    p8 = BatchedPredictor(
        model, params, batch_size=4, greedy=True,
        rollout_dtype="int8", quant_spec=spec,
        tele_role="predictor.int8",
    )
    assert p8.serving_dtype == "int8"
    table = p8._policies["default"]
    assert np.asarray(table["Conv_0"]["kernel_q"]).dtype == np.int8
    _, v32, _ = p32.predict_batch(states)
    _, v8, _ = p8.predict_batch(states)
    assert np.max(np.abs(v32 - v8)) < BAND_VALUE
    p8.update_params(jax.device_put(params))
    table = p8._policies["default"]
    assert np.asarray(table["Conv_0"]["kernel_q"]).dtype == np.int8
    a8, _, _ = p8.predict_batch(states)
    assert a8.shape == (4,)


def test_predictor_quant_ctor_validation(pong_parts):
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg, model, opt, _mesh, _pong = pong_parts
    params = _init_params(model, cfg)
    spec = _full_spec(model)
    # int8 with no source, int8 with both sources, quant args off-int8
    with pytest.raises(ValueError):
        BatchedPredictor(model, params, rollout_dtype="int8")
    with pytest.raises(ValueError):
        BatchedPredictor(
            model, params, rollout_dtype="int8",
            quant_spec=spec, quant_calibrate=4,
        )
    with pytest.raises(ValueError):
        BatchedPredictor(
            model, params, rollout_dtype="bfloat16", quant_spec=spec
        )


def _drain(pred, states, n):
    done = threading.Event()
    left = [n]
    for _ in range(n):
        def cb(a, v, lp):
            left[0] -= 1
            if left[0] == 0:
                done.set()

        pred.put_block_task(states, cb)
    assert done.wait(120)


def test_predictor_calibrate_then_switch(pong_parts):
    """The live-calibration path end to end: serve f32 while the shadow
    tap accumulates, freeze after N batches, switch the plane to int8 in
    place — table quantized, tap uninstalled, async AND sync serving
    keep working on the SAME predictor."""
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg, model, opt, _mesh, pong = pong_parts
    params, frames = _real_frames(cfg, model, opt, pong)
    states = frames[:4]
    pred = BatchedPredictor(
        model, params, batch_size=4, greedy=True, coalesce_ms=0.0,
        rollout_dtype="int8", quant_calibrate=3,
        tele_role="predictor.calib",
    )
    assert pred.serving_dtype == "float32"  # not calibrated yet
    assert pred.shadow_tap is not None
    pred.warmup(cfg.state_shape)
    pred.start()
    try:
        _drain(pred, states, 4)
        deadline = time.monotonic() + 60
        while pred.quant_spec is None and time.monotonic() < deadline:
            _drain(pred, states, 1)
        assert pred.quant_spec is not None, "spec never froze"
        assert pred.serving_dtype == "int8"
        assert pred.shadow_tap is None and pred._shadow is None
        table = pred._policies["default"]
        assert np.asarray(table["Conv_0"]["kernel_q"]).dtype == np.int8
        assert pred.quant_spec.calibration_batches == 3
        # async serving continues on the switched program
        _drain(pred, states, 2)
        # and the sync path sees program+table consistently
        a, v, _ = pred.predict_batch(states)
        assert a.shape == (4,) and np.isfinite(v).all()
    finally:
        pred.stop()


def test_calibration_tap_overhead_alternating_reps(pong_parts):
    """The tap's cost rides inside a loose budget, measured the
    plane_bench --trace way: off/on reps ALTERNATE so host drift hits
    both sides equally, medians compared. The bound is deliberately slack
    (the calibrating plane mirrors every batch by design — the PR-9
    shadow cost — and CI hosts are 1-core): the gate catches the tap
    going accidentally synchronous-per-row, not percent-level noise."""
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg, model, opt, _mesh, pong = pong_parts
    params, frames = _real_frames(cfg, model, opt, pong)
    states = frames[:4]

    off = BatchedPredictor(
        model, params, batch_size=4, greedy=True, coalesce_ms=0.0,
        tele_role="predictor.tap_off",
    )
    on = BatchedPredictor(
        model, params, batch_size=4, greedy=True, coalesce_ms=0.0,
        rollout_dtype="int8", quant_calibrate=10_000,  # never freezes here
        tele_role="predictor.tap_on",
    )
    for p in (off, on):
        p.warmup(cfg.state_shape)
        p.start()
    try:
        _drain(off, states, 3)  # warm both paths (incl. the tap's
        _drain(on, states, 3)   # stats-forward compile) before timing
        t_off, t_on = [], []
        for _ in range(4):
            t0 = time.monotonic()
            _drain(off, states, 6)
            t_off.append(time.monotonic() - t0)
            t0 = time.monotonic()
            _drain(on, states, 6)
            t_on.append(time.monotonic() - t0)
        ratio = float(np.median(t_on) / max(np.median(t_off), 1e-9))
        assert ratio < 6.0, (ratio, t_off, t_on)
        assert on.quant_spec is None  # still calibrating, never froze
    finally:
        off.stop()
        on.stop()


# -------------------------------------------------------------------------
# topology / flag surface
# -------------------------------------------------------------------------


def test_mode_topology_quant_validation():
    from distributed_ba3c_tpu.orchestrate.topology import (
        ModeTopology,
        TopologyError,
    )

    # exactly-one-source, both ways
    with pytest.raises(TopologyError):
        ModeTopology(rollout_dtype="int8")
    with pytest.raises(TopologyError):
        ModeTopology(
            rollout_dtype="int8", quant_spec="s.json", quant_calibrate=4
        )
    # quant knobs are int8-only
    with pytest.raises(TopologyError):
        ModeTopology(rollout_dtype="bfloat16", quant_calibrate=4)
    with pytest.raises(TopologyError):
        ModeTopology(rollout_dtype="float32", quant_spec="s.json")
    with pytest.raises(TopologyError):
        ModeTopology(rollout_dtype="float8")
    ModeTopology(rollout_dtype="int8", quant_spec="s.json")
    ModeTopology(
        trainer="tpu_fused_ba3c", overlap=True, rollout_dtype="int8",
        quant_calibrate=8,
    )


def test_topology_int8_fused_requires_overlap():
    """Cross-section rule: int8 quantizes the ACTOR program's snapshot,
    so the fused trainer must run the overlap split."""
    from distributed_ba3c_tpu.orchestrate.topology import (
        ModeTopology,
        TopologyError,
        TopologySpec,
    )

    def spec(**over):
        return TopologySpec(
            mode=ModeTopology(
                task="train", trainer="tpu_fused_ba3c", env="jax:pong",
                rollout_dtype="int8", quant_calibrate=4, **over,
            ),
        )

    with pytest.raises(TopologyError, match="overlap"):
        spec()
    spec(overlap=True)


def test_topology_roundtrip_carries_quant_fields():
    from distributed_ba3c_tpu.orchestrate.topology import (
        ModeTopology,
        TopologySpec,
    )

    spec = TopologySpec(
        mode=ModeTopology(
            task="train", trainer="tpu_fused_ba3c", env="jax:pong",
            overlap=True, rollout_dtype="int8", quant_calibrate=16,
        ),
    )
    doc = json.loads(spec.to_json())
    assert doc["mode"]["rollout_dtype"] == "int8"
    assert doc["mode"]["quant_calibrate"] == 16
    again = TopologySpec.from_json(spec.to_json())
    assert again == spec


def test_cli_int8_usage_errors_exit_2():
    """Both flag surfaces reject a sourceless int8 (and quant knobs
    off-int8) as clean exit-2 usage errors — no tracebacks."""
    import subprocess
    import sys

    cases = [
        ("distributed_ba3c_tpu.cli", [
            "--task", "train", "--trainer", "tpu_fused_ba3c", "--overlap",
            "--env", "jax:pong", "--rollout_dtype", "int8",
            "--dump_topology",
        ]),
        ("distributed_ba3c_tpu.cli", [
            "--task", "train", "--env", "cpp:pong",
            "--quant_calibrate", "4", "--dump_topology",
        ]),
        ("distributed_ba3c_tpu.pod.host", [
            "--host_id", "0", "--learner_c2s", "tcp://x:1",
            "--learner_s2c", "tcp://x:2", "--rollout_dtype", "int8",
        ]),
    ]
    for mod, argv in cases:
        r = subprocess.run(
            [sys.executable, "-c",
             f"from {mod} import main; import sys; sys.exit(main({argv!r}))"],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 2, (mod, argv, r.returncode, r.stderr)
        assert "Traceback" not in r.stderr, r.stderr
