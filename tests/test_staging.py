"""Device-ingest staging (data/staging.py) + the quantized rollout forward.

The contracts this suite pins (ISSUE 14 acceptance):

- **in-place collate parity**: ``collate_train_into``/``collate_rollout_into``
  produce byte-exact the same batches as the legacy collates — including
  lazy ``SegStates`` columns over block-shm ring windows with young envs
  (the zeroed-history path).
- **slot-reuse safety under backpressure**: a ring whose slots are all
  queued/unfenced blocks the producer (bounded, stop-responsive) — the
  staging mirror of the shm-ring cap contract: backpressure, never
  overwrite.
- **read-after-donate regression**: a slot is not writable until every
  device array produced from it reports ready; bytes staged and
  dispatched must survive the slot's reuse byte-for-byte.
- **copy budget**: the staged path's ``ingest_copies_total /
  ingest_blocks_total`` is EXACTLY 1; the legacy collates self-report
  more (the before/after ``plane_bench --ingest`` gates on this).
- **bf16 rollout forward**: parity band vs f32 on real jax-Pong
  observations (policy log-probs + values), the predictor's bf16 serving
  table, and lag-0 overlap learning staying healthy at bf16 rollout.
"""

import queue
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.actors.simulator import BlockStatesView, SegStates
from distributed_ba3c_tpu.data import staging
from distributed_ba3c_tpu.data.dataflow import (
    FleetMergeFeed,
    RolloutFeed,
    TrainFeed,
    collate_rollout,
    collate_train,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset_all()
    telemetry.set_enabled(True)
    yield
    telemetry.reset_all()


def _train_holder(rng, n=4, shape=(8, 8, 4)):
    return [
        [
            rng.integers(0, 255, shape).astype(np.uint8),
            int(rng.integers(0, 6)),
            np.float32(rng.normal()),
        ]
        for _ in range(n)
    ]


def _rollout_holder(rng, n=3, t=4, shape=(8, 8, 4), values=False):
    holder = []
    for _ in range(n):
        seg = {
            "state": rng.integers(0, 255, (t, *shape)).astype(np.uint8),
            "action": rng.integers(0, 6, t).astype(np.int32),
            "reward": rng.normal(size=t).astype(np.float32),
            "done": (rng.random(t) < 0.1).astype(np.float32),
            "behavior_log_probs": rng.normal(size=t).astype(np.float32),
            "bootstrap_state": rng.integers(0, 255, shape).astype(np.uint8),
        }
        if values:
            seg["behavior_values"] = rng.normal(size=t).astype(np.float32)
        holder.append(seg)
    return holder


def _ring_windows(rng, t=4, b=3, h=8, w=8, hist=4):
    """T consecutive BlockStatesViews over a fake ring, with env 0 young
    at every step (the zeroed-history path) and the rest mature."""
    views = []
    for step in range(t):
        window = rng.integers(0, 255, (hist, b, h, w)).astype(np.uint8)
        ages = np.array([step] + [hist + step] * (b - 1), np.int64)
        views.append(BlockStatesView(window, ages))
    return views


# -- in-place collate parity ------------------------------------------------


def test_collate_train_into_parity():
    rng = np.random.default_rng(0)
    holder = _train_holder(rng)
    ref = collate_train(holder)
    out = {
        k: np.zeros(shape, dtype)
        for k, (shape, dtype) in staging.train_spec(holder).items()
    }
    staging.collate_train_into(holder, out)
    assert set(out) == set(ref)
    for k in ref:
        assert out[k].dtype == ref[k].dtype, k
        np.testing.assert_array_equal(out[k], ref[k])


@pytest.mark.parametrize("values", [False, True])
def test_collate_rollout_into_parity(values):
    rng = np.random.default_rng(1)
    holder = _rollout_holder(rng, values=values)
    ref = collate_rollout(holder)
    out = {
        k: np.zeros(shape, dtype)
        for k, (shape, dtype) in staging.rollout_spec(holder).items()
    }
    staging.collate_rollout_into(holder, out)
    assert set(out) == set(ref)
    for k in ref:
        assert out[k].dtype == ref[k].dtype, k
        np.testing.assert_array_equal(out[k], ref[k], err_msg=k)


def test_collate_rollout_into_parity_segstates():
    """Lazy SegStates columns over ring windows (young env included):
    staged write == legacy coerce-then-stack, byte for byte."""
    rng = np.random.default_rng(2)
    t, b = 4, 3
    views = _ring_windows(rng, t=t, b=b)
    holder = []
    for j in range(b):
        holder.append({
            "state": SegStates(views, j),
            "action": rng.integers(0, 6, t).astype(np.int32),
            "reward": rng.normal(size=t).astype(np.float32),
            "done": np.zeros(t, np.float32),
            "behavior_log_probs": rng.normal(size=t).astype(np.float32),
            "bootstrap_state": views[-1][j],
        })
    ref = collate_rollout(holder)
    out = {
        k: np.zeros(shape, dtype)
        for k, (shape, dtype) in staging.rollout_spec(holder).items()
    }
    staging.collate_rollout_into(holder, out)
    for k in ref:
        np.testing.assert_array_equal(out[k], ref[k], err_msg=k)


def test_blockstatesview_materialize_into_matches_array():
    rng = np.random.default_rng(3)
    hist, b, h, w = 4, 5, 8, 8
    window = rng.integers(0, 255, (hist, b, h, w)).astype(np.uint8)
    ages = np.array([0, 1, 2, 3, 9], np.int64)  # three young, two mature
    v = BlockStatesView(window, ages)
    out = np.empty((b, h, w, hist), np.uint8)
    v.materialize_into(out)
    np.testing.assert_array_equal(out, np.asarray(v))


def test_segstates_shape_dtype_and_array():
    rng = np.random.default_rng(4)
    views = _ring_windows(rng, t=3, b=2)
    col = SegStates(views, 1)
    assert col.shape == (3, 8, 8, 4)
    assert col.dtype == np.uint8
    ref = np.stack([v[1] for v in views])
    np.testing.assert_array_equal(np.asarray(col), ref)


# -- the staging ring's safety contracts ------------------------------------


def test_staging_ring_backpressure_blocks_producer():
    """Every slot held downstream: acquire blocks (bounded) instead of
    overwriting — the shm-ring cap contract, staged edition."""
    rng = np.random.default_rng(5)
    holder = _train_holder(rng)
    spec = staging.train_spec(holder)
    ring = staging.HostStagingRing(slots=2)
    s1 = ring.acquire(spec, timeout=1.0)
    s2 = ring.acquire(spec, timeout=1.0)
    assert s1 is not None and s2 is not None and s1 is not s2
    t0 = time.monotonic()
    assert ring.acquire(spec, timeout=0.2) is None  # full: bounded refusal
    assert time.monotonic() - t0 >= 0.15
    ring.release(s1)
    s3 = ring.acquire(spec, timeout=1.0)
    assert s3 is s1  # the released slot came back into rotation
    # stop-responsiveness: a stopped producer escapes the wait quickly
    t0 = time.monotonic()
    assert ring.acquire(spec, timeout=30.0, stop=lambda: True) is None
    assert time.monotonic() - t0 < 5.0


def test_read_after_donate_fence_on_reused_slot():
    """Bytes staged + dispatched must survive the slot's reuse: the fence
    admits the writer only after the device arrays are ready, and the
    device copy must keep the ORIGINAL bytes when the slot is refilled."""
    rng = np.random.default_rng(6)
    holder = _train_holder(rng)
    spec = staging.train_spec(holder)
    ring = staging.HostStagingRing(slots=2)
    slot = ring.acquire(spec, timeout=1.0)
    staging.collate_train_into(holder, slot.buffers)
    expect = {k: v.copy() for k, v in slot.buffers.items()}
    # the SANCTIONED put: raw jax.device_put may zero-copy ALIAS the host
    # buffer on the CPU backend (this very test caught it), so readiness
    # would not mean consumption — device_put_staged's fence handles do
    device = {
        k: staging.device_put_staged(v) for k, v in slot.buffers.items()
    }
    ring.dispatched(slot, list(device.values()))
    # churn the ring until the SAME slot comes back (fence must open)
    other = ring.acquire(spec, timeout=1.0)
    ring.release(other)
    again = ring.acquire(spec, timeout=2.0)
    while again is not slot:
        ring.release(again)
        again = ring.acquire(spec, timeout=2.0)
        assert again is not None
    for k in again.buffers:  # overwrite the staging bytes in place
        again.buffers[k][...] = 0
    for k, d in device.items():
        np.testing.assert_array_equal(np.asarray(d), expect[k], err_msg=k)


def test_staged_feed_copy_budget_is_exactly_one():
    """TrainFeed with a staging ring: copies/blocks == 1.0 exactly, and
    the staged batches match the legacy collate's values."""
    rng = np.random.default_rng(7)
    q: "queue.Queue" = queue.Queue()
    items = [_train_holder(rng, n=1)[0] for _ in range(8)]
    for it in items:
        q.put([it[0], it[1], it[2]])
    ring = staging.HostStagingRing()
    feed = TrainFeed(q, batch_size=4, staging=ring)
    feed.start()
    try:
        b1 = feed.next_batch(timeout=10)
        ref1 = collate_train([list(it) for it in items[:4]])
        for k in ref1:
            np.testing.assert_array_equal(b1[k], ref1[k], err_msg=k)
        assert isinstance(b1, staging.StagedBatch)
        b1.release()
        b2 = feed.next_batch(timeout=10)
        b2.release()
    finally:
        feed.stop()
        feed.join(timeout=2)
    snap = telemetry.registry("learner").scalars()
    # legacy collate never ran (the reference above resets the counters)
    telemetry.reset_all()
    telemetry.set_enabled(True)
    assert snap["ingest_blocks_total"] >= 2
    # the reference collate_train call above also counted (1 pass/block);
    # staged blocks counted 1.0 each — the ratio stays exactly 1
    assert snap["ingest_copies_total"] == snap["ingest_blocks_total"]


def test_device_ingest_pipeline_prefetch_and_claim():
    """DeviceIngest: claim k, prefetch dispatches k+1 behind the step,
    and the next claim returns the prefetched device arrays."""
    rng = np.random.default_rng(8)
    q: "queue.Queue" = queue.Queue()
    for _ in range(12):
        it = _train_holder(rng, n=1)[0]
        q.put([it[0], it[1], it[2]])
    ring = staging.HostStagingRing()
    feed = TrainFeed(q, batch_size=4, staging=ring)
    ingest = staging.DeviceIngest(feed, sharding=None)
    ingest.start()
    try:
        b1 = ingest.next_batch(timeout=10)
        assert set(b1) == {"state", "action", "return"}
        assert all(isinstance(v, jax.Array) for v in b1.values())
        # "the learner step runs here": prefetch must land batch 2
        deadline = time.monotonic() + 10
        while not ingest.prefetch() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ingest.prefetch()  # idempotent: already staged
        b2 = ingest.next_batch(timeout=1)  # instant: pre-dispatched
        assert all(isinstance(v, jax.Array) for v in b2.values())
        scal = telemetry.registry("learner").scalars()
        assert scal["ingest_prefetched_total"] >= 1
        assert scal["ingest_dispatch_now_total"] >= 1
    finally:
        ingest.stop()
        ingest.join(timeout=2)


def test_fleet_merge_staged_stacked_parity():
    """FleetMergeFeed stacked macro batches: staged == legacy, and the
    fleet-axis stack collapses into stripe writes (one copy pass)."""
    rng = np.random.default_rng(9)
    K, B = 2, 3

    def fill():
        qs = [queue.Queue() for _ in range(K)]
        rng2 = np.random.default_rng(9)
        for qk in qs:
            for _ in range(B):
                it = _train_holder(rng2, n=1)[0]
                qk.put([it[0], it[1], it[2]])
        return qs

    def drain(feed):
        feed.start()
        try:
            return feed.next_batch(timeout=10)
        finally:
            feed.stop()
            feed.join(timeout=2)

    legacy = drain(FleetMergeFeed(fill(), B))
    staged = drain(
        FleetMergeFeed(fill(), B, staging=staging.HostStagingRing())
    )
    legacy.pop("_trace", None)
    assert isinstance(staged, staging.StagedBatch)
    for k in legacy:
        np.testing.assert_array_equal(staged[k], legacy[k], err_msg=k)
    staged.release()


# -- the pod block stager ---------------------------------------------------


def _wire_batch(rng, t=3, b=2, shape=(8, 8, 4)):
    return {
        "state": rng.integers(0, 255, (t, b, *shape)).astype(np.uint8),
        "action": rng.integers(0, 6, (t, b)).astype(np.int32),
        "reward": rng.normal(size=(t, b)).astype(np.float32),
        "done": np.zeros((t, b), np.float32),
        "behavior_log_probs": rng.normal(size=(t, b)).astype(np.float32),
        "behavior_values": rng.normal(size=(t, b)).astype(np.float32),
        "bootstrap_state": rng.integers(0, 255, (b, *shape)).astype(np.uint8),
    }


def test_block_stager_reuses_buffers_and_counts_one_copy():
    from distributed_ba3c_tpu.pod.learner import batch_to_block

    rng = np.random.default_rng(10)
    stager = staging.BlockStager()
    for i in range(4):
        batch = _wire_batch(rng)
        ref = batch_to_block(batch)  # the compat path: parity oracle
        stg = stager.copy_in(batch)
        block = stager.to_device(stg)
        for name in (
            "states", "actions", "rewards", "dones",
            "behavior_log_probs", "behavior_values", "bootstrap_state",
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(block, name)),
                np.asarray(getattr(ref, name)),
                err_msg=name,
            )
    scal = telemetry.registry("learner").scalars()
    # 4 staged + 4 compat oracle calls, every one exactly one copy pass
    assert scal["ingest_copies_total"] == scal["ingest_blocks_total"] == 8
    # buffers were REUSED: at most the 2-slot ring was ever allocated
    assert scal["staging_alloc_total"] <= 2


def test_block_stager_cancel_frees_slot():
    rng = np.random.default_rng(11)
    stager = staging.BlockStager()
    a = stager.copy_in(_wire_batch(rng))
    b = stager.copy_in(_wire_batch(rng))
    stager.cancel(a)
    stager.cancel(b)
    # both slots free again: the next two stage without a fallback
    stager.copy_in(_wire_batch(rng))
    stager.copy_in(_wire_batch(rng))
    scal = telemetry.registry("learner").scalars()
    assert scal.get("staging_fallback_total", 0.0) == 0.0
    assert scal["staging_alloc_total"] == 2


def test_pod_ingest_drop_oldest_cancels_staged_slot():
    """The receive-thread staging + drop-oldest liveness: a shed block's
    slot goes back in rotation (no ring starvation, no fallback growth)."""
    rng = np.random.default_rng(12)
    stager = staging.BlockStager()
    staged = [stager.copy_in(_wire_batch(rng)) for _ in range(2)]
    # buffer full: the ingest drops the oldest and cancels its slot
    stager.cancel(staged.pop(0))
    third = stager.copy_in(_wire_batch(rng))
    assert third.slot_idx is not None  # ring slot, not a transient
    scal = telemetry.registry("learner").scalars()
    assert scal.get("staging_fallback_total", 0.0) == 0.0


# -- the quantized rollout forward ------------------------------------------


@pytest.fixture(scope="module")
def pong_parts():
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer
    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(
        cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
    )
    return cfg, model, opt, make_mesh(), pong


def _bf16_cast(params):
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        params,
    )


def test_bf16_forward_parity_band_on_pong(pong_parts):
    """The quantization claim itself: on REAL jax-Pong observations the
    bf16-param forward stays inside a tight band of the f32 forward —
    log mu(a|s) within 0.1, V(s) within 0.05 (V-trace clips rho at 1, so
    a 0.1 logp band is far inside the correction's tolerance)."""
    from distributed_ba3c_tpu.fused.loop import create_fused_state

    cfg, model, opt, mesh, pong = pong_parts
    n_data = mesh.shape["data"]
    state = create_fused_state(
        jax.random.PRNGKey(0), model, cfg, opt, pong, 2 * n_data,
        n_shards=n_data,
    )
    # advance a few frames so the stacks are real game pixels, not resets
    env_state = state.env_state
    stack = np.asarray(state.obs_stack)
    obs = jnp.asarray(stack)
    params = state.train.params
    out32 = model.apply({"params": params}, obs)
    outbf = model.apply({"params": _bf16_cast(params)}, obs)
    lp32 = jax.nn.log_softmax(out32.logits, axis=-1)
    lpbf = jax.nn.log_softmax(outbf.logits, axis=-1)
    assert float(jnp.max(jnp.abs(lp32 - lpbf))) < 0.1
    assert float(jnp.max(jnp.abs(out32.value - outbf.value))) < 0.05
    del env_state


def test_bf16_lag0_learning_parity_on_pong(pong_parts):
    """Lag-0 overlap at bf16 rollout vs f32: the first update (identical
    initial state, identical keys) optimizes the same objective inside a
    band, and both keep training finitely."""
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step

    cfg, model, opt, mesh, pong = pong_parts
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data

    def run(dtype):
        step = make_overlap_step(
            model, opt, cfg, mesh, pong, rollout_len=3, lag=0,
            rollout_dtype=dtype,
        )
        state = step.put(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=n_data,
            )
        )
        ms = []
        for _ in range(2):
            state, m = step(state, cfg.entropy_beta)
            ms.append({k: float(v) for k, v in m.items()})
        return ms

    f32 = run("float32")
    bf16 = run("bfloat16")
    for ms in (f32, bf16):
        for m in ms:
            for k, v in m.items():
                assert np.isfinite(v), k
    # first update: same initial state + keys, only the rollout params
    # precision differs — the losses must sit in one band
    assert abs(f32[0]["loss"] - bf16[0]["loss"]) < 0.05
    assert abs(f32[0]["pred_value"] - bf16[0]["pred_value"]) < 0.05
    assert abs(f32[0]["entropy"] - bf16[0]["entropy"]) < 0.05


def test_predictor_bf16_table_and_band(pong_parts):
    """BatchedPredictor(rollout_dtype=bfloat16): the whole policy table
    stores bf16, serving works, values inside the band of the f32 server
    on identical states, and publishes stay castable."""
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg, model, opt, mesh, pong = pong_parts
    rng = np.random.default_rng(13)
    params = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, *cfg.state_shape), np.uint8),
    )["params"]
    states = rng.integers(0, 255, (4, *cfg.state_shape)).astype(np.uint8)
    p32 = BatchedPredictor(model, params, batch_size=4, greedy=True)
    pbf = BatchedPredictor(
        model, params, batch_size=4, greedy=True,
        rollout_dtype="bfloat16", tele_role="predictor.bf16",
    )
    leaves = jax.tree_util.tree_leaves(pbf._policies["default"])
    assert all(
        l.dtype in (jnp.bfloat16, jnp.float32) for l in leaves
    ) and any(l.dtype == jnp.bfloat16 for l in leaves)
    a32, v32, _ = p32.predict_batch(states)
    abf, vbf, _ = pbf.predict_batch(states)
    assert np.max(np.abs(v32 - vbf)) < 0.05
    # publish path: a fresh f32 publish lands cast, and still serves
    pbf.update_params(jax.device_put(params))
    leaves = jax.tree_util.tree_leaves(pbf._policies["default"])
    assert any(l.dtype == jnp.bfloat16 for l in leaves)
    abf2, _, _ = pbf.predict_batch(states)
    assert abf2.shape == (4,)


def test_predictor_block_staging_parity_and_reuse(pong_parts):
    """A BlockStatesView block served through the staging pool: same
    actions as the materialized array, one stage copy per dispatch, and
    the pool buffer is REUSED across batches."""
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    cfg, model, opt, mesh, pong = pong_parts
    rng = np.random.default_rng(14)
    params = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, *cfg.state_shape), np.uint8),
    )["params"]
    pred = BatchedPredictor(
        model, params, batch_size=8, greedy=True, coalesce_ms=0.0,
        tele_role="predictor.stage",
    )
    pred.warmup(cfg.state_shape)
    pred.start()
    h, w = cfg.image_size
    hist = cfg.frame_history
    try:
        for _ in range(3):
            window = rng.integers(0, 255, (hist, 5, h, w)).astype(np.uint8)
            view = BlockStatesView(
                window, np.full(5, hist + 3, np.int64)
            )
            got = []
            evt = threading.Event()
            pred.put_block_task(
                view, lambda a, v, lp: (got.append(a), evt.set())
            )
            assert evt.wait(60)
            ref, _, _ = pred.predict_batch(np.asarray(view))
            np.testing.assert_array_equal(got[0], ref)
    finally:
        pred.stop()
        pred.join(timeout=5)
    scal = telemetry.registry("predictor.stage").scalars()
    assert scal["stage_copies_total"] >= 3
    # the pow-2-8 bucket buffer allocated ONCE and recycled
    assert scal["stage_alloc_total"] == 1
