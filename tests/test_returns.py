"""Golden-value tests for n-step return computation (SURVEY.md §7 step 1)."""

import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu.ops import (
    discounted_returns_np,
    n_step_returns,
)


def test_discounted_returns_np_matches_hand_computation():
    # r = [1, 0, 2], bootstrap 10, gamma 0.5
    # R2 = 2 + 0.5*10 = 7 ; R1 = 0 + 0.5*7 = 3.5 ; R0 = 1 + 0.5*3.5 = 2.75
    out = discounted_returns_np(np.array([1.0, 0.0, 2.0]), bootstrap=10.0, gamma=0.5)
    np.testing.assert_allclose(out, [2.75, 3.5, 7.0])


def test_n_step_returns_matches_numpy_no_done():
    rng = np.random.default_rng(1)
    T, B = 7, 3
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    bootstrap = rng.normal(size=(B,)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    gamma = 0.99

    got = np.asarray(n_step_returns(jnp.array(rewards), jnp.array(dones), jnp.array(bootstrap), gamma))
    for b in range(B):
        want = discounted_returns_np(rewards[:, b], bootstrap[b], gamma)
        np.testing.assert_allclose(got[:, b], want, rtol=1e-5)


def test_n_step_returns_resets_at_episode_boundary():
    gamma = 0.9
    rewards = jnp.array([[1.0], [1.0], [1.0]])
    dones = jnp.array([[0.0], [1.0], [0.0]])  # episode ends after t=1
    bootstrap = jnp.array([5.0])
    out = np.asarray(n_step_returns(rewards, dones, bootstrap, gamma))
    # R2 = 1 + .9*5 = 5.5 ; R1 = 1 (done: no leak from R2) ; R0 = 1 + .9*1 = 1.9
    np.testing.assert_allclose(out[:, 0], [1.9, 1.0, 5.5], rtol=1e-6)
