"""Multi-fleet macro-batching (ISSUE 10): fleet addressing, the round-robin
merge collator, macro-step gradient equivalence (BA3C / V-trace / overlap
macro learner), experience-stream parity across fleet splits, per-fleet
telemetry identity + the global cardinality caps, and per-fleet scrape
addressing.

The equivalence tolerance story: the conv stack is bf16 by policy (audit
T1), so re-ordering a mean (K sub-batch means vs one K*B-batch mean)
perturbs cancellation-heavy reductions — bias/alpha gradients — at the
bf16 noise floor while kernel gradients agree to ulps and the aggregate
loss/grad-norm agree to ~1e-5. The per-leaf gate is therefore a relative
L2 bound (not elementwise allclose against near-zero entries), plus tight
scalar agreement on loss and grad_norm.
"""

from __future__ import annotations

import json
import queue
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.actors.fleet import (
    FanoutPredictors,
    build_fleet_planes,
    fleet_pipes,
)
from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.data.dataflow import (
    FleetMergeFeed,
    collate_rollout,
    collate_train,
)
from distributed_ba3c_tpu.envs.fake import build_fake_player
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.parallel.mesh import make_mesh
from distributed_ba3c_tpu.parallel.train_step import (
    create_train_state,
    make_macro_train_step,
    make_train_step,
)
from distributed_ba3c_tpu.parallel.vtrace_step import (
    make_vtrace_macro_step,
    make_vtrace_train_step,
)
from distributed_ba3c_tpu.utils.concurrency import FastQueue

N_ACTIONS = 4


# ---------------------------------------------------------------------------
# fleet addressing
# ---------------------------------------------------------------------------


def test_fleet_pipes_fleet0_identity():
    assert fleet_pipes("ipc:///tmp/x-c2s", "ipc:///tmp/x-s2c", 0) == (
        "ipc:///tmp/x-c2s", "ipc:///tmp/x-s2c"
    )


def test_fleet_pipes_tcp_port_stride():
    c2s, s2c = fleet_pipes("tcp://0.0.0.0:5555", "tcp://0.0.0.0:5556", 3)
    assert c2s == "tcp://0.0.0.0:5561"
    assert s2c == "tcp://0.0.0.0:5562"
    # the even stride keeps the conventional adjacent pair collision-free
    all_addrs = [
        a
        for k in range(4)
        for a in fleet_pipes("tcp://h:5555", "tcp://h:5556", k)
    ]
    assert len(set(all_addrs)) == len(all_addrs)


def test_fleet_pipes_ipc_suffix():
    c2s, s2c = fleet_pipes("ipc:///tmp/a", "ipc:///tmp/b", 2)
    assert c2s == "ipc:///tmp/a-f2"
    assert s2c == "ipc:///tmp/b-f2"


def test_build_fleet_planes_rejects_colliding_addresses():
    # odd spacing between the base c2s/s2c ports makes fleet 1's c2s land
    # on fleet 0's s2c — assembly must refuse, not double-bind
    with pytest.raises(ValueError, match="collide"):
        build_fleet_planes(
            2, "tcp://h:5555", "tcp://h:5557",
            make_predictor=lambda k, role: object(),
            make_master=lambda k, c, s, p, role: object(),
        )


def test_build_fleet_planes_roles_and_fanout():
    made = []

    class _Pred:
        def __init__(self, role):
            self.role = role
            self.num_actions = N_ACTIONS
            self.published = []

        def update_params(self, params, policy="default"):
            self.published.append((params, policy))

        def predict_batch(self, states):
            return "fleet0-answer"

    def make_predictor(k, role):
        p = _Pred(role)
        made.append(p)
        return p

    def make_master(k, c2s, s2c, pred, role):
        return (k, c2s, s2c, pred, role)

    planes = build_fleet_planes(
        3, "ipc:///tmp/q-c2s", "ipc:///tmp/q-s2c", make_predictor, make_master
    )
    assert [pl.predictor.role for pl in planes] == [
        "predictor.f0", "predictor.f1", "predictor.f2"
    ]
    assert [pl.master[4] for pl in planes] == [
        "master.f0", "master.f1", "master.f2"
    ]
    # fleet 0 binds the base pair verbatim
    assert planes[0].pipe_c2s == "ipc:///tmp/q-c2s"
    assert planes[1].pipe_c2s == "ipc:///tmp/q-c2s-f1"

    fan = FanoutPredictors([pl.predictor for pl in planes])
    try:
        fan.update_params({"w": 1})
        # the fan-out is asynchronous (per-predictor latest-wins pumps);
        # flush() is the settledness barrier
        assert fan.flush(10.0)
        assert all(len(p.published) == 1 for p in made)
        assert fan.predict_batch(None) == "fleet0-answer"
        assert fan.num_actions == N_ACTIONS
    finally:
        fan.close()

    # single-fleet assembly keeps the legacy role names
    single = build_fleet_planes(
        1, "ipc:///tmp/q1-c2s", "ipc:///tmp/q1-s2c", make_predictor,
        make_master,
    )
    assert single[0].predictor.role == "predictor"
    assert single[0].master[4] == "master"


def test_fanout_publish_nonblocking_under_wedged_predictor():
    """ISSUE 15 satellite: ``FanoutPredictors.update_params`` must never
    block the learner's publish path — a deliberately WEDGED replica
    stalls only its own pump, the healthy replica keeps receiving, and
    when the wedge releases the stalled replica converges to the LATEST
    params (intermediate versions coalesced away, counted)."""
    import threading

    telemetry.reset_all()
    release = threading.Event()

    class _WedgedPred:
        num_actions = N_ACTIONS

        def __init__(self):
            self.published = []

        def update_params(self, params, policy="default"):
            assert release.wait(30), "test wedge never released"
            self.published.append(params)

    class _HealthyPred:
        num_actions = N_ACTIONS

        def __init__(self):
            self.published = []

        def update_params(self, params, policy="default"):
            self.published.append(params)

    wedged, healthy = _WedgedPred(), _HealthyPred()
    fan = FanoutPredictors([wedged, healthy])
    try:
        n = 50
        t0 = time.monotonic()
        for v in range(n):
            fan.update_params({"v": v})
        publish_elapsed = time.monotonic() - t0
        # the learner's thread never waited on the wedge (the old
        # sequential fan-out blocked here for the wedge's full duration)
        assert publish_elapsed < 2.0, (
            f"publish path blocked {publish_elapsed:.1f}s behind a wedged "
            "replica"
        )
        # the healthy replica converges to the latest publish regardless
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if healthy.published and healthy.published[-1] == {"v": n - 1}:
                break
            time.sleep(0.01)
        assert healthy.published[-1] == {"v": n - 1}
        # un-wedge: the stalled replica gets the LATEST params, with the
        # skipped intermediates coalesced (not replayed one by one)
        release.set()
        assert fan.flush(10.0)
        assert wedged.published[-1] == {"v": n - 1}
        assert len(wedged.published) < n
        scal = telemetry.registry("learner").scalars()
        assert scal["fanout_publishes_total"] == n
        assert scal["fanout_publishes_coalesced_total"] > 0
    finally:
        release.set()
        fan.close()


def test_fanout_publish_error_is_loud():
    """A replica whose update_params RAISES must not fail silently inside
    the pump thread: the error is counted AND flight-recorded (the old
    synchronous fan-out propagated the exception to the learner; the
    async pump keeps the evidence loud)."""
    telemetry.reset_all()

    class _BrokenPred:
        num_actions = N_ACTIONS

        def update_params(self, params, policy="default"):
            raise RuntimeError("device OOM during policy device_put")

    class _HealthyPred:
        num_actions = N_ACTIONS

        def __init__(self):
            self.published = []

        def update_params(self, params, policy="default"):
            self.published.append(params)

    healthy = _HealthyPred()
    fan = FanoutPredictors([_BrokenPred(), healthy])
    try:
        fan.update_params({"v": 1})
        assert fan.flush(10.0)
        # the healthy fleet still got the publish
        assert healthy.published == [{"v": 1}]
        scal = telemetry.registry("learner").scalars()
        assert scal["fanout_publish_errors_total"] == 1
        evs = [
            e for e in telemetry.flight_recorder().snapshot()
            if e.get("kind") == "fanout_publish_error"
        ]
        assert len(evs) == 1
        assert evs[0]["fleet"] == 0 and "OOM" in evs[0]["error"]
    finally:
        fan.close()


# ---------------------------------------------------------------------------
# the fleet-merge collator
# ---------------------------------------------------------------------------


def _dp(fleet: int, i: int):
    """A tiny distinguishable [state, action, return] datapoint."""
    return [
        np.full((2, 2), fleet * 100 + i, np.uint8),
        np.int32(fleet),
        np.float32(i),
    ]


def _drain_feed(feed, n, timeout=10.0):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        try:
            out.append(feed.next_batch(timeout=0.2))
        except queue.Empty:
            continue
    assert len(out) == n, f"feed produced {len(out)}/{n} batches"
    return out


def test_fleet_merge_feed_stacked_layout():
    """Stacked mode: fleet k's sub-batch is exactly fleet k's items, on the
    leading fleet axis, in emission order."""
    K, B = 3, 4
    qs = [FastQueue(maxsize=64) for _ in range(K)]
    feed = FleetMergeFeed(qs, B, collate=collate_train, stacked=True)
    for k in range(K):
        for i in range(2 * B):
            qs[k].put(_dp(k, i))
    feed.start()
    try:
        batches = _drain_feed(feed, 2)
    finally:
        feed.stop()
        feed.join(2)
    for b in batches:
        assert b["state"].shape == (K, B, 2, 2)
        assert b["action"].shape == (K, B)
        # fleet k's slice came only from fleet k
        for k in range(K):
            assert (b["action"][k] == k).all()
    # in-order per fleet across batches
    assert list(batches[0]["return"][0]) == [0, 1, 2, 3]
    assert list(batches[1]["return"][0]) == [4, 5, 6, 7]


def test_fleet_merge_feed_no_starvation_under_slow_fleet():
    """One slow fleet: the fast fleets keep being DRAINED (their bounded
    queues don't fill while waiting), and the batch completes as soon as
    the slow fleet delivers — the stream is slowest-fleet-bound, never
    order-deadlocked."""
    K, B = 2, 4
    qs = [FastQueue(maxsize=8) for _ in range(K)]
    feed = FleetMergeFeed(qs, B, collate=collate_train, stacked=True)
    # fast fleet delivers immediately; slow fleet is empty
    for i in range(B):
        qs[0].put(_dp(0, i))
    feed.start()
    try:
        time.sleep(0.2)
        # fast fleet's queue was drained into the holder (not left to
        # back up against its bound) while the slow fleet lags
        assert qs[0].qsize() == 0
        assert feed.qsize() == 0  # no batch yet: fleet 1 owes its share
        for i in range(B):
            qs[1].put(_dp(1, i))
        (batch,) = _drain_feed(feed, 1)
        assert (batch["action"][0] == 0).all()
        assert (batch["action"][1] == 1).all()
    finally:
        feed.stop()
        feed.join(2)


def test_fleet_merge_feed_flat_round_robin():
    """Flat mode: items interleave fairly — with all fleets full, each
    contributes exactly B/K items per batch."""
    K, B = 2, 6
    qs = [FastQueue(maxsize=64) for _ in range(K)]
    feed = FleetMergeFeed(qs, B, collate=collate_train, stacked=False)
    for k in range(K):
        for i in range(6):
            qs[k].put(_dp(k, i))
    feed.start()
    try:
        batches = _drain_feed(feed, 2)
    finally:
        feed.stop()
        feed.join(2)
    for b in batches:
        assert b["action"].shape == (B,)
        counts = {k: int((b["action"] == k).sum()) for k in range(K)}
        assert counts == {0: B // K, 1: B // K}, counts


# ---------------------------------------------------------------------------
# macro-step gradient equivalence (the ISSUE-10 acceptance gate)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def macro_parts():
    cfg = BA3CConfig(
        num_actions=N_ACTIONS, fc_units=32, image_size=(16, 16),
        frame_history=2,
    )
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    # plain SGD isolates GRADIENT equivalence: Adam's g/(sqrt(v)+eps)
    # sign-normalization amplifies bf16-noise-floor differences on
    # near-zero entries into O(lr) param deltas, which would test the
    # optimizer's conditioning, not the accumulation math
    opt = optax.sgd(0.5)
    mesh = make_mesh(num_data=2, num_model=1, devices=jax.devices()[:2])
    state_h = jax.device_get(
        create_train_state(jax.random.PRNGKey(0), model, cfg, opt)
    )
    return cfg, model, opt, mesh, state_h


def _fresh(state_h):
    return jax.tree_util.tree_map(jnp.asarray, state_h)


def _assert_updates_equivalent(state_h, s1, s2, m1, m2, rel_l2=5e-2):
    """Per-leaf relative-L2 on the UPDATES plus tight scalar agreement —
    see the module docstring for why not elementwise allclose."""
    d1 = jax.tree_util.tree_map(
        lambda a, b: np.asarray(a) - np.asarray(b),
        state_h.params, jax.device_get(s1.params),
    )
    d2 = jax.tree_util.tree_map(
        lambda a, b: np.asarray(a) - np.asarray(b),
        state_h.params, jax.device_get(s2.params),
    )
    global_norm = np.sqrt(
        sum(
            float(np.linalg.norm(leaf)) ** 2
            for leaf in jax.tree_util.tree_leaves(d1)
        )
    )
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(d1),
        jax.tree_util.tree_leaves_with_path(d2),
    ):
        err = np.linalg.norm(a - b)
        ref = np.linalg.norm(a)
        # floor on the GLOBAL update norm: a leaf carrying 0.2% of the
        # update (PReLU alpha, a conv bias) may sit entirely at the bf16
        # cancellation noise floor — its own norm is not the right
        # yardstick for noise that small (measured: alpha's reorder noise
        # is 76% of its own norm, 0.2% of the update)
        assert err <= rel_l2 * ref + 3e-3 * global_norm + 1e-6, (
            f"{jax.tree_util.keystr(path)}: |d1-d2|={err:.3e} vs "
            f"{rel_l2} * |d1|={ref:.3e} (global {global_norm:.3e})"
        )
    # scalar agreement: V-trace's clipped-rho/c recursion can switch a
    # clip branch on bf16-noise-perturbed values, so the loss agrees to
    # ~1e-3 relative rather than float ulps (BA3C agrees to ~1e-7)
    assert abs(float(m1["loss"]) - float(m2["loss"])) <= 5e-3 * (
        1 + abs(float(m1["loss"]))
    )
    assert abs(float(m1["grad_norm"]) - float(m2["grad_norm"])) <= 5e-3 * (
        1 + abs(float(m1["grad_norm"]))
    )


def test_macro_train_step_equals_full_batch(macro_parts):
    """K accumulated BA3C fleet sub-batches == ONE [K*B] full-batch update
    (K=4 over a 2-device mesh, so the in-program accumulation scan runs)."""
    cfg, model, opt, mesh, state_h = macro_parts
    K, B = 4, 8
    rng = np.random.default_rng(0)
    batch_k = {
        "state": rng.integers(
            0, 255, (K, B, *cfg.state_shape), dtype=np.uint8
        ),
        "action": rng.integers(0, N_ACTIONS, (K, B)).astype(np.int32),
        "return": rng.normal(size=(K, B)).astype(np.float32),
    }
    flat = {k: v.reshape(K * B, *v.shape[2:]) for k, v in batch_k.items()}
    single = make_train_step(model, opt, cfg, mesh)
    macro = make_macro_train_step(model, opt, cfg, mesh, n_fleets=K)
    s1, m1 = single(_fresh(state_h), flat, 0.01, 1e-3)
    s2, m2 = macro(_fresh(state_h), batch_k, 0.01, 1e-3)
    _assert_updates_equivalent(state_h, s1, s2, m1, m2)


def test_macro_vtrace_step_equals_full_batch(macro_parts):
    """K accumulated V-trace fleet sub-batches == ONE [T, K*B] full-batch
    update — V-trace couples time within an env column, never envs, so
    splitting the env axis across fleets is gradient-exact."""
    cfg, model, opt, mesh, state_h = macro_parts
    K, T, B = 4, 5, 8
    rng = np.random.default_rng(1)
    bk = {
        "state": rng.integers(
            0, 255, (K, T, B, *cfg.state_shape), dtype=np.uint8
        ),
        "action": rng.integers(0, N_ACTIONS, (K, T, B)).astype(np.int32),
        "reward": rng.normal(size=(K, T, B)).astype(np.float32),
        "done": (rng.random((K, T, B)) < 0.1).astype(np.float32),
        "behavior_log_probs": (-rng.random((K, T, B))).astype(np.float32),
        "bootstrap_state": rng.integers(
            0, 255, (K, B, *cfg.state_shape), dtype=np.uint8
        ),
    }
    flat = {
        k: (
            v.reshape(K * B, *v.shape[2:])
            if k == "bootstrap_state"
            # [K,T,B,...] -> [T, K*B, ...] with fleet-major env columns
            else np.moveaxis(v, 0, 2).reshape(T, K * B, *v.shape[3:])
        )
        for k, v in bk.items()
    }
    single = make_vtrace_train_step(model, opt, cfg, mesh)
    macro = make_vtrace_macro_step(model, opt, cfg, mesh, n_fleets=K)
    s1, m1 = single(_fresh(state_h), flat, 0.01, 1e-3)
    s2, m2 = macro(_fresh(state_h), bk, 0.01, 1e-3)
    _assert_updates_equivalent(state_h, s1, s2, m1, m2)


def test_macro_step_rejects_bad_fleet_counts(macro_parts):
    cfg, model, opt, mesh, _ = macro_parts
    with pytest.raises(ValueError, match="divisible"):
        make_macro_train_step(model, opt, cfg, mesh, n_fleets=3)
    with pytest.raises(ValueError, match=">= 1"):
        make_vtrace_macro_step(model, opt, cfg, mesh, n_fleets=0)


def test_overlap_macro_learner_equals_env_concat(macro_parts):
    """fused.macro_learner over K stacked trajectory blocks == the single
    overlap learner over the SAME data concatenated along the env axis —
    the chunked-vs-full equivalence gate extended over the fleet axis."""
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.overlap import (
        TrajBlock,
        make_overlap_step,
    )

    cfg, model, opt, mesh, state_h = macro_parts
    K, T, B = 2, 3, 4
    step = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=T, macro_fleets=K
    )
    assert step.macro_fleets == K and step.macro_learner_jit is not None
    rng = np.random.default_rng(2)

    def block():
        return TrajBlock(
            states=rng.integers(
                0, 255, (T, B, *cfg.state_shape), dtype=np.uint8
            ),
            actions=rng.integers(0, N_ACTIONS, (T, B)).astype(np.int32),
            rewards=rng.normal(size=(T, B)).astype(np.float32),
            dones=(rng.random((T, B)) < 0.1).astype(np.float32),
            behavior_log_probs=(-rng.random((T, B))).astype(np.float32),
            behavior_values=rng.normal(size=(T, B)).astype(np.float32),
            bootstrap_state=rng.integers(
                0, 255, (B, *cfg.state_shape), dtype=np.uint8
            ),
        )

    b1, b2 = block(), block()
    # env axis: axis 1 for [T, B, ...] leaves, axis 0 for bootstrap [B,...]
    concat = TrajBlock(
        states=np.concatenate([b1.states, b2.states], axis=1),
        actions=np.concatenate([b1.actions, b2.actions], axis=1),
        rewards=np.concatenate([b1.rewards, b2.rewards], axis=1),
        dones=np.concatenate([b1.dones, b2.dones], axis=1),
        behavior_log_probs=np.concatenate(
            [b1.behavior_log_probs, b2.behavior_log_probs], axis=1
        ),
        behavior_values=np.concatenate(
            [b1.behavior_values, b2.behavior_values], axis=1
        ),
        bootstrap_state=np.concatenate(
            [b1.bootstrap_state, b2.bootstrap_state], axis=0
        ),
    )
    beta = jnp.float32(0.01)
    lr = jnp.float32(1e-3)
    s1, m1 = step.learner_jit(_fresh(state_h), concat, beta, lr)
    s2, m2 = step.macro_learner_jit(_fresh(state_h), (b1, b2), beta, lr)
    _assert_updates_equivalent(state_h, s1, s2, m1, m2)


def test_overlap_macro_facade_trains(macro_parts):
    """The macro_fleets facade end-to-end on the real on-device env: K
    rollouts per update, metrics finite, step count advances by updates
    (not rollouts)."""
    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import make_overlap_step

    cfg, model, opt, mesh, _ = macro_parts
    # pong's native observation is 84x84; use its own cfg shape
    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=32)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    n_data = mesh.shape["data"]
    n_envs = 2 * n_data
    step = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=3, macro_fleets=2
    )
    state = step.put(
        create_fused_state(
            jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
            n_shards=n_data,
        )
    )
    step0 = int(state.train.step)
    for _ in range(2):
        state, metrics = step(state, cfg.entropy_beta)
    assert int(state.train.step) == step0 + 2  # one UPDATE per facade call
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["grad_norm"]))
    with pytest.raises(NotImplementedError, match="probe_overlap"):
        step.probe_overlap(state, cfg.entropy_beta)


# ---------------------------------------------------------------------------
# experience-stream parity across fleet splits (offline wire drivers, the
# test_block_wire harness idiom)
# ---------------------------------------------------------------------------


def _policy(state: np.ndarray):
    h = int(np.asarray(state, np.uint64).sum())
    return h % N_ACTIONS, (h % 8) / 8.0, -1.25


class _DetPredictor:
    def put_task(self, state, cb, **kw):
        a, v, lp = _policy(state)
        cb(a, v, lp)


def _players(n, seed_base=0):
    return [
        build_fake_player(
            seed_base + i, image_size=(16, 16), frame_history=2,
            num_actions=N_ACTIONS,
        )
        for i in range(n)
    ]


def _drive_per_env(master, players, n_steps, seed_base=0):
    idents = [f"sim-{seed_base + i}".encode() for i in range(len(players))]
    states = [p.current_state() for p in players]
    rewards = [0.0] * len(players)
    overs = [False] * len(players)
    for _ in range(n_steps):
        for j in range(len(players)):
            master._on_message(idents[j], states[j], rewards[j], overs[j])
            a, _, _ = _policy(states[j])
            rewards[j], overs[j] = players[j].action(a)
            states[j] = players[j].current_state()


def _drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def _dp_key(dp):
    state, action, ret = dp
    return (np.asarray(state).tobytes(), int(action), float(ret))


def _seg_key(seg):
    return tuple(
        np.asarray(seg[k]).tobytes()
        for k in (
            "state", "action", "reward", "done", "behavior_log_probs",
            "bootstrap_state",
        )
    )


def test_fleet_split_parity_ba3c(tmp_path):
    """2 fleets x B/2 envs produce the SAME per-env experience multiset as
    1 fleet x B envs (identical env seeds, identical deterministic policy)
    — splitting a fleet is a transport re-arrangement, invisible to the
    learner."""
    B, steps = 6, 40
    kw = dict(gamma=0.5, local_time_max=3)
    one = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/one-c", f"ipc://{tmp_path}/one-s",
        _DetPredictor(), **kw,
    )
    fa = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/fa-c", f"ipc://{tmp_path}/fa-s",
        _DetPredictor(), tele_role="master.f0", **kw,
    )
    fb = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/fb-c", f"ipc://{tmp_path}/fb-s",
        _DetPredictor(), tele_role="master.f1", **kw,
    )
    try:
        _drive_per_env(one, _players(B), steps)
        _drive_per_env(fa, _players(B // 2, seed_base=0), steps, seed_base=0)
        _drive_per_env(
            fb, _players(B // 2, seed_base=B // 2), steps, seed_base=B // 2
        )
        merged = sorted(
            _dp_key(d) for d in (_drain(fa.queue) + _drain(fb.queue))
        )
        single = sorted(_dp_key(d) for d in _drain(one.queue))
        assert merged == single and len(single) > 0
    finally:
        for m in (one, fa, fb):
            m.close()


def test_fleet_split_parity_vtrace(tmp_path):
    B, steps = 6, 40
    kw = dict(unroll_len=4)
    one = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/vone-c", f"ipc://{tmp_path}/vone-s",
        _DetPredictor(), **kw,
    )
    fa = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/vfa-c", f"ipc://{tmp_path}/vfa-s",
        _DetPredictor(), tele_role="master.f0", **kw,
    )
    fb = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/vfb-c", f"ipc://{tmp_path}/vfb-s",
        _DetPredictor(), tele_role="master.f1", **kw,
    )
    try:
        _drive_per_env(one, _players(B), steps)
        _drive_per_env(fa, _players(B // 2, seed_base=0), steps, seed_base=0)
        _drive_per_env(
            fb, _players(B // 2, seed_base=B // 2), steps, seed_base=B // 2
        )
        merged = sorted(
            _seg_key(s) for s in (_drain(fa.queue) + _drain(fb.queue))
        )
        single = sorted(_seg_key(s) for s in _drain(one.queue))
        assert merged == single and len(single) > 0
    finally:
        for m in (one, fa, fb):
            m.close()


def test_fleet_split_parity_through_merge_feed(tmp_path):
    """Same parity, one layer up: the FleetMergeFeed's stacked macro batch
    over 2 fleet queues carries exactly the experience a single TrainFeed
    batch would, as a multiset of (state, action, return) rows."""
    B, steps, sub = 4, 30, 6
    kw = dict(gamma=0.5, local_time_max=3)
    # pass 1: collect the raw per-fleet experience (the reference multiset)
    fa = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/ma-c", f"ipc://{tmp_path}/ma-s",
        _DetPredictor(), tele_role="master.f0", **kw,
    )
    fb = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/mb-c", f"ipc://{tmp_path}/mb-s",
        _DetPredictor(), tele_role="master.f1", **kw,
    )
    try:
        _drive_per_env(fa, _players(B // 2, seed_base=0), steps, seed_base=0)
        _drive_per_env(
            fb, _players(B // 2, seed_base=B // 2), steps, seed_base=B // 2
        )
        raw = [
            _dp_key(d)
            for d in (_drain(fa.queue) + _drain(fb.queue))
        ]
    finally:
        for m in (fa, fb):
            m.close()
    # pass 2: the identical deterministic drive, this time through the
    # merge feed (drives are seed-reproducible, so raw is the reference)
    qa, qb = FastQueue(maxsize=4096), FastQueue(maxsize=4096)
    fa2 = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/m2a-c", f"ipc://{tmp_path}/m2a-s",
        _DetPredictor(), train_queue=qa, tele_role="master.f0", **kw,
    )
    fb2 = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/m2b-c", f"ipc://{tmp_path}/m2b-s",
        _DetPredictor(), train_queue=qb, tele_role="master.f1", **kw,
    )
    try:
        _drive_per_env(fa2, _players(B // 2, seed_base=0), steps, seed_base=0)
        _drive_per_env(
            fb2, _players(B // 2, seed_base=B // 2), steps, seed_base=B // 2
        )
        n_items = qa.qsize() + qb.qsize()
        n_batches = min(qa.qsize(), qb.qsize()) // sub
        feed = FleetMergeFeed(
            [qa, qb], sub, collate=collate_train, stacked=True
        )
        feed.start()
        try:
            batches = _drain_feed(feed, n_batches)
        finally:
            feed.stop()
            feed.join(2)
    finally:
        for m in (fa2, fb2):
            m.close()
    got = []
    for b in batches:
        K = b["state"].shape[0]
        for k in range(K):
            for j in range(sub):
                got.append(
                    (
                        b["state"][k, j].tobytes(),
                        int(b["action"][k, j]),
                        float(b["return"][k, j]),
                    )
                )
    # every collated row is one of the raw datapoints, in multiset terms
    from collections import Counter

    raw_counts = Counter(raw)
    got_counts = Counter(got)
    assert sum((got_counts - raw_counts).values()) == 0, (
        "collator invented rows not present in the raw experience"
    )
    assert len(got) == n_batches * 2 * sub


def test_fast_queue_multi_producer_fairness():
    """N producers against one bounded FastQueue under a slow consumer:
    every producer makes progress (the sleep-poll put has no ticket queue,
    so fairness is statistical — what we pin is NO STARVATION: the least
    served producer lands within a constant factor of its fair share)."""
    import threading

    K, per, bound = 4, 300, 16
    q = FastQueue(maxsize=bound)
    done = threading.Event()
    counts = [0] * K

    def producer(k):
        for i in range(per):
            q.put((k, i), timeout=30)
            counts[k] += 1

    threads = [
        threading.Thread(target=producer, args=(k,), daemon=True)
        for k in range(K)
    ]
    consumed = []

    def consumer():
        while not done.is_set() or q.qsize():
            try:
                consumed.append(q.get(timeout=0.2))
            except queue.Empty:
                continue

    ct = threading.Thread(target=consumer, daemon=True)
    ct.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "a producer starved against the bound"
    done.set()
    ct.join(timeout=10)
    assert len(consumed) == K * per
    per_producer = {k: sum(1 for kk, _ in consumed if kk == k) for k in range(K)}
    assert per_producer == {k: per for k in range(K)}
    # FIFO holds per producer even under contention (deque append is
    # GIL-atomic; a producer's own items can never reorder)
    last = [-1] * K
    for k, i in consumed:
        assert i > last[k]
        last[k] = i


# ---------------------------------------------------------------------------
# per-fleet telemetry identity + cardinality caps
# ---------------------------------------------------------------------------


def test_master_fleet_tele_role(tmp_path):
    m = BA3CSimulatorMaster(
        f"ipc://{tmp_path}/tr-c", f"ipc://{tmp_path}/tr-s",
        _DetPredictor(), tele_role="master.f3",
    )
    try:
        assert m.tele_role == "master.f3"
        assert m._fleet_tele_role == "fleet.f3"
        assert "datapoints_total" in telemetry.registry("master.f3").names()
        snap = m.fleet_snapshot()
        assert snap["queue_maxsize"] > 0
    finally:
        m.close()


def test_fleet_delta_cardinality_caps_with_churning_fleets():
    """8 fleets of churning senders minting fresh series/idents: every
    fleet registry respects the 256-series cap, the GLOBAL ident table
    respects the 4096 cap, and the legitimate instrumentation series
    survive the junk churn in every fleet (roles are trusted — only a
    master's configured tele_role mints one — so the process series total
    is bounded by K x 256 with K operator-chosen)."""
    from distributed_ba3c_tpu.telemetry import wire

    telemetry.reset_all()
    try:
        for k in range(8):
            role = telemetry.fleet_role("fleet", k)
            for sender in range(800):
                ident = f"f{k}-churn-{sender}".encode()
                deltas = {
                    # 400 distinct junk names per fleet — well past the cap
                    f"metric_{k}_{sender % 400}_total": 1,
                    "env_steps_total": 64,
                }
                telemetry.apply_fleet_deltas(ident, deltas, role=role)
        for k in range(8):
            role = telemetry.fleet_role("fleet", k)
            reg = telemetry.registry(role)
            assert len(reg.names()) <= wire._FLEET_MAX_SERIES
            # the cap drops junk, never the known instrumentation series
            assert "env_steps_total" in reg.names()
            assert reg.counter("env_steps_total").value() == 800 * 64
        assert len(wire._FLEET_SEEN) <= wire._FLEET_MAX_SENDERS
        # per-fleet reporting_clients counts only that fleet's senders
        c0 = telemetry.registry("fleet.f0").collect()["reporting_clients"]
        assert 0 < c0["value"] <= 800
    finally:
        telemetry.reset_all()


def test_fleet_sender_table_keeps_colliding_idents_per_fleet():
    """Two fleets' senders sharing an ident (external fleets launched with
    the default cppsim-* prefixes) must count toward BOTH fleets'
    reporting_clients — an ident-keyed table would flap the stored role
    between fleets and corrode both gauges toward zero (review finding)."""
    telemetry.reset_all()
    try:
        for _ in range(3):  # interleaved reports, same ident both fleets
            telemetry.apply_fleet_deltas(
                b"cppsim-0*block", {"env_steps_total": 1}, role="fleet.f0"
            )
            telemetry.apply_fleet_deltas(
                b"cppsim-0*block", {"env_steps_total": 1}, role="fleet.f1"
            )
        for role in ("fleet.f0", "fleet.f1"):
            c = telemetry.registry(role).collect()["reporting_clients"]
            assert c["value"] == 1, (role, c)
    finally:
        telemetry.reset_all()


def test_export_scalars_includes_fleet_roles():
    telemetry.reset_all()
    try:
        telemetry.registry("master.f1").counter("datapoints_total").inc(7)
        telemetry.registry("master").counter("datapoints_total").inc(3)
        out = telemetry.export_scalars(roles=("master",))
        assert out["tele/master/datapoints_total"] == 3
        assert out["tele/master.f1/datapoints_total"] == 7
    finally:
        telemetry.reset_all()


def test_http_signals_addresses_one_fleet():
    from distributed_ba3c_tpu.orchestrate import http_signals

    telemetry.reset_all()
    server = telemetry.TelemetryServer(0, host="127.0.0.1")
    try:
        r0 = telemetry.registry("master.f0")
        r1 = telemetry.registry("master.f1")
        for reg, depth in ((r0, 5), (r1, 11)):
            reg.gauge("train_queue_depth", fn=lambda d=depth: d)
            reg.gauge("train_queue_capacity", fn=lambda: 100)
            reg.counter("queue_blocked_puts_total")
            reg.counter("datapoints_total").inc(1)
            reg.gauge("clients", fn=lambda: 1)
        server.start()
        url = f"http://127.0.0.1:{server.port}"
        s1 = http_signals(url, fleet=1)()
        assert s1["queue_depth"] == 11 and s1["queue_maxsize"] == 100
        s0 = http_signals(url, fleet=0)()
        assert s0["queue_depth"] == 5
        # a typo'd fleet index fails LOUDLY instead of reading all-zeros
        with pytest.raises(KeyError, match="master.f7"):
            http_signals(url, fleet=7)()
        # prometheus text carries the per-fleet role labels
        with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert 'role="master.f1"' in text
        with urllib.request.urlopen(f"{url}/json", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert "master.f0" in doc and "master.f1" in doc
    finally:
        server.stop()
        server.join(2)
        server.close()
        telemetry.reset_all()


# ---------------------------------------------------------------------------
# cli validation (pre-lock usage errors — no jax import on these paths)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "argv",
    [
        ["--fleets", "0"],
        ["--fleets", "2", "--trainer", "tpu_fused_ba3c", "--env", "jax:pong"],
        ["--fleets", "2", "--task", "eval", "--env", "cpp:pong"],
        ["--fleet_accum", "2"],
        ["--fleet_accum", "0", "--overlap", "--trainer", "tpu_fused_ba3c"],
    ],
)
def test_cli_rejects_bad_fleet_flags(argv):
    from distributed_ba3c_tpu import cli

    with pytest.raises(SystemExit):
        cli.main(argv)
