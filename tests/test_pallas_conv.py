"""Pallas fused conv blocks (ops/pallas_conv.py) vs the XLA reference.

Runs in interpreter mode on the CPU backend (the kernel auto-selects
interpret off-TPU), so CI needs no TPU. Perf status (measured slower on
v5e, default off) is documented in the module and PERF.md; these tests pin
CORRECTNESS so the infrastructure stays trustworthy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ba3c_tpu.ops.pallas_conv import (
    ConvSpec,
    ba3c_specs,
    conv_block,
    conv_block_fwd,
    pack_bias,
    pack_weights,
    reference_block,
    supported,
)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def test_ba3c_specs_chain():
    specs = ba3c_specs()
    assert [(s.H, s.W, s.Ci, s.Co) for s in specs] == [
        (84, 84, 4, 32),
        (42, 42, 32, 32),
        (21, 21, 32, 64),
        (10, 10, 64, 64),
    ]
    assert [s.Ho for s in specs] == [42, 21, 10, 10]
    # conv0's P*Ci=16 lane granularity is not Mosaic-compilable; the rest are
    assert [supported(s) for s in specs] == [False, True, True, True]


def test_fwd_matches_reference_all_blocks(rng):
    specs = ba3c_specs()
    x = jnp.asarray(rng.integers(0, 256, (2, 84, 84 * 4), dtype=np.uint8))
    for i, s in enumerate(specs):
        w = jnp.asarray(
            rng.normal(0, 0.1, (s.kh, s.kw, s.Ci, s.Co)), jnp.float32
        )
        b = jnp.asarray(rng.normal(0, 0.05, (s.Co,)), jnp.float32)
        ref = reference_block(x, w, b, s)
        if supported(s):
            got = conv_block_fwd(
                x, pack_weights(w, s), pack_bias(b, s), s, interpret=True
            )
            err = jnp.max(
                jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32))
            )
            scale = jnp.max(jnp.abs(ref.astype(jnp.float32))) + 1e-6
            assert err / scale < 2e-2, (i, float(err), float(scale))
        x = ref  # chain the stack through the reference path


def test_batch_padding(rng):
    """B not divisible by the batch tile pads and trims correctly."""
    s = ba3c_specs()[1]
    B = s.bt + 1
    x = jnp.asarray(
        np.abs(rng.normal(0, 0.5, (B, s.H, s.W * s.Ci))), jnp.bfloat16
    )
    w = jnp.asarray(rng.normal(0, 0.1, (s.kh, s.kw, s.Ci, s.Co)), jnp.float32)
    b = jnp.zeros((s.Co,), jnp.float32)
    got = conv_block_fwd(
        x, pack_weights(w, s), pack_bias(b, s), s, interpret=True
    )
    assert got.shape == (B, s.Ho, s.Wo * s.Co)
    ref = reference_block(x, w, b, s)
    assert float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32)))) < 0.1


def test_model_pallas_backend_value_and_grad(rng):
    """BA3CNet(conv_backend='pallas') matches the XLA model: fwd + grads."""
    from distributed_ba3c_tpu.models.a3c import BA3CNet

    x = jnp.asarray(rng.integers(0, 256, (2, 84, 84, 4), dtype=np.uint8))
    m_x = BA3CNet(num_actions=4)
    m_p = BA3CNet(num_actions=4, conv_backend="pallas")
    params = m_x.init(jax.random.PRNGKey(0), x)["params"]
    # identical param trees (names/shapes interchangeable)
    out_x = m_x.apply({"params": params}, x)
    out_p = m_p.apply({"params": params}, x)
    assert np.allclose(out_x.logits, out_p.logits, atol=0.15), (
        np.max(np.abs(np.asarray(out_x.logits) - np.asarray(out_p.logits)))
    )

    def loss(m, p):
        out = m.apply({"params": p}, x)
        return jnp.sum(out.logits**2) + jnp.sum(out.value**2)

    g_x = jax.grad(lambda p: loss(m_x, p))(params)
    g_p = jax.grad(lambda p: loss(m_p, p))(params)
    key = lambda kv: str(kv[0])  # noqa: E731
    for (kx, vx), (kp, vp) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(g_x), key=key),
        sorted(jax.tree_util.tree_leaves_with_path(g_p), key=key),
        strict=True,
    ):
        scale = np.max(np.abs(np.asarray(vx))) + 1e-3
        assert np.max(np.abs(np.asarray(vx) - np.asarray(vp))) / scale < 0.2, kx
