"""Wire-codec fuzz: mangled frames become typed rejects, never silence.

The ISSUE-13 acceptance contract, pinned at three layers:

1. **codec**: with CRC framing armed, EVERY random truncation or bitflip
   of a valid message — any frame, any offset — raises the typed
   :class:`CorruptFrameError` (or a plain ValueError for structural
   damage). Never any other exception class, and never a successful
   decode whose arrays differ from what was sent (the silently-wrong
   array is the failure mode this whole plane exists to kill). A
   truncated frame must never reach ``frombuffer``.
2. **master receive loop** (block + per-env wires): fuzzed messages on a
   LIVE pipe tick ``corrupt_frames_total`` / ``blocks_rejected_total``
   and the loop keeps serving — a valid message sent after the garbage
   still lands.
3. **pod wires** (params + experience): same contract through
   ``PodIngest`` and the params cache's ``_apply_safe``.
"""

import queue
import time

import numpy as np
import pytest
import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.utils.serialize import (
    CorruptFrameError,
    dumps,
    loads,
    pack_block,
    unpack_block,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


def _mangle(rng, frames):
    """One random truncation or bitflip on one random frame; returns a
    new frame list (always actually different from the input)."""
    frames = [bytes(f) for f in frames]
    candidates = [i for i, f in enumerate(frames) if len(f) > 0]
    i = int(rng.choice(candidates))
    buf = bytearray(frames[i])
    if rng.random() < 0.5 and len(buf) > 1:
        cut = int(rng.integers(0, len(buf)))
        frames[i] = bytes(buf[:cut])
    else:
        pos = int(rng.integers(0, len(buf)))
        buf[pos] ^= 1 << int(rng.integers(0, 8))
        frames[i] = bytes(buf)
    return frames


def _block_frames():
    obs = np.arange(4 * 8 * 6 * 6, dtype=np.uint8).reshape(4, 8, 6, 6)
    rewards = np.linspace(-1, 1, 8).astype(np.float32)
    dones = np.zeros(8, np.uint8)
    return (
        pack_block([b"srv-0", 17, 8], [obs, rewards, dones], crc=True),
        (obs, rewards, dones),
    )


def _shm_frames():
    # the block-shm layout: header + rewards + dones only (obs in the ring)
    rewards = np.ones(4, np.float32)
    dones = np.zeros(4, np.uint8)
    meta = [b"srv-1", 5, 4, "ring", 64, 6, 6, 4]
    return pack_block(meta, [rewards, dones], crc=True), (rewards, dones)


# ---------------------------------------------------------------------------
# layer 1: codec
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("maker", [_block_frames, _shm_frames])
def test_fuzzed_block_frames_always_typed_never_silent(maker):
    rng = np.random.default_rng(0)
    silent_wrong = 0
    for trial in range(400):
        frames, originals = maker()
        frames = [bytes(f) for f in frames]
        bad = _mangle(rng, frames)
        if bad == frames:  # a 0-byte truncation that landed at full length
            continue
        try:
            meta, arrays = unpack_block(bad)
        except (CorruptFrameError, ValueError):
            continue  # typed reject: the contract
        except Exception as e:  # noqa: BLE001 — the assertion IS the test
            pytest.fail(f"trial {trial}: non-typed escape {type(e).__name__}: {e}")
        # decode succeeded despite the mangle: every array must still be
        # byte-identical or the codec silently served wrong data
        for got, want in zip(arrays, originals):
            if got.tobytes() != want.tobytes():
                silent_wrong += 1
    assert silent_wrong == 0


def test_truncated_frame_never_reaches_frombuffer():
    """The acceptance bullet verbatim: cut the obs frame anywhere and the
    reject happens at CRC level — unpack_block must not build a view."""
    frames, _ = _block_frames()
    frames = [bytes(f) for f in frames]
    for cut in (0, 1, len(frames[1]) // 2, len(frames[1]) - 1):
        bad = list(frames)
        bad[1] = frames[1][:cut]
        with pytest.raises((CorruptFrameError, ValueError)):
            unpack_block(bad)


def test_fuzzed_single_frame_payloads_typed():
    rng = np.random.default_rng(1)
    msg = [b"sim-3", np.arange(64, dtype=np.uint8).reshape(8, 8), 0.5, False]
    for _ in range(300):
        payload = dumps(msg, crc=True)
        (bad,) = _mangle(rng, [payload])
        if bad == payload:
            continue
        try:
            out = loads(bad)
        except (CorruptFrameError, ValueError):
            continue
        except Exception as e:  # msgpack's own hierarchy is NOT typed
            # the receive loops catch broad Exception for exactly this
            # reason; the codec itself may surface msgpack errors only
            # when the CRC prefix was itself destroyed
            assert "msgpack" in type(e).__module__, (
                f"unexpected escape {type(e).__name__}: {e}"
            )
            continue
        assert np.asarray(out[1]).tobytes() == np.asarray(msg[1]).tobytes()


def test_crc_off_frames_still_parse_at_crc_aware_receiver():
    obs = np.zeros((2, 2), np.uint8)
    frames = pack_block([b"x", 1, 2], [obs], crc=False)
    meta, arrays = unpack_block([bytes(f) for f in frames])
    assert meta[0] == b"x" and arrays[0].shape == (2, 2)
    assert loads(dumps([1, 2, 3], crc=False)) == [1, 2, 3]


# ---------------------------------------------------------------------------
# layer 2: the master's live receive loop
# ---------------------------------------------------------------------------

class _FuzzMaster:
    """Minimal concrete master over the real SimulatorMaster loop."""

    def __new__(cls, *a, **k):
        from distributed_ba3c_tpu.actors.simulator import SimulatorMaster

        class Impl(SimulatorMaster):
            def __init__(self, c2s, s2c):
                super().__init__(c2s, s2c)
                self.seen = queue.Queue()

            def _on_state(self, state, ident):
                self.seen.put(("per-env", bytes(ident)))

            def _on_episode_over(self, ident):
                pass

            def _on_datapoint(self, ident):
                pass

            def _on_block_state(self, states, ident):
                self.seen.put(("block", bytes(ident)))

            def _on_block_flush(self, ident):
                pass

        return Impl(*a, **k)


def test_master_loop_survives_fuzz_and_counts_typed_rejects(tmp_path):
    rng = np.random.default_rng(7)
    c2s = f"ipc://{tmp_path}/c2s"
    s2c = f"ipc://{tmp_path}/s2c"
    master = _FuzzMaster(c2s, s2c)
    master.start()
    ctx = zmq.Context()
    push = ctx.socket(zmq.PUSH)
    push.setsockopt(zmq.LINGER, 0)
    push.connect(c2s)
    tele = telemetry.registry("master")
    try:
        time.sleep(0.2)
        # fuzzed BLOCK messages + fuzzed PER-ENV messages, interleaved
        n_bad = 0
        for i in range(60):
            if i % 2 == 0:
                frames, _ = _block_frames()
                bad = _mangle(rng, [bytes(f) for f in frames])
            else:
                payload = dumps(
                    [b"sim-9", np.zeros((4, 4), np.uint8), 0.0, False],
                    crc=True,
                )
                bad = _mangle(rng, [payload])
            push.send_multipart(bad)
            n_bad += 1
        # then one VALID message of each wire mode: the loop must still
        # be alive and serving
        frames, _ = _block_frames()
        push.send_multipart([bytes(f) for f in frames])
        push.send_multipart([
            dumps([b"sim-9", np.zeros((4, 4), np.uint8), 0.0, False],
                  crc=True)
        ])
        got = {master.seen.get(timeout=10)[0] for _ in range(2)}
        assert got == {"block", "per-env"}
        s = tele.scalars()
        typed = (
            s.get("corrupt_frames_total", 0)
            + s.get("blocks_rejected_total", 0)
        )
        # every fuzzed message either was typed-rejected or (rarely, for
        # per-env flips that dodge the reject by mangling only meta
        # fields the loop tolerates) processed without effect — but MOST
        # must land in the typed counters, and corruption specifically
        # must be represented
        assert typed >= n_bad * 0.8, s
        assert s.get("corrupt_frames_total", 0) > 0, s
    finally:
        push.close(0)
        ctx.term()
        master.stop()
        master.close()


# ---------------------------------------------------------------------------
# layer 3: the pod wires
# ---------------------------------------------------------------------------

def test_pod_ingest_survives_fuzz_and_counts_typed_rejects(tmp_path):
    from distributed_ba3c_tpu.pod import PodIngest, pack_experience
    from distributed_ba3c_tpu.pod.wire import pod_endpoints

    rng = np.random.default_rng(11)
    eps = pod_endpoints(f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c")
    ingest = PodIngest(eps, depth=8)
    ingest.start()
    ctx = zmq.Context()
    push = ctx.socket(zmq.PUSH)
    push.setsockopt(zmq.LINGER, 0)
    push.connect(eps.experience)

    def batch(T=2, B=3, H=6):
        return {
            "state": np.zeros((T, B, H, H, 4), np.uint8),
            "action": np.zeros((T, B), np.int32),
            "reward": np.zeros((T, B), np.float32),
            "done": np.zeros((T, B), np.float32),
            "behavior_log_probs": np.zeros((T, B), np.float32),
            "behavior_values": np.zeros((T, B), np.float32),
            "bootstrap_state": np.zeros((B, H, H, 4), np.uint8),
        }

    try:
        time.sleep(0.2)
        for _ in range(40):
            frames = pack_experience(0, 3, batch(), {}, epoch=1, crc=True)
            push.send_multipart(_mangle(rng, [bytes(f) for f in frames]))
        push.send_multipart(
            [bytes(f) for f in
             pack_experience(0, 3, batch(), {}, epoch=1, crc=True)]
        )
        stamped = None
        deadline = time.monotonic() + 10
        while stamped is None and time.monotonic() < deadline:
            stamped = ingest.next_batch(timeout=0.5)
        assert stamped is not None and stamped.version == 3  # loop alive
        s = telemetry.registry("learner").scalars()
        typed = (
            s.get("pod_corrupt_frames_total", 0)
            + s.get("pod_ingest_rejected_total", 0)
        )
        assert typed >= 40 * 0.8, s
        assert s.get("pod_corrupt_frames_total", 0) > 0, s
    finally:
        push.close(0)
        ctx.term()
        ingest.close()


def test_params_cache_apply_safe_counts_corrupt_and_malformed():
    from distributed_ba3c_tpu.pod import StaleParamsCache
    from distributed_ba3c_tpu.pod.wire import pack_params, pod_endpoints

    rng = np.random.default_rng(13)
    eps = pod_endpoints("ipc:///tmp/ba3c-fuzz-c2s", "ipc:///tmp/ba3c-fuzz-s2c")
    cache = StaleParamsCache(eps, host=0)  # never started: _apply_safe only
    try:
        payload = pack_params(
            4, {"w": np.arange(8, dtype=np.float32)}, epoch=9, crc=True
        )
        applied = typed = 0
        for _ in range(200):
            (bad,) = _mangle(rng, [payload])
            if cache._apply_safe(bad):
                applied += 1  # mangle landed somewhere harmless? count it
        s = telemetry.registry("pod.host0").scalars()
        typed = (
            s.get("params_corrupt_total", 0)
            + s.get("params_malformed_total", 0)
        )
        assert applied == 0  # a mangled snapshot must NEVER apply
        assert typed == 200, s
        assert s.get("params_corrupt_total", 0) > 0, s
        # and a clean payload still applies after all that abuse
        assert cache._apply_safe(payload) is True
        assert cache.version == 4 and cache.epoch == 9
    finally:
        cache.close()
