"""The pod parameter plane (distributed_ba3c_tpu/pod/, docs/pod.md).

The contracts this suite pins (ISSUE 11 acceptance):

- wire: endpoint derivation from the fleet port map, version-stamp
  round-trips for both channels.
- params plane: publisher broadcast + late-joiner fetch with retry, the
  cache's immediate-callback contract, rejoin at the CURRENT version
  after a (simulated) host respawn.
- ingest: stamped delivery, drop-oldest under the depth bound (actor
  hosts never backpressured by a slow learner), the per-host
  ``pod.host<k>`` telemetry mirror.
- measured-lag V-trace: lag-0 through the pod path stays BIT-EXACT vs
  the fused step (the overlap parity contract, extended); lag-k updates
  equal an oracle recomputation from the recorded block alone (the
  correction reads measured behavior data, never an assumed lag); the
  recorded behavior log-probs ARE the stale policy's (recomputation from
  the old snapshot matches).
- bounded staleness: the learner gate rejects past ``max_staleness`` with
  the typed counter and KEEPS CONSUMING; the host-side
  VersionGatedPredictor sheds with the masters' uniform fallback so a
  lockstep server always gets its action reply (never wedges in recv).
- e2e (slow): a real 2-host localhost pod trains, survives a host-loss
  SIGKILL without a learner restart, and the killed host rejoins at the
  current version.
"""

import queue
import tempfile
import time

import jax
import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.jaxenv import pong
from distributed_ba3c_tpu.fused.loop import create_fused_state, make_fused_step
from distributed_ba3c_tpu.fused.overlap import make_overlap_step
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import make_mesh
from distributed_ba3c_tpu.pod import (
    LaggedBlockDriver,
    ParamsPublisher,
    PodIngest,
    PodLearner,
    StaleParamsCache,
    StalenessGate,
    VersionGatedPredictor,
    batch_to_block,
    make_pod_learner_step,
    pack_experience,
    pack_params,
    pod_endpoints,
    pod_role,
    unpack_experience,
    unpack_params,
)
from distributed_ba3c_tpu.pod.ingest import StampedBatch


# ---------------------------------------------------------------------------
# wire
# ---------------------------------------------------------------------------

def test_pod_endpoints_tcp_derivation():
    eps = pod_endpoints("tcp://10.0.0.1:5555", "tcp://10.0.0.1:5556")
    assert eps.params_pub == "tcp://10.0.0.1:5655"
    assert eps.params_fetch == "tcp://10.0.0.1:5656"
    assert eps.experience == "tcp://10.0.0.1:5657"


def test_pod_endpoints_ipc_suffixes():
    eps = pod_endpoints("ipc:///tmp/x/c2s", "ipc:///tmp/x/s2c")
    assert eps.params_pub.endswith("-pod-pub")
    assert eps.params_fetch.endswith("-pod-fetch")
    assert eps.experience.endswith("-pod-exp")
    assert len({eps.params_pub, eps.params_fetch, eps.experience}) == 3


def test_pod_endpoints_fleet_collision_refused():
    # 50+ fleets would stride into the pod port band — fail at derivation
    with pytest.raises(ValueError):
        pod_endpoints("tcp://h:5555", "tcp://h:5556", n_fleets=64)


def test_pod_role_formula():
    assert pod_role(0) == "pod.host0"
    assert pod_role(3) == "pod.host3"


def test_params_roundtrip_preserves_tree_and_version():
    params = {
        "conv": {"kernel": np.arange(12, dtype=np.float32).reshape(3, 4)},
        "head": {"bias": np.ones(5, np.float32)},
    }
    epoch, v, step, out = unpack_params(
        pack_params(7, params, step=42, epoch=99)
    )
    assert (epoch, v, step) == (99, 7, 42)
    np.testing.assert_array_equal(out["conv"]["kernel"], params["conv"]["kernel"])
    np.testing.assert_array_equal(out["head"]["bias"], params["head"]["bias"])
    # the unpacked arrays OWN their memory (they outlive the zmq frame)
    assert out["conv"]["kernel"].flags["OWNDATA"]


def _batch(T=3, B=4, H=8):
    return {
        "state": np.random.randint(0, 255, (T, B, H, H, 4), dtype=np.uint8),
        "action": np.random.randint(0, 4, (T, B)).astype(np.int32),
        "reward": np.random.randn(T, B).astype(np.float32),
        "done": np.zeros((T, B), np.float32),
        "behavior_log_probs": np.random.randn(T, B).astype(np.float32),
        "behavior_values": np.random.randn(T, B).astype(np.float32),
        "bootstrap_state": np.random.randint(
            0, 255, (B, H, H, 4), dtype=np.uint8
        ),
    }


def test_experience_roundtrip_stamp_and_arrays():
    batch = _batch()
    frames = pack_experience(2, 9, batch, {"env_steps_total": 11.0}, epoch=5)
    # simulate the wire: frames arrive as bytes
    host, epoch, version, scalars, out = unpack_experience(
        [bytes(f) for f in frames]
    )
    assert (host, epoch, version) == (2, 5, 9)
    assert scalars == {"env_steps_total": 11.0}
    for k, v in batch.items():
        np.testing.assert_array_equal(out[k], v)


def test_experience_missing_key_refused():
    batch = _batch()
    del batch["behavior_values"]
    with pytest.raises(ValueError):
        pack_experience(0, 0, batch)


# ---------------------------------------------------------------------------
# params plane: publisher <-> cache
# ---------------------------------------------------------------------------

@pytest.fixture
def ipc_endpoints(tmp_path):
    return pod_endpoints(f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c")


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_cache_fetches_before_any_broadcast(ipc_endpoints):
    """The late-joiner path: a cache started while the publisher holds
    nothing retries with backoff and lands on the first publish."""
    pub = ParamsPublisher(ipc_endpoints)
    pub.start()
    cache = StaleParamsCache(
        ipc_endpoints, host=0, fetch_backoff_s=0.05, fetch_backoff_max_s=0.2
    )
    cache.start()
    try:
        assert not cache.wait_first(0.3)  # nothing published yet
        pub.publish(0, {"w": np.zeros(2, np.float32)})
        assert cache.wait_first(10)
        assert cache.version == 0
    finally:
        cache.close()
        pub.close()


def test_cache_applies_broadcasts_and_fires_callbacks(ipc_endpoints):
    pub = ParamsPublisher(ipc_endpoints)
    pub.start()
    pub.publish(0, {"w": np.zeros(2, np.float32)})
    cache = StaleParamsCache(ipc_endpoints, host=0)
    cache.start()
    try:
        assert cache.wait_first(10)
        seen = []
        cache.on_update(lambda p, v: seen.append(v))
        # registered after the first version: fires immediately with it
        assert seen == [0]
        pub.publish(1, {"w": np.ones(2, np.float32)})
        assert _wait(lambda: cache.version == 1)
        assert seen == [0, 1]
        np.testing.assert_array_equal(cache.params["w"], np.ones(2, np.float32))
        assert cache.behind() == 0
    finally:
        cache.close()
        pub.close()


def test_cache_adopts_new_epoch_despite_lower_version(ipc_endpoints):
    """A restarted learner's versions regress to 0 under a FRESH epoch:
    surviving caches must adopt the new lineage instead of silently
    dropping every 'older' broadcast forever (the wedge a version-only
    stamp cannot detect)."""
    pub1 = ParamsPublisher(ipc_endpoints, epoch=111)
    pub1.start()
    for v in range(4):
        pub1.publish(v, {"w": np.full(2, float(v), np.float32)})
    cache = StaleParamsCache(ipc_endpoints, host=0, fetch_backoff_s=0.05)
    cache.start()
    try:
        assert cache.wait_first(10)
        assert (cache.epoch, cache.version) == (111, 3)
        # the learner restarts: same endpoints, NEW epoch, version 0
        pub1.close()
        pub2 = ParamsPublisher(ipc_endpoints, epoch=222)
        pub2.start()
        try:
            # publish REPEATEDLY, like a live learner: the cache's SUB
            # needs a reconnect interval to find the rebound endpoint,
            # and PUB drops broadcasts sent before a subscriber attaches
            deadline = time.monotonic() + 10
            while cache.epoch != 222 and time.monotonic() < deadline:
                pub2.publish(0, {"w": np.full(2, 42.0, np.float32)})
                time.sleep(0.1)
            assert cache.epoch == 222
            assert cache.version == 0
            np.testing.assert_array_equal(
                cache.params["w"], np.full(2, 42.0, np.float32)
            )
        finally:
            pub2.close()
    finally:
        cache.close()


def test_cache_retry_backoff_ceiling_against_unreachable_publisher(
    ipc_endpoints,
):
    """ISSUE-13 satellite: the PR-12 retry path, partition-shaped. Against
    an endpoint where NOTHING answers, the fetch retries with backoff up
    to the ceiling and no further — bounded probing, not hammering — and
    nothing on the serving surface ever blocks."""
    cache = StaleParamsCache(
        ipc_endpoints, host=0,
        fetch_backoff_s=0.05, fetch_backoff_max_s=0.2,
    )
    cache.start()
    try:
        time.sleep(1.3)
        retries = telemetry.registry("pod.host0").scalars()[
            "params_fetch_retries_total"
        ]
        # doubling 0.05 -> cap 0.2 gives ~8 attempts in 1.3 s; a flat
        # 0.05 cadence (no backoff) would give ~26, a stuck loop 0. The
        # band proves BOTH halves: it keeps retrying AND the ceiling is
        # respected.
        assert 3 <= retries <= 14, retries
        # rollout-facing surface never blocks on the dead publisher
        t0 = time.monotonic()
        assert cache.params is None
        assert cache.behind() == 0  # nothing seen -> no measurable lag
        assert not cache.wait_first(0.05)
        assert time.monotonic() - t0 < 0.5
    finally:
        cache.close()


def test_cache_rejoins_current_epoch_when_publisher_heals(ipc_endpoints):
    """Unreachable-then-healed: the publisher that finally appears is a
    NEW lifetime (fresh epoch, versions from 0) — the rejoining cache
    must adopt it through the retrying fetch path."""
    cache = StaleParamsCache(
        ipc_endpoints, host=0,
        fetch_backoff_s=0.05, fetch_backoff_max_s=0.2,
    )
    cache.start()
    try:
        assert not cache.wait_first(0.5)  # provably unreachable first
        pub = ParamsPublisher(ipc_endpoints, epoch=333)
        pub.start()
        pub.publish(7, {"w": np.full(2, 7.0, np.float32)})
        try:
            assert cache.wait_first(10)  # the RETRY landed, no restart
            assert (cache.epoch, cache.version) == (333, 7)
            np.testing.assert_array_equal(
                cache.params["w"], np.full(2, 7.0, np.float32)
            )
        finally:
            pub.close()
    finally:
        cache.close()


def test_cache_degraded_broadcast_channel_probes_fetch(ipc_endpoints):
    """Asymmetric-partition self-heal: when the SUB channel goes silent
    past its degraded threshold, the cache re-arms the bounded-backoff
    fetch even though it HOLDS params — and catches up to versions it
    never saw broadcast."""
    pub = ParamsPublisher(ipc_endpoints)
    pub.start()
    cache = StaleParamsCache(
        ipc_endpoints, host=0,
        fetch_backoff_s=0.05, fetch_backoff_max_s=0.2,
        heartbeat_s=0.1, degraded_after_s=0.3, partitioned_after_s=2.0,
    )
    cache.start()
    try:
        pub.publish(1, {"w": np.zeros(2, np.float32)})
        assert cache.wait_first(10)
        assert _wait(lambda: cache.version == 1)
        # "lose" the broadcast: arm the fetch channel's latest WITHOUT a
        # PUB send — exactly a dead broadcast path with a live ROUTER
        pub._latest = None
        from distributed_ba3c_tpu.pod.wire import pack_params

        pub._latest = pack_params(2, {"w": np.ones(2, np.float32)}, epoch=pub.epoch)
        # past degraded_after_s the cache must probe the fetch channel and
        # adopt the version the broadcast never delivered
        assert _wait(lambda: cache.version == 2, timeout=10)
        from distributed_ba3c_tpu.pod.linkstate import UP

        assert cache.fetch_link.poll() == UP  # side-channel alive
    finally:
        cache.close()
        pub.close()


def test_learner_rejects_foreign_epoch_blocks(pod_parts, ipc_endpoints):
    """A block stamped under a publisher lifetime the learner does not
    own carries a version from the wrong lineage — typed rejection (the
    clamped lag would otherwise read 0 and admit it silently)."""
    cfg, model, opt, mesh, pstep = pod_parts
    pub = ParamsPublisher(ipc_endpoints, epoch=7)
    try:
        learner = PodLearner(
            pstep, _fresh_train(cfg, model, opt), cfg, publisher=pub,
            max_staleness=4,
        )
        foreign = StampedBatch(0, 0, _pong_batch(cfg), epoch=1234)
        assert learner.consume(foreign) is None
        assert (
            telemetry.registry("learner")
            .counter("epoch_mismatch_blocks_total").value() >= 1
        )
        ours = StampedBatch(0, 0, _pong_batch(cfg), epoch=7)
        assert learner.consume(ours) is not None
    finally:
        pub.close()


def test_respawned_cache_rejoins_at_current_version(ipc_endpoints):
    """The host-loss recovery contract: a brand-new cache (the respawned
    host) fetches the CURRENT version, not a replay from zero."""
    pub = ParamsPublisher(ipc_endpoints)
    pub.start()
    for v in range(5):
        pub.publish(v, {"w": np.full(2, float(v), np.float32)})
    rejoined = StaleParamsCache(ipc_endpoints, host=1, fetch_backoff_s=0.05)
    rejoined.start()
    try:
        assert rejoined.wait_first(10)
        assert rejoined.version == 4
        np.testing.assert_array_equal(
            rejoined.params["w"], np.full(2, 4.0, np.float32)
        )
    finally:
        rejoined.close()
        pub.close()


# ---------------------------------------------------------------------------
# ingest
# ---------------------------------------------------------------------------

class _Pusher:
    """One persistent PUSH socket: ordering across sends is guaranteed
    (separate sockets would interleave arbitrarily at the PULL side),
    and the default linger flushes every message before close."""

    def __init__(self, eps):
        import zmq

        self._ctx = zmq.Context()
        self.sock = self._ctx.socket(zmq.PUSH)
        self.sock.connect(eps.experience)

    def send(self, host, version, batch, scalars=None):
        self.sock.send_multipart(pack_experience(host, version, batch, scalars))

    def close(self):
        self.sock.close()
        self._ctx.term()


def test_ingest_stamped_delivery_and_host_mirror(ipc_endpoints):
    telemetry.reset_all()
    ing = PodIngest(ipc_endpoints, depth=4)
    ing.start()
    push = _Pusher(ipc_endpoints)
    try:
        push.send(
            3, 17, _batch(),
            {"env_steps_total": 99.0, "params_version": 17.0},
        )
        sb = ing.next_batch(timeout=10)
        assert sb is not None and (sb.host, sb.version) == (3, 17)
        assert sb.batch["state"].shape[0] == 3  # time-major [T, B]
        mirror = telemetry.registry(pod_role(3)).scalars()
        assert mirror["env_steps_total"] == 99.0
        assert mirror["params_version"] == 17.0
    finally:
        push.close()
        ing.close()


def test_ingest_drop_oldest_never_blocks_hosts(ipc_endpoints):
    telemetry.reset_all()
    ing = PodIngest(ipc_endpoints, depth=2)
    ing.start()
    push = _Pusher(ipc_endpoints)
    try:
        for v in range(5):
            push.send(0, v, _batch())
        assert _wait(
            lambda: telemetry.registry("learner")
            .counter("pod_ingest_blocks_total").value() == 5
        )
        assert _wait(lambda: ing.qsize() == 2)
        dropped = telemetry.registry("learner").counter(
            "pod_ingest_dropped_total"
        ).value()
        assert dropped == 3
        # the survivors are the NEWEST stamps
        versions = [ing.next_batch(timeout=2).version for _ in range(2)]
        assert versions == [3, 4]
    finally:
        push.close()
        ing.close()


def test_export_scalars_carries_pod_host_roles():
    telemetry.reset_all()
    telemetry.registry(pod_role(0)).gauge("params_version").set(5)
    telemetry.registry(pod_role(1)).counter("env_steps_total").inc(7)
    out = telemetry.export_scalars()
    assert out["tele/pod.host0/params_version"] == 5.0
    assert out["tele/pod.host1/env_steps_total"] == 7.0


def test_bench_role_scalars_sums_pod_hosts():
    from bench import _role_scalars

    telemetry.reset_all()
    telemetry.registry(pod_role(0)).counter("env_steps_total").inc(3)
    telemetry.registry(pod_role(1)).counter("env_steps_total").inc(4)
    assert _role_scalars("pod")["env_steps_total"] == 7.0


# ---------------------------------------------------------------------------
# the staleness gate
# ---------------------------------------------------------------------------

def test_gate_measures_and_bounds():
    telemetry.reset_all()
    gate = StalenessGate(max_staleness=2)
    assert gate.admit(5, 5) == 0
    assert gate.admit(3, 5) == 2
    assert gate.admit(2, 5) is None  # lag 3 > bound 2: typed rejection
    s = telemetry.registry("learner").scalars()
    assert s["stale_blocks_rejected_total"] == 1
    assert s["params_lag_count"] == 3  # rejected blocks are still measured
    assert s["pod_max_staleness"] == 2


def test_gate_unbounded_measures_only():
    telemetry.reset_all()
    gate = StalenessGate(max_staleness=None)
    assert gate.admit(0, 1000) == 1000
    assert (
        telemetry.registry("learner")
        .counter("stale_blocks_rejected_total").value() == 0
    )


def test_learner_rejection_keeps_consuming(pod_parts):
    """A burst of over-stale blocks must not wedge the consuming loop:
    rejects return None (counted) and the next fresh block still trains."""
    cfg, model, opt, mesh, pstep = pod_parts
    learner = PodLearner(pstep, _fresh_train(cfg, model, opt), cfg,
                         max_staleness=1)
    learner.version = 10
    stale = StampedBatch(0, 2, _pong_batch(cfg))  # lag 8 >> 1
    assert learner.consume(stale) is None
    assert learner.version == 10  # rejected: no update happened
    fresh = StampedBatch(0, 10, _pong_batch(cfg))
    assert learner.consume(fresh) is not None
    assert learner.version == 11


# ---------------------------------------------------------------------------
# the pod learner step: parity + oracle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pod_parts():
    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon,
                         cfg.grad_clip_norm)
    mesh = make_mesh()
    pstep = make_pod_learner_step(model, opt, cfg, mesh)
    return cfg, model, opt, mesh, pstep


def _fresh_train(cfg, model, opt, seed=0):
    from distributed_ba3c_tpu.parallel.train_step import create_train_state

    return create_train_state(jax.random.PRNGKey(seed), model, cfg, opt)


def _pong_batch(cfg, T=3, B=16, seed=0):
    """A host-shaped random batch at pong's action space (collate layout)."""
    rng = np.random.default_rng(seed)
    H, W, C = cfg.state_shape
    return {
        "state": rng.integers(0, 255, (T, B, H, W, C), dtype=np.uint8),
        "action": rng.integers(0, cfg.num_actions, (T, B)).astype(np.int32),
        "reward": rng.standard_normal((T, B)).astype(np.float32),
        "done": (rng.random((T, B)) < 0.05).astype(np.float32),
        "behavior_log_probs": -np.abs(
            rng.standard_normal((T, B))
        ).astype(np.float32),
        "behavior_values": rng.standard_normal((T, B)).astype(np.float32),
        "bootstrap_state": rng.integers(
            0, 255, (B, H, W, C), dtype=np.uint8
        ),
    }


def test_batch_to_block_coerces_dtypes(pod_parts):
    cfg, _, _, _, pstep = pod_parts
    b = _pong_batch(cfg)
    b["action"] = b["action"].astype(np.int64)
    b["reward"] = b["reward"].astype(np.float64)
    block = batch_to_block(b, pstep.block_sharding)
    assert block.actions.dtype == np.int32
    assert block.rewards.dtype == np.float32
    assert block.states.dtype == np.uint8


@pytest.fixture(scope="module")
def overlap_parts(pod_parts):
    cfg, model, opt, mesh, _ = pod_parts
    n_envs = 2 * mesh.shape["data"]
    ostep = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=3,
                              lag=0)

    def fresh_state(putter):
        return putter(
            create_fused_state(
                jax.random.PRNGKey(0), model, cfg, opt, pong, n_envs,
                n_shards=mesh.shape["data"],
            )
        )

    return ostep, fresh_state, n_envs


def test_lag0_pod_path_bitexact_with_fused(pod_parts, overlap_parts):
    """THE acceptance parity: the pod path at lag 0 with frozen params is
    bit-exact with the fused step over a K-window — same trajectories,
    frame stacks, env carries (the overlap parity contract, driven
    through LaggedBlockDriver + the pod.learner program)."""
    cfg, model, opt, mesh, pstep = pod_parts
    ostep, fresh_state, n_envs = overlap_parts
    K = 4
    fstep = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=3)
    f = fresh_state(fstep.put)
    learner = PodLearner(pstep, _fresh_train(cfg, model, opt), cfg)
    learner.learning_rate = 0.0
    drv = LaggedBlockDriver(ostep, learner, lag=0)
    drv.prime(fresh_state(ostep.put))
    for _ in range(K):
        f, _ = fstep(f, cfg.entropy_beta, learning_rate=0.0)
        m = drv.iterate()
        assert m is not None
    assert learner.version == K
    np.testing.assert_array_equal(
        np.asarray(f.obs_stack), np.asarray(drv.astate.obs_stack)
    )
    for fl, ol in zip(
        jax.tree_util.tree_leaves(f.env_state),
        jax.tree_util.tree_leaves(drv.astate.env_state),
    ):
        np.testing.assert_array_equal(np.asarray(fl), np.asarray(ol))
    np.testing.assert_array_equal(
        np.asarray(f.ep_count), np.asarray(drv.astate.ep_count)
    )
    # at lag 0 the correction is the identity
    assert abs(float(m["mean_rho"]) - 1.0) < 1e-5


def test_lag0_pod_update_matches_fused_math(pod_parts, overlap_parts):
    """One LIVE update from identical state lands on the fused step's
    params up to float reassociation (the learning-math half)."""
    cfg, model, opt, mesh, pstep = pod_parts
    ostep, fresh_state, _ = overlap_parts
    fstep = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=3)
    f, mf = fstep(fresh_state(fstep.put), cfg.entropy_beta)
    learner = PodLearner(pstep, _fresh_train(cfg, model, opt), cfg)
    drv = LaggedBlockDriver(ostep, learner, lag=0)
    drv.prime(fresh_state(ostep.put))
    mo = drv.iterate()
    for fl, ol in zip(
        jax.tree_util.tree_leaves(f.train.params),
        jax.tree_util.tree_leaves(learner.state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(fl), np.asarray(ol), rtol=2e-4, atol=2e-5
        )
    for k in ("loss", "policy_loss", "value_loss", "entropy"):
        assert abs(float(mf[k]) - float(mo[k])) < 5e-4, k


def test_recorded_behavior_log_probs_are_the_stale_policys(pod_parts,
                                                           overlap_parts):
    """At measured lag k the correction inputs are EXACT: the block's
    recorded behavior log-probs equal a recomputation under the stale
    snapshot that served the rollout (nothing is approximated away by
    growing lag — the property that makes the correction exact at any k).
    """
    cfg, model, opt, mesh, pstep = pod_parts
    ostep, fresh_state, _ = overlap_parts
    learner = PodLearner(pstep, _fresh_train(cfg, model, opt), cfg)
    drv = LaggedBlockDriver(ostep, learner, lag=2)
    drv.prime(fresh_state(ostep.put))
    for _ in range(4):  # fill the snapshot ring past the warmup ramp
        drv.iterate()
    stale_version, stale_params = drv._snaps[0]
    # genuinely stale: the ring's oldest snapshot trails the learner by
    # the configured lag (plus one — version advanced after its last use)
    assert learner.version - stale_version >= 2
    astate, block = drv.actor_jit(stale_params, drv.astate)
    drv.astate = astate
    T, B = block.actions.shape
    states = np.asarray(block.states).reshape(T * B, *cfg.state_shape)
    out = model.apply({"params": stale_params}, states)
    lp = jax.nn.log_softmax(out.logits, axis=-1)
    recomputed = np.take_along_axis(
        np.asarray(lp), np.asarray(block.actions).reshape(T * B, 1), axis=1
    ).reshape(T, B)
    np.testing.assert_allclose(
        recomputed, np.asarray(block.behavior_log_probs),
        rtol=1e-5, atol=1e-5,
    )


def test_lagk_update_matches_oracle_recomputation():
    """The lag-k correction equals an oracle that recomputes V-trace +
    Adam directly from the recorded block (plain jax, no shard_map): the
    pod update is a pure function of (current params, recorded data) —
    measured behavior probs, not an assumed lag."""
    from distributed_ba3c_tpu.ops.gradproc import inject_learning_rate
    from distributed_ba3c_tpu.ops.vtrace import vtrace_returns
    import jax.numpy as jnp
    import optax

    cfg = BA3CConfig(num_actions=pong.num_actions, fc_units=16)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon,
                         cfg.grad_clip_norm)
    mesh1 = make_mesh(num_data=1, devices=jax.devices()[:1])
    pstep = make_pod_learner_step(model, opt, cfg, mesh1)
    train = _fresh_train(cfg, model, opt)
    batch = _pong_batch(cfg, T=4, B=6, seed=3)  # "collected 3 versions ago"
    block = batch_to_block(batch, pstep.block_sharding)

    learner = PodLearner(pstep, train, cfg, max_staleness=8)
    learner.version = 3
    m = learner.consume(StampedBatch(0, 0, batch))
    assert m is not None and learner.gate is not None

    # oracle: the same math, written independently of the pod program
    def oracle_loss(params):
        T, B = batch["action"].shape
        flat = block.states.reshape((T * B, *cfg.state_shape))
        all_states = jnp.concatenate([flat, block.bootstrap_state], axis=0)
        out = model.apply({"params": params}, all_states)
        logits = out.logits[: T * B].reshape((T, B, -1))
        values = out.value[: T * B].reshape((T, B))
        boot = out.value[T * B:]
        lp = jax.nn.log_softmax(logits, axis=-1)
        probs = jax.nn.softmax(logits, axis=-1)
        target_lp = jnp.take_along_axis(
            lp, block.actions[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        vt = vtrace_returns(
            behaviour_log_probs=block.behavior_log_probs,
            target_log_probs=jax.lax.stop_gradient(target_lp),
            rewards=block.rewards,
            dones=block.dones,
            values=jax.lax.stop_gradient(values),
            bootstrap_value=jax.lax.stop_gradient(boot),
            gamma=cfg.gamma,
        )
        policy_loss = -jnp.mean(target_lp * vt.pg_advantages)
        value_loss = 0.5 * jnp.mean(jnp.square(values - vt.vs))
        entropy = -jnp.mean(jnp.sum(probs * lp, axis=-1))
        return (
            policy_loss + cfg.value_loss_coef * value_loss
            - cfg.entropy_beta * entropy
        )

    train0 = _fresh_train(cfg, model, opt)
    grads = jax.grad(oracle_loss)(train0.params)
    opt_state = inject_learning_rate(train0.opt_state, cfg.learning_rate)
    updates, _ = opt.update(grads, opt_state, train0.params)
    oracle_params = optax.apply_updates(train0.params, updates)
    for a, b in zip(
        jax.tree_util.tree_leaves(oracle_params),
        jax.tree_util.tree_leaves(learner.state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_staleness_curve_value_lag_mae_grows_with_lag(pod_parts,
                                                      overlap_parts):
    """The curve the bench measures, in miniature: training at a larger
    measured lag yields a larger (or equal) value drift signal. Smoke of
    monotone direction, not magnitudes — CPU, tiny model, few steps."""
    cfg, model, opt, mesh, pstep = pod_parts
    ostep, fresh_state, _ = overlap_parts

    def run(lag, iters=6):
        telemetry.reset_all()
        learner = PodLearner(pstep, _fresh_train(cfg, model, opt), cfg)
        learner.learning_rate = 1e-2  # move the value net so lag shows
        drv = LaggedBlockDriver(ostep, learner, lag=lag)
        drv.prime(fresh_state(ostep.put))
        maes = []
        for _ in range(iters):
            m = drv.iterate()
            maes.append(float(m["value_lag_mae"]))
        # skip the ring-fill ramp: only full-lag iterations count
        return np.mean(maes[lag:])

    mae0, mae4 = run(0), run(4)
    assert mae4 >= mae0


# ---------------------------------------------------------------------------
# host-side shed: the uniform fallback keeps lockstep servers stepping
# ---------------------------------------------------------------------------

class _NeverServePredictor:
    """A predictor stand-in that must never be reached past the gate."""

    num_actions = 4

    def put_block_task(self, *a, **k):  # pragma: no cover
        raise AssertionError("gate must shed before the predictor")

    def put_task(self, *a, **k):  # pragma: no cover
        raise AssertionError("gate must shed before the predictor")


def test_version_gate_sheds_with_typed_reject():
    telemetry.reset_all()
    gated = VersionGatedPredictor(
        _NeverServePredictor(), behind_fn=lambda: 5, max_staleness=2,
        tele_role=pod_role(0),
    )
    rejects = []
    ok = gated.put_block_task(
        np.zeros((4, 8, 8, 4), np.uint8), lambda *a: None,
        shed_callback=rejects.append,
    )
    assert ok is False and len(rejects) == 1
    assert rejects[0].reason == "stale_params"
    assert (
        telemetry.registry(pod_role(0))
        .counter("stale_params_sheds_total").value() == 4
    )


def test_stale_shed_answers_with_uniform_fallback(tmp_path):
    """Compose the host gate with a real master's shed fallback: the
    lockstep server's action reply is produced IMMEDIATELY (uniform
    policy, exact log-prob) — the server steps on instead of parking in
    recv, and V-trace stays exact on the fallback experience."""
    from distributed_ba3c_tpu.pod.host import PodSimulatorMaster

    telemetry.reset_all()
    gated = VersionGatedPredictor(
        _NeverServePredictor(), behind_fn=lambda: 9, max_staleness=3,
        tele_role=pod_role(0),
    )
    master = PodSimulatorMaster(
        f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c", gated,
        unroll_len=3,
    )
    try:
        replies = []

        def cb(actions, values, logps):
            replies.append((actions, values, logps))

        k = 6
        ok = gated.put_block_task(
            np.zeros((k, 8, 8, 4), np.uint8), cb,
            shed_callback=master._shed_fallback_block(cb, k),
        )
        assert ok is False
        assert len(replies) == 1  # the reply exists: no wedge possible
        actions, values, logps = replies[0]
        assert actions.shape == (k,) and actions.dtype == np.int32
        assert np.all((actions >= 0) & (actions < 4))
        # the recorded behavior log-prob IS the fallback policy's
        np.testing.assert_allclose(logps, np.full(k, -np.log(4)), rtol=1e-6)
        np.testing.assert_array_equal(values, np.zeros(k, np.float32))
    finally:
        master.close()


def test_pod_master_segments_carry_behavior_values(tmp_path):
    """PodSimulatorMaster's per-env path emits behavior_values, and
    collate_rollout stacks them into the [T, B] layout the wire ships."""
    from distributed_ba3c_tpu.data.dataflow import collate_rollout
    from distributed_ba3c_tpu.pod.host import PodSimulatorMaster

    class _InstantPredictor:
        num_actions = 4

        def put_task(self, state, cb, *, shed_callback=None):
            cb(1, 0.5, -1.25)
            return True

    master = PodSimulatorMaster(
        f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c",
        _InstantPredictor(), unroll_len=2,
    )
    try:
        ident = b"simulator-0"
        state = np.zeros((8, 8, 4), np.uint8)
        for _ in range(4):  # 3 completed transitions -> one T=2 segment
            master._on_message(ident, state, reward=1.0, is_over=False)
        seg = master.queue.get_nowait()
        assert seg["behavior_values"].shape == (2,)
        np.testing.assert_allclose(seg["behavior_values"], [0.5, 0.5])
        np.testing.assert_allclose(seg["behavior_log_probs"], [-1.25, -1.25])
        batch = collate_rollout([seg, seg])
        assert batch["behavior_values"].shape == (2, 2)  # [T, B]
    finally:
        master.close()


def test_vtrace_master_segments_unchanged(tmp_path):
    """The V-trace plane's segments must NOT grow the key (its learner
    feed has no spec for it) — only the pod master records values."""
    from distributed_ba3c_tpu.actors.vtrace_master import VTraceSimulatorMaster

    class _InstantPredictor:
        num_actions = 4

        def put_task(self, state, cb, *, shed_callback=None):
            cb(1, 0.5, -1.25)
            return True

    master = VTraceSimulatorMaster(
        f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c",
        _InstantPredictor(), unroll_len=2,
    )
    try:
        ident = b"simulator-0"
        state = np.zeros((8, 8, 4), np.uint8)
        for _ in range(4):
            master._on_message(ident, state, reward=1.0, is_over=False)
        seg = master.queue.get_nowait()
        assert "behavior_values" not in seg
    finally:
        master.close()


# ---------------------------------------------------------------------------
# e2e: a real 2-host localhost pod (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(600)
def test_pod_e2e_two_hosts_train_and_survive_host_loss(tmp_path):
    """The whole pod on localhost ipc-derived tcp: two supervised actor
    hosts feed one bounded-staleness learner; a SIGKILLed host's blocks
    keep flowing from the survivor (no learner restart), the supervisor
    respawns it, and its cache rejoins at the current version."""
    import socket

    from distributed_ba3c_tpu.orchestrate.pod import (
        PodLearnerPlane,
        PodSupervisor,
        host_argv,
    )

    telemetry.reset_all()
    # pick a free tcp port band (the pod channels derive +100..+102)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    base = s.getsockname()[1]
    s.close()
    c2s = f"tcp://127.0.0.1:{base}"
    s2c = f"tcp://127.0.0.1:{base + 1}"

    cfg = BA3CConfig(
        image_size=(16, 16), frame_history=4, num_actions=4, fc_units=16,
        local_time_max=3, predict_batch_size=16,
    )
    plane = PodLearnerPlane(cfg, c2s, s2c, max_staleness=64)
    plane.start()
    sup = PodSupervisor(
        2,
        lambda i: host_argv(
            i, c2s, s2c, env="fake", n_sims=2, unroll_len=3,
            segments_per_block=8, image_size=16, frame_history=4,
            num_actions=4, fc_units=16,
        ),
        backoff_base_s=0.2,
    )
    sup.start()
    try:
        def train_until(n, timeout):
            deadline = time.monotonic() + timeout
            while plane.learner.version < n and time.monotonic() < deadline:
                plane.step_once(timeout=1.0)
            return plane.learner.version >= n

        assert train_until(5, 240), "pod never produced 5 updates"
        # both hosts reported in (registry ROLES persist process-wide
        # across reset_all, so read live mirrored series, not role names)
        hosts_seen = {
            r for r, reg in telemetry.all_registries().items()
            if r.startswith("pod.host") and reg.scalars()
        }
        assert hosts_seen == {"pod.host0", "pod.host1"}

        # host-loss chaos: SIGKILL host 0's whole process group
        v_kill = plane.learner.version
        assert sup.sigkill_slot(0)
        # the learner keeps training on the survivor — no restart of
        # anything learner-side
        assert train_until(v_kill + 3, 240), "learner stalled after host loss"
        # the supervisor respawns the host and its cache rejoins at the
        # CURRENT version (not zero): its mirrored params_version catches
        # back up to the learner's publish frontier
        def rejoined():
            g = telemetry.registry("pod.host0").scalars()
            return g.get("params_version", -1) >= v_kill
        deadline = time.monotonic() + 240
        ok = False
        while time.monotonic() < deadline:
            plane.step_once(timeout=0.5)
            if rejoined():
                ok = True
                break
        assert ok, "killed host never rejoined at the current version"
        assert (
            telemetry.registry("orchestrator")
            .counter("server_respawns_total").value() >= 1
        )
    finally:
        sup.stop()
        sup.join(timeout=5)
        sup.close()
        plane.close()
