"""TopologySpec (orchestrate/topology.py, docs/topology.md).

Four contracts:

- lossless JSON round-trip of a fully-populated spec (every section);
- the ``--dump_topology`` migration path: cli.py's flag set and the
  emitted document describe the SAME spec;
- validation: unknown fields at every nesting level, half-specified
  combos (the rules cli.py used to police inline live in the spec now),
  bad bounds — each one a TopologyError, which both entry points turn
  into a clean exit-2 usage error;
- the fuzz gate: junk, truncated and type-confused JSON NEVER escapes as
  a raw traceback.
"""

import json
import random

import pytest

from distributed_ba3c_tpu.orchestrate.spec import FleetSpec
from distributed_ba3c_tpu.orchestrate.topology import (
    ChaosTopology,
    LearnerTopology,
    ModeTopology,
    NetChaosTopology,
    PodTopology,
    ReconcilePolicy,
    ServingTopology,
    TopologyError,
    TopologySpec,
)


def full_spec() -> TopologySpec:
    """Every section populated — the round-trip worst case."""
    return TopologySpec(
        mode=ModeTopology(
            task="train", trainer="tpu_sync_ba3c", env="cpp:breakout",
            steps_per_epoch=120, steps_per_dispatch=4,
        ),
        fleets=(
            FleetSpec(
                pipe_c2s="ipc://t-c2s-0", pipe_s2c="ipc://t-s2c-0",
                game="breakout", envs_per_server=8, fleet_size=3,
                fleet_min=2, fleet_max=6,
            ),
            FleetSpec(
                pipe_c2s="ipc://t-c2s-1", pipe_s2c="ipc://t-s2c-1",
                game="breakout", envs_per_server=8, fleet_size=3,
                fleet_min=2, fleet_max=6,
            ),
        ),
        learner=LearnerTopology(
            logdir="/tmp/topo-test", train_args=("--logdir", "/tmp/topo-test"),
            max_restarts=3, stall_secs=120,
        ),
        pod=PodTopology(
            hosts=2, sims_per_host=4, pipe_c2s="tcp://127.0.0.1:15555",
            pipe_s2c="tcp://127.0.0.1:15556", max_staleness=4,
        ),
        serving=ServingTopology(
            replicas=2, replicas_max=4, slo_ms=50,
            canary_load="/ckpt/cand", canary_fraction=0.1,
        ),
        chaos=ChaosTopology(seed=7, interval_s=2.5, max_kills=6),
        netchaos=NetChaosTopology(seed=11, links={
            "pod": {"partitions": [{"start_s": 1.0, "end_s": 3.0}]},
        }),
        reconcile=ReconcilePolicy(poll_interval_s=0.1, restart_budget=32),
    )


# --------------------------------------------------------------------------
# round-trip
# --------------------------------------------------------------------------


def test_full_round_trip_is_lossless():
    spec = full_spec()
    again = TopologySpec.from_json(spec.to_json())
    assert again == spec
    # and the re-emitted document is byte-identical (sorted, stable)
    assert again.to_json() == spec.to_json()


def test_minimal_round_trip():
    spec = TopologySpec()
    again = TopologySpec.from_json(spec.to_json())
    assert again == spec
    assert again.learner is None and again.pod is None


def test_load_reads_a_file(tmp_path):
    p = tmp_path / "spec.json"
    p.write_text(full_spec().to_json())
    assert TopologySpec.load(str(p)) == full_spec()


def test_load_missing_file_is_a_usage_error(tmp_path):
    with pytest.raises(TopologyError, match="cannot read"):
        TopologySpec.load(str(tmp_path / "nope.json"))


# --------------------------------------------------------------------------
# the --dump_topology migration path
# --------------------------------------------------------------------------


def test_dump_topology_round_trips_through_cli(tmp_path, capsys):
    from distributed_ba3c_tpu.cli import main

    logdir = str(tmp_path / "run")
    rc = main([
        "--env", "fake", "--simulator_procs", "4", "--logdir", logdir,
        "--dump_topology",
    ])
    assert rc == 0
    emitted = TopologySpec.from_json(capsys.readouterr().out)
    # the document IS the flag set: fake env → per-env wire, one server
    # per simulator; the learner section carries the supervised logdir
    assert emitted.mode.env == "fake"
    assert len(emitted.fleets) == 1
    assert emitted.fleets[0].wire == "per-env"
    assert emitted.fleets[0].fleet_size == 4
    assert emitted.learner is not None
    assert emitted.learner.logdir == logdir
    # and the emitted JSON re-parses to the same spec (the pin)
    assert TopologySpec.from_json(emitted.to_json()) == emitted


def test_dump_topology_multi_fleet_derives_distinct_pipes(capsys):
    from distributed_ba3c_tpu.cli import main

    rc = main([
        "--env", "zmq:pong", "--fleets", "2",
        "--pipe_c2s", "tcp://0.0.0.0:5555",
        "--pipe_s2c", "tcp://0.0.0.0:5556",
        "--dump_topology",
    ])
    assert rc == 0
    spec = TopologySpec.from_json(capsys.readouterr().out)
    pipes = [a for f in spec.fleets for a in (f.pipe_c2s, f.pipe_s2c)]
    assert len(set(pipes)) == 4  # fleet_pipes derived, no collisions


# --------------------------------------------------------------------------
# validation: unknown fields, moved cli rules, bad bounds
# --------------------------------------------------------------------------


def test_unknown_top_level_field_rejected():
    with pytest.raises(TopologyError, match="unknown topology fields"):
        TopologySpec.from_doc({"bogus": 1})


@pytest.mark.parametrize("section", [
    "mode", "learner", "pod", "serving", "chaos", "netchaos", "reconcile",
])
def test_unknown_nested_field_rejected_at_every_level(section):
    doc = json.loads(full_spec().to_json())
    doc[section]["typoed_knob"] = 1
    with pytest.raises(TopologyError, match=f"unknown {section} fields"):
        TopologySpec.from_doc(doc)


def test_unknown_fleet_field_rejected():
    doc = json.loads(full_spec().to_json())
    doc["fleets"][1]["typoed_knob"] = 1
    with pytest.raises(TopologyError, match=r"unknown fleets\[1\] fields"):
        TopologySpec.from_doc(doc)


def test_unknown_version_rejected():
    with pytest.raises(TopologyError, match="version"):
        TopologySpec.from_doc({"version": 2})


@pytest.mark.parametrize("mutate, msg", [
    # the rules cli.py used to police inline — they live in the spec now
    (lambda d: d["mode"].update(trainer="tpu_fused_ba3c"),
     "multiple fleets"),
    (lambda d: d["mode"].update(task="eval"), "multiple fleets"),
    (lambda d: d["mode"].update(overlap=True), "overlap"),
    (lambda d: d["mode"].update(fleet_accum=2), "fleet_accum"),
    (lambda d: d["mode"].update(steps_per_dispatch=7), "must divide"),
    (lambda d: d["serving"].update(canary_autopromote=True),
     "canary decision must not be made N times"),
    (lambda d: d["fleets"][1].update(pipe_c2s="ipc://t-c2s-0"),
     "collide"),
])
def test_cross_section_rules(mutate, msg):
    doc = json.loads(full_spec().to_json())
    mutate(doc)
    with pytest.raises(TopologyError, match=msg):
        TopologySpec.from_doc(doc)


def test_serving_section_rejected_on_fused_trainer():
    doc = json.loads(full_spec().to_json())
    doc["fleets"] = []
    doc["mode"].update(trainer="tpu_fused_ba3c")
    with pytest.raises(TopologyError, match="serving section"):
        TopologySpec.from_doc(doc)


def test_external_zmq_fleet_needs_endpoints():
    with pytest.raises(TopologyError, match="reachable endpoints"):
        TopologySpec(
            mode=ModeTopology(env="zmq:pong"),
            fleets=(FleetSpec(pipe_c2s="", pipe_s2c=""),),
        )


@pytest.mark.parametrize("section_cls, kw, msg", [
    (LearnerTopology, {"logdir": ""}, "logdir"),
    (LearnerTopology, {"logdir": "x", "max_restarts": -1}, "max_restarts"),
    (PodTopology, {"hosts": 0}, "hosts"),
    (PodTopology, {"max_staleness": -2}, "version lag"),
    (ServingTopology, {"replicas": 0}, "replicas"),
    (ServingTopology, {"replicas": 2, "replicas_max": 1}, "replicas_max"),
    (ServingTopology, {"replicas_max": 4}, "slo_ms"),
    (ServingTopology, {"canary_load": "/ckpt"}, "come\\s+together"),
    (ServingTopology, {"canary_fraction": 0.5}, "come\\s+together"),
    (ServingTopology,
     {"canary_load": "/ckpt", "canary_fraction": 1.5}, "fraction"),
    (ServingTopology, {"canary_autopromote": True}, "canary_load"),
    (ChaosTopology, {"interval_s": 0}, "interval_s"),
    (ChaosTopology, {"max_kills": -1}, "bounds"),
    (ReconcilePolicy, {"poll_interval_s": 0}, "poll_interval_s"),
    (ReconcilePolicy, {"backoff_base_s": 5, "backoff_max_s": 1}, "backoff"),
    (ReconcilePolicy, {"restart_budget": -1}, "restart_budget"),
    (ModeTopology, {"task": "dance"}, "task"),
    (ModeTopology, {"fleet_accum": 0}, "fleet_accum"),
])
def test_section_bounds(section_cls, kw, msg):
    with pytest.raises(TopologyError, match=msg):
        section_cls(**kw)


def test_bad_netchaos_schedule_is_a_topology_error():
    with pytest.raises(TopologyError, match="netchaos"):
        NetChaosTopology(links={"pod": {"drop": "not-a-schedule"}})


def test_backoff_schedule_shape():
    p = ReconcilePolicy(backoff_base_s=0.5, backoff_max_s=8.0)
    assert [p.backoff_s(n) for n in (1, 2, 3, 4, 5, 99)] == [
        0.5, 1.0, 2.0, 4.0, 8.0, 8.0,
    ]


# --------------------------------------------------------------------------
# exit-2 at both entry points
# --------------------------------------------------------------------------


def test_cli_flag_combos_exit_2(capsys):
    from distributed_ba3c_tpu.cli import main

    with pytest.raises(SystemExit) as ei:
        main(["--fleets", "2", "--trainer", "tpu_fused_ba3c",
              "--env", "jax:pong"])
    assert ei.value.code == 2
    assert "fused trainer" in capsys.readouterr().err


def test_orchestrate_topology_bad_spec_exits_2(tmp_path, capsys):
    from distributed_ba3c_tpu.orchestrate.__main__ import main

    p = tmp_path / "bad.json"
    p.write_text('{"bogus_section": {}}')
    with pytest.raises(SystemExit) as ei:
        main(["--topology", str(p)])
    assert ei.value.code == 2
    assert "unknown topology fields" in capsys.readouterr().err


def test_orchestrate_topology_missing_file_exits_2(tmp_path, capsys):
    from distributed_ba3c_tpu.orchestrate.__main__ import main

    with pytest.raises(SystemExit) as ei:
        main(["--topology", str(tmp_path / "nope.json")])
    assert ei.value.code == 2


def test_orchestrate_topology_rejects_train_args(tmp_path, capsys):
    from distributed_ba3c_tpu.orchestrate.__main__ import main

    p = tmp_path / "spec.json"
    p.write_text(TopologySpec().to_json())
    with pytest.raises(SystemExit) as ei:
        main(["--topology", str(p), "--", "--logdir", "/tmp/x"])
    assert ei.value.code == 2


def test_orchestrate_topology_rejects_mode_mixing(tmp_path):
    from distributed_ba3c_tpu.orchestrate.__main__ import main

    p = tmp_path / "spec.json"
    p.write_text(TopologySpec().to_json())
    with pytest.raises(SystemExit) as ei:
        main(["--topology", str(p), "--pod_hosts", "2"])
    assert ei.value.code == 2


def test_orchestrate_empty_topology_exits_2(tmp_path, capsys):
    from distributed_ba3c_tpu.orchestrate.__main__ import main

    p = tmp_path / "spec.json"
    p.write_text(TopologySpec().to_json())  # no fleets/pod/learner
    with pytest.raises(SystemExit) as ei:
        main(["--topology", str(p)])
    assert ei.value.code == 2
    assert "names nothing" in capsys.readouterr().err


# --------------------------------------------------------------------------
# the fuzz gate: junk in, TopologyError out — never a raw traceback
# --------------------------------------------------------------------------

_TYPE_CONFUSIONS = [
    "[]", "17", '"a string"', "null", "true",
    '{"fleets": {}}',
    '{"fleets": [[]]}',
    '{"fleets": [{"fleet_size": "many"}]}',
    '{"mode": []}',
    '{"mode": {"task": 3}}',
    '{"mode": {"fleet_accum": "two"}}',
    '{"learner": []}',
    '{"learner": {"logdir": null}}',
    '{"learner": {"logdir": "x", "train_args": 5}}',
    '{"learner": {"logdir": "x", "max_restarts": "lots"}}',
    '{"pod": {"hosts": "two"}}',
    '{"pod": {"hosts": []}}',
    '{"serving": {"replicas": null}}',
    '{"serving": {"canary_fraction": "most"}}',
    '{"chaos": {"interval_s": "fast"}}',
    '{"netchaos": {"links": 3}}',
    '{"netchaos": {"links": {"pod": 3}}}',
    '{"reconcile": {"poll_interval_s": []}}',
    '{"reconcile": 0.25}',
    '{"version": "one"}',
    '{"version": null}',
]


@pytest.mark.parametrize("text", _TYPE_CONFUSIONS)
def test_type_confused_docs_never_traceback(text):
    with pytest.raises(TopologyError):
        TopologySpec.from_json(text)


def test_truncations_never_traceback():
    whole = full_spec().to_json()
    for cut in range(0, len(whole), 37):
        with pytest.raises(TopologyError):
            TopologySpec.from_json(whole[:cut])


def test_seeded_mutation_fuzz_never_tracebacks():
    """300 seeded mutations of a valid document: flip values to wrong
    types, inject junk keys, truncate — the outcome is always a clean
    TopologySpec or a TopologyError, never anything else."""
    rng = random.Random(0xBA3C)
    whole = full_spec().to_json()
    junk_values = ["{}", "[]", "null", '"x"', "-1", "1e99", "true"]
    for _ in range(300):
        text = whole
        op = rng.randrange(3)
        if op == 0:  # splice junk into a random value position
            i = rng.randrange(len(text))
            text = text[:i] + rng.choice(junk_values) + text[i:]
        elif op == 1:  # random truncation
            text = text[: rng.randrange(len(text))]
        else:  # type-confuse one line
            lines = text.splitlines()
            k = rng.randrange(len(lines))
            if ":" in lines[k]:
                key = lines[k].split(":", 1)[0]
                lines[k] = f"{key}: {rng.choice(junk_values)},"
            text = "\n".join(lines)
        try:
            TopologySpec.from_json(text)
        except TopologyError:
            pass  # the only acceptable failure mode


def test_fuzz_through_the_file_entry_point(tmp_path, capsys):
    """The operator-facing path: a corrupt file exits 2 with a usage
    message, no traceback on stderr."""
    from distributed_ba3c_tpu.orchestrate.__main__ import main

    p = tmp_path / "corrupt.json"
    p.write_text('{"fleets": [{"fleet_size": "many"}]')  # truncated too
    with pytest.raises(SystemExit) as ei:
        main(["--topology", str(p)])
    assert ei.value.code == 2
    err = capsys.readouterr().err
    assert "Traceback" not in err
