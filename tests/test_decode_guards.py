"""Regression tests for the receive-loop decode guards (ba3cwire W3).

These pin the PR-17 fixes for the four findings W3 raised on the live
planes: the python simulator's action reply (actors/simulator.py) and the
C++ env server's three reply paths (envs/native.py) now decode through
fallback helpers — a corrupt reply repeats the previous action, bumps
``corrupt_action_replies_total``, and the lockstep loop stays alive.
"""

import numpy as np

from distributed_ba3c_tpu.actors.simulator import _decode_action as sim_decode
from distributed_ba3c_tpu.envs.native import (
    _decode_action as native_decode_one,
    _decode_actions as native_decode_batch,
)
from distributed_ba3c_tpu.utils.serialize import dumps


class _Counter:
    def __init__(self):
        self.n = 0

    def inc(self, v=1):
        self.n += v


def test_simulator_decode_action_roundtrip():
    c = _Counter()
    assert sim_decode(dumps(3), 0, c) == 3
    assert c.n == 0


def test_simulator_decode_action_junk_repeats_previous():
    c = _Counter()
    assert sim_decode(b"\xff\x00garbage", 7, c) == 7
    assert c.n == 1


def test_native_decode_batch_roundtrip():
    c = _Counter()
    prev = np.zeros(4, np.int32)
    raw = np.array([1, 2, 3, 4], np.int32).tobytes()
    out = native_decode_batch(raw, prev, c)
    assert out.tolist() == [1, 2, 3, 4]
    assert c.n == 0


def test_native_decode_batch_short_frame_repeats_previous():
    """A truncated reply must not reach env.step with the wrong batch
    shape — the fallback (previous actions) keeps lockstep intact."""
    c = _Counter()
    prev = np.array([5, 6, 7, 8], np.int32)
    out = native_decode_batch(b"\x01\x00\x00\x00", prev, c)
    assert out is prev
    assert c.n == 1


def test_native_decode_batch_unaligned_frame_repeats_previous():
    """frombuffer raises on a byte count that isn't a multiple of the
    itemsize — exactly the corrupt frame that used to kill the loop."""
    c = _Counter()
    prev = np.zeros(2, np.int32)
    out = native_decode_batch(b"\x01\x02\x03", prev, c)
    assert out is prev
    assert c.n == 1


def test_native_decode_one_roundtrip_and_junk():
    c = _Counter()
    assert native_decode_one(dumps(2), 0, c) == 2
    assert c.n == 0
    assert native_decode_one(b"not-msgpack\xff", 9, c) == 9
    assert c.n == 1
