"""Remote actor fleets over tcp:// (BASELINE config #3's topology).

A learner with NO local simulators (`--env zmq:pong`) binds its master pipes
on tcp://127.0.0.1; an env-server fleet launched by scripts/launch_env_fleet.py
— a separate process tree, exactly what an actor host runs — connects over
TCP and feeds it. The learner must complete its epoch budget on fleet
experience alone and write its stats. Reference: SURVEY.md §2.12 plane 1
(remote simulators on the reference's ipc/tcp pipe pair).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from distributed_ba3c_tpu.envs import native

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
@pytest.mark.skipif(not native.available(), reason="cpp core not built")
def test_learner_trains_on_remote_tcp_fleet(tmp_path):
    logdir = str(tmp_path / "log")
    c2s = f"tcp://127.0.0.1:{_free_port()}"
    s2c = f"tcp://127.0.0.1:{_free_port()}"

    learner = subprocess.Popen(
        [
            sys.executable, os.path.join(_ROOT, "train.py"),
            "--env", "zmq:pong",
            "--pipe_c2s", c2s.replace("127.0.0.1", "0.0.0.0"),
            "--pipe_s2c", s2c.replace("127.0.0.1", "0.0.0.0"),
            "--batch_size", "16",
            "--fc_units", "16",
            "--steps_per_epoch", "5",
            "--max_epoch", "1",
            "--nr_eval", "0",
            "--logdir", logdir,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_env(),
        cwd=_ROOT,
    )
    fleet = subprocess.Popen(
        [
            sys.executable, os.path.join(_ROOT, "scripts/launch_env_fleet.py"),
            "--game", "pong",
            "--n_envs", "32",
            "--c2s", c2s,
            "--s2c", s2c,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_env(),
        cwd=_ROOT,
    )
    try:
        out, _ = learner.communicate(timeout=420)
        assert learner.returncode == 0, out
    finally:
        fleet.terminate()
        try:
            fleet.wait(timeout=10)
        except subprocess.TimeoutExpired:
            fleet.kill()
        if learner.poll() is None:
            learner.kill()

    stats = json.load(open(os.path.join(logdir, "stat.json")))
    assert stats and stats[-1]["global_step"] == 5
    # fleet episodes really flowed back (pong always scores within the cap)
    fout = fleet.communicate()[0]
    assert "fleet up: 32 x pong" in fout
