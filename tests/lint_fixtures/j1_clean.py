"""J1 clean: syncs at epoch boundaries, host numpy in host-side loops."""
import jax
import jax.numpy as jnp
import numpy as np


def step_fn(state, batch):
    return state + jnp.sum(batch)


jitted = jax.jit(step_fn)


def train(state, batches):
    for batch in batches:
        state = jitted(state, batch)
    # fetch ONCE after the loop: dispatch stayed async the whole epoch
    return jax.device_get(state)


def collate(holder):
    out = []
    for dp in holder:
        out.append(np.asarray(dp, np.float32))  # host data, host loop: fine
    return np.stack(out)  # ba3clint: disable=A13 — J1 fixture, not an ingest-path collate
