"""A6 flagged: per-env socket ops inside loops over env indices (3 findings)."""

import numpy as np


def serve_per_env(n_envs, push, dealers, stacks, dumps, loads):
    actions = np.zeros(n_envs, np.int32)
    for i in range(n_envs):
        push.send(dumps(stacks[i]))  # one message per env per step
    for i in range(n_envs):
        actions[i] = loads(dealers[i].recv())  # one drain per env per step
    for sock in dealers:
        sock.send(b"ack")  # iterating the per-env socket list is the same wire
    return actions
