"""J4 clean: every consumption goes through split/fold_in."""
import jax


def sample_twice(shape):
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    return jax.random.normal(k1, shape), jax.random.uniform(k2, shape)


def sample_loop(shapes):
    key = jax.random.PRNGKey(1)
    outs = []
    for i, s in enumerate(shapes):
        sub = jax.random.fold_in(key, i)
        outs.append(jax.random.normal(sub, s))
    return outs
