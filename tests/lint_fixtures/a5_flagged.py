"""A5 flagged: from-imports of underscore-private names (3 findings)."""

from distributed_ba3c_tpu.utils.devicelock import _stderr_print  # noqa: F401
from queue import _PySimpleQueue as SimpleQueueImpl  # noqa: F401
from .a5_clean import _helper  # noqa: F401


def use():
    _stderr_print("hi")
    return SimpleQueueImpl, _helper
