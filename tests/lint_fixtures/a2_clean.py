"""A2 clean: timeouts + stop-flag rechecks, _nowait variants, dict.get."""
import queue


class Pump:
    def __init__(self, in_queue, out_queue, stop_evt):
        self.in_queue = in_queue
        self.out_queue = out_queue
        self.stop_evt = stop_evt

    def drain(self):
        while not self.stop_evt.is_set():
            try:
                item = self.in_queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.out_queue.put(item, timeout=0.2)
            except queue.Full:
                pass

    def best_effort(self, item, config):
        self.out_queue.put_nowait(item)
        got = self.in_queue.get_nowait()
        return got, config.get("mode")  # dict.get, not a queue op
