"""J3 clean: arrays/variables passed to the jitted callable."""
import jax
import jax.numpy as jnp


def fwd(params, batch):
    return batch


jitted = jax.jit(fwd)


def serve(params, states):
    batch = jnp.stack(states)
    return jitted(params, batch)  # a name, built outside the call
