"""A8 fixture: fleet-role processes spawned outside orchestrate/ — every
pattern here bypasses the supervisor's respawn/backoff/scale accounting."""

import os
import subprocess

from distributed_ba3c_tpu.actors.fleet import build_fleet_planes
from distributed_ba3c_tpu.actors.simulator import SimulatorProcess
from distributed_ba3c_tpu.envs import native


def build_fleet(c2s, s2c, build_player):
    # direct fleet-role construction: dies dead, nothing accounted
    servers = [
        native.CppEnvServerProcess(i, c2s, s2c, n_envs=16) for i in range(4)
    ]
    sims = [SimulatorProcess(i, c2s, s2c, build_player) for i in range(4)]
    return servers + sims


def launch_learner(logdir):
    # unsupervised learner: no checkpoint failover when it dies
    return subprocess.Popen(["python", "train.py", "--logdir", logdir])


def launch_remote_fleet(host):
    subprocess.run(["ssh", host, "python", "scripts/launch_env_fleet.py"])


def fork_worker():
    # the repo is spawn-context-only
    return os.fork()


def assemble_fleets(c2s, s2c, make_predictor, make_master, make_sup):
    # multi-fleet assembly outside orchestrate/: K fleets of unaccounted
    # spawns behind one call
    return build_fleet_planes(4, c2s, s2c, make_predictor, make_master, make_sup)
