"""Real violations silenced by inline suppressions (engine test fixture)."""
import threading
import time


def start_watcher(fn):
    # event-wait watcher with externally managed lifetime (justification!)
    t = threading.Thread(target=fn, daemon=True)  # ba3clint: disable=A1
    t.start()
    return t


def drain(q):
    while True:
        # the producer is the OS (signalfd): it cannot die before us
        # ba3clint: disable=A2
        item = q.get()
        if item is None:
            return


def stamp():
    started = time.time() - 0.0  # ba3clint: disable=all
    return started
