"""A15 clean fixture: poll-only, spawn-only, and sanctioned loop shapes."""
import time


def wait_for_exit(child, timeout_s):
    # poll-only loop: observing liveness without respawning is a plain
    # wait, not a shadow supervisor
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if child.poll() is not None:
            return child.returncode
        time.sleep(0.1)
    return None


def launch_fan_out(factories):
    # spawn-only loop: a launch fan-out never observes liveness, so it
    # cannot be a supervision cycle
    workers = [f() for f in factories]
    for w in workers:
        w.start()
    return workers


def sanctioned_bench_loop(worker_factory, reps):
    # an acceptance bench that IS the measurand of supervision carries
    # the sanction
    worker = worker_factory()
    worker.start()
    for _ in range(reps):  # ba3clint: disable=A15 — bench measures respawn latency; the reconciler under test budgets the heals
        if not worker.is_alive():
            worker = worker_factory()
            worker.start()
