"""A6 clean: the block wire and the loop shapes that are NOT per-env ops."""
import zmq

SNDMORE = 2


def serve_block(n_envs, push, dealer, frames, rewards):
    # bounded waits (A12): these sockets carry send/recv timeouts
    push.setsockopt(zmq.SNDTIMEO, 2000)
    dealer.setsockopt(zmq.RCVTIMEO, 2000)
    # the block wire: ONE multipart send + ONE batched reply for all B envs
    push.send_multipart(frames, copy=False)
    reply = dealer.recv_multipart()
    # chunking the FRAMES of one logical message is not a per-env loop
    for frame in frames:
        push.send(frame, flags=SNDMORE)
    # compute-only loops over env indices are fine
    total = 0.0
    for i in range(n_envs):
        total += rewards[i]
    return reply, total


def shutdown(dealers):
    for s in dealers:
        s.close(0)  # close is lifecycle, not a wire op
