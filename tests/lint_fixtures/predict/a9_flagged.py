"""A9 fixture: blocking I/O and unbounded queues in the serving plane.

Lives under a ``predict/`` directory on purpose — the rule only applies
there (the serving hot path, docs/serving.md).
"""
import queue
import time

tasks = queue.Queue()  # unbounded admission queue
backlog = queue.Queue(maxsize=0)  # maxsize=0 is queue.Queue's unbounded


def scheduler_tick(sock):
    time.sleep(0.01)  # stalls every in-flight request
    print("batch dispatched")  # console I/O on the hot path
    sock.send(b"reply")  # wire I/O belongs to the masters
