"""A16 fixture: ad-hoc bf16/int8 casts on the publish/actor-forward path.

Lives under a ``predict/`` directory on purpose — the rule only applies
to the params-publish/actor-forward path (predict/, fused/, pod/); the
sanctioned homes are ``quantize/`` and THE suppressed audited cast site.
"""
import jax.numpy as jnp
from jax import lax


def publish_cast(params):
    # ad-hoc quantizing publish cast: no audit entry pins this program
    return jnp.asarray(params).astype(jnp.bfloat16)


def publish_cast_stringly(params):
    return jnp.asarray(params).astype("int8")


def forward_cast(x):
    return lax.convert_element_type(x, jnp.int8)


def forward_cast_kw(x):
    return lax.convert_element_type(x, new_dtype=jnp.bfloat16)
