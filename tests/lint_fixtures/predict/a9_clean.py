"""A9 clean fixture: the idioms the real serving plane uses.

Bounded queues (literal or computed bound), bounded-timeout waits, no
sleeps/prints/file I/O on the scheduler path.
"""
import queue

from distributed_ba3c_tpu.utils.concurrency import FastQueue

DEPTH = 4096

admission = FastQueue(maxsize=4096)
sized = FastQueue(maxsize=DEPTH)  # computed bound: sizing policy, not A9's
small = queue.Queue(maxsize=256)


def scheduler_tick(q):
    try:
        return q.get(timeout=0.5)
    except queue.Empty:
        return None
