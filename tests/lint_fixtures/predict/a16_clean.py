"""A16 clean fixture: the casts the publish/actor-forward path IS allowed.

f32 is the ladder's base rung (not a quantization), integer index/obs
dtypes are not serving numerics, and the quantizing cast itself is
delegated to the sanctioned hook a ``rollout_dtype`` switch selects.
"""
import jax.numpy as jnp
from jax import lax


def to_full_precision(params):
    # widening back to the base rung is not a quantization
    return jnp.asarray(params).astype(jnp.float32)


def pack_actions(actions):
    return jnp.asarray(actions).astype(jnp.int32)


def frame_bytes(obs):
    return lax.convert_element_type(obs, jnp.uint8)


def select_cast(rollout_dtype, cast_hooks):
    # dtype selection delegated to the sanctioned (audited) hook table
    return cast_hooks[rollout_dtype]
