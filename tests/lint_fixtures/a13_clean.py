"""A13 clean fixture: the staged-ingest idioms (and sanctioned shapes)."""
import numpy as np


def collate_batch_into(holder, out):
    # the budget path: obs bytes write straight into the staging slot
    for i, dp in enumerate(holder):
        out["state"][i] = dp[0]
        out["action"][i] = dp[1]


def collate_compat(holder):
    # sanctioned compat collate: suppression states the sanction
    return {"state": np.stack([dp[0] for dp in holder])}  # ba3clint: disable=A13 — per-env compat foil


def flush_bookkeeping(client):
    # dict/list .copy() on plain names is not an obs-byte pass
    snapshot = client.scores.copy()
    return snapshot


def assemble_rows(rows):
    # copies OUTSIDE the ingest-path functions are someone else's budget
    return np.concatenate(rows)
