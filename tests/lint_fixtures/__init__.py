"""Lint-rule fixtures: parsed by ba3clint in tests, never imported/executed.

Each rule R has ``r*_flagged.py`` (>=1 violation of R) and ``r*_clean.py``
(idiomatic code the rule must NOT fire on). ``suppressed.py`` holds real
violations silenced by inline ``# ba3clint: disable=...`` comments.
"""
