"""A5 clean: public from-imports, dunders, and module-local privates."""

from __future__ import annotations

import queue as _queue_alias  # aliasing PUBLIC names privately is fine
from distributed_ba3c_tpu.utils.devicelock import stderr_print  # noqa: F401
from os.path import __all__ as _os_path_all  # dunder names are not private


def _helper():  # defining privates locally is the point of the convention
    return _queue_alias.Queue(), _os_path_all
