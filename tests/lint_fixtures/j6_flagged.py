"""J6 flagged: host syncs on actor outputs between the two dispatches."""
import jax
import numpy as np


def actor_fn(params, astate):
    return astate, astate


def learner_fn(train, block):
    return train, {}


actor_jit = jax.jit(actor_fn, donate_argnums=(1,))
learner_jit = jax.jit(learner_fn, donate_argnums=(0,))


def overlap_loop(train, astate, block, n):
    for _ in range(n):
        astate, next_block = actor_jit(train, astate)
        jax.block_until_ready(next_block)  # J6: re-serializes the programs
        train, m = learner_jit(train, block)
        block = next_block
    return train, astate, block


def overlap_loop_device_get(train, astate, block, n):
    for _ in range(n):
        astate, next_block = actor_jit(train, astate)
        host = jax.device_get(next_block)  # J6: sync between dispatches
        print(host)
        train, m = learner_jit(train, block)
        block = next_block
    return train, astate, block


def overlap_loop_np_cast(train, astate, block):
    astate, next_block = actor_jit(train, astate)
    arr = np.asarray(next_block)  # J6: np cast is the same sync in a hat
    print(arr.shape)
    train, m = learner_jit(train, block)
    return train, astate, next_block


def overlap_loop_item(train, astate, block):
    astate, next_block = actor_jit(train, astate)
    x = next_block.item()  # J6: .item() blocks on the rollout
    print(x)
    train, m = learner_jit(train, block)
    return train, astate, next_block
