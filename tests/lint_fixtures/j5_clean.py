"""J5 clean: the donated name is rebound by the call (the intended idiom)."""
import jax
import jax.numpy as jnp


def train_step(state, batch):
    return state


jitted = jax.jit(train_step, donate_argnums=(0,))


def run(state, batches, predictor):
    for batch in batches:
        state = jitted(state, batch)  # rebinds: old buffer never read again
    predictor.update(state)
    return state


def publish(state, batch, predictor):
    params = jnp.copy(state)  # copy BEFORE donating
    state = jitted(state, batch)
    predictor.update(params)
    return state


from distributed_ba3c_tpu.audit import tripwire_jit  # noqa: E402

wired = tripwire_jit("fixture.step", train_step, donate_argnums=(0,))


def run_wired(state, batches, predictor):
    for batch in batches:
        state = wired(state, batch)  # rebinds: the clean idiom, wrapped
    predictor.update(state)
    return state
