"""A4 flagged: wall-clock time in interval/timeout arithmetic."""
import time


class Heartbeats:
    def __init__(self, timeout):
        self.timeout = timeout
        self.last_seen = time.time()  # A4: suspicious target name

    def beat(self):
        self.last_seen = time.time()  # A4

    def expired(self):
        return time.time() - self.last_seen > self.timeout  # A4: arithmetic


def wait_until(deadline_s):
    deadline = time.time() + deadline_s  # A4
    while time.time() < deadline:  # A4: comparison
        pass
