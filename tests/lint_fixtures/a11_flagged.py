"""A11 fixture: orphan spans + ad-hoc monotonic-pair latency math."""
import time

from distributed_ba3c_tpu.telemetry import tracing


def orphan_bare(trace_id, parent_id):
    # constructed and dropped: never a with-item, never finish()ed
    tracing.span(trace_id, "collate", "learner", parent=parent_id)


def orphan_assigned(trace_id):
    s = tracing.span(trace_id, "ingest", "learner")
    return s  # escapes without finish() on this path


def adhoc_monotonic_latency(t0):
    latency = time.monotonic() - t0
    return latency


def adhoc_monotonic_rate(n, t0):
    rate = n / (time.monotonic() - t0)
    return rate
