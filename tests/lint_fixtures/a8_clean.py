"""A8-clean: the idioms the real codebase uses — fleet roles ride the
orchestrate/ supervisors; non-fleet subprocesses stay fine."""

import subprocess

from distributed_ba3c_tpu.orchestrate import (
    FleetSpec,
    FleetSupervisor,
    LearnerSupervisor,
    default_factory,
)


def build_fleet(c2s, s2c):
    spec = FleetSpec(pipe_c2s=c2s, pipe_s2c=s2c, fleet_size=4, fleet_max=8)
    # the supervisor owns spawn/respawn/scale; the factory only
    # parameterizes each slot
    return FleetSupervisor(spec, factory=default_factory(spec))


def launch_learner(logdir, train_args):
    # supervised learner: checkpoint failover without operator action
    return LearnerSupervisor(logdir, train_args).run()


def run_build_tool():
    # non-fleet subprocess use is not A8's business
    return subprocess.run(["make", "-C", "cpp"], check=True)
