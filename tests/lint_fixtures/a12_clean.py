"""A12 clean fixture: every sanctioned bounded-wait shape."""
import zmq


def poller_guarded_recv(sock):
    poller = zmq.Poller()
    poller.register(sock, zmq.POLLIN)
    while True:
        if not poller.poll(200):
            continue
        return sock.recv()  # bounded by the poll timeout above


def nonblocking_send(push_sock, frames):
    try:
        push_sock.send_multipart(frames, zmq.NOBLOCK)
    except zmq.Again:
        return False
    return True


def nonblocking_flag_kw(dealer_sock, payload):
    dealer_sock.send(payload, flags=zmq.DONTWAIT)
