"""A1 flagged: bare Thread/Process instantiation."""
import multiprocessing as mp
import threading


def start_worker(fn):
    t = threading.Thread(target=fn, daemon=True)  # A1: no stop flag
    t.start()
    return t


def start_child(fn):
    p = mp.Process(target=fn)  # A1: unmanaged process
    p.start()
    return p
