"""J3 flagged: literal dict/list/str args to a jitted callable."""
import jax


def fwd(params, batch, mode):
    return batch


jitted = jax.jit(fwd)


def serve(params, x):
    out = jitted(params, {"state": x, "scale": 0.5}, "train")  # J3 x2
    return jitted(params, [1, 2, 3], mode=None)  # J3: list literal
