"""A3 flagged: client-table state mutated from closures."""


class Master:
    def __init__(self, predictor):
        self.clients = {}
        self.predictor = predictor

    def on_state(self, state, ident):
        def cb(action, value):
            client = self.clients[ident]
            client.memory.append((state, action, value))  # A3
            client.score += value  # A3
            self.clients[ident] = client  # A3: structural write

        self.predictor.put_task(state, cb)
