"""J1 flagged: host syncs inside loops / jitted scopes."""
import jax
import numpy as np


def step_fn(state, batch):
    return state


jitted = jax.jit(step_fn)


def train_loop(state, batches):
    for batch in batches:
        state = jitted(state, batch)
        loss = jax.device_get(state)  # J1: host sync every iteration
        print(loss)
    return state


def wait_loop(arrays):
    for a in arrays:
        a.block_until_ready()  # J1: sync in loop


def traced(x):
    return np.asarray(x) + 1  # J1: np inside a jitted function


traced_jit = jax.jit(traced)


def cast_loop(state, batches):
    for batch in batches:
        v = float(jitted(state, batch))  # J1: host cast of jitted result
        print(v)
