"""A3 clean: closures only read; mutation stays on the master thread."""


class Master:
    def __init__(self, predictor, send_queue):
        self.clients = {}
        self.predictor = predictor
        self.send_queue = send_queue

    def on_state(self, state, ident):
        def cb(action, value):
            # hand the result back to the master thread via the queue
            self.send_queue.put((ident, state, action, value), timeout=0.5)

        self.predictor.put_task(state, cb)

    def on_result(self, ident, state, action, value):
        # master thread: the single owner of client state
        client = self.clients[ident]
        client.memory.append((state, action, value))
        client.score += value
