"""A13 flagged fixture: the pre-staging ingest chain's copy shapes."""
import numpy as np


def collate_batch(holder):
    # fresh stack on the ingest path: the staging write is the budget
    batch = {"state": np.stack([dp[0] for dp in holder])}
    batch["state_t"] = np.swapaxes(batch["state"], 0, 1).copy()
    return batch


def _on_block_flush(steps, j):
    # per-segment materialization at emit time — the SegStates lesson
    return np.stack([st[j] for st in steps])


def batch_to_block(batch):
    # fresh contiguous copy per block instead of a reused staging buffer
    return np.ascontiguousarray(batch["state"], np.uint8)


def unrelated_helper(rows):
    # NOT on the ingest path (no scope fragment in the name): quiet
    return np.stack(rows)
