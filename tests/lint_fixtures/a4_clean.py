"""A4 clean: monotonic for intervals; wall clock only as exported timestamp."""
import json
import time


class Heartbeats:
    def __init__(self, timeout):
        self.timeout = timeout
        self.last_seen = time.monotonic()

    def beat(self):
        self.last_seen = time.monotonic()

    def expired(self):
        return time.monotonic() - self.last_seen > self.timeout


def log_event(channel, value):
    # a timestamp that leaves the process IS wall-clock business
    return json.dumps({"channel": channel, "y": value, "ts": time.time()}) + "\n"
