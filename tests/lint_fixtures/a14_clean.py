"""A14 clean fixture: the sanctioned serving shapes outside predict/."""


def master_dispatch(self, states):
    # dispatch on an INJECTED handle (router or predictor — the caller
    # decided): the masters' shape, clean by construction
    return self.predictor.put_block_task(states, lambda a, v, lp: None)


def sanctioned_factory(model, params, cfg):
    # the cli factory shape: construction carries the sanction
    from distributed_ba3c_tpu.predict.server import BatchedPredictor

    pred = BatchedPredictor(model, params, batch_size=cfg.predict_batch_size)  # ba3clint: disable=A14 — fleet-assembly factory, lifecycle owned by cli startables
    return pred


def routed_dispatch(router, states):
    # the router is predict/'s own front door — dispatching at it is the
    # whole point
    return router.put_block_task(states, lambda a, v, lp: None)
