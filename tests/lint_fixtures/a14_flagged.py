"""A14 flagged fixture: ad-hoc serving planes outside predict/."""
from distributed_ba3c_tpu.predict.server import BatchedPredictor


def stand_up_private_plane(model, params, states):
    # direct construction outside predict/: an unrouted serving plane
    pred = BatchedPredictor(model, params, batch_size=8)
    pred.warmup((4, 4, 2))
    # dispatch at the locally-constructed predictor: traffic that
    # bypasses the router's overflow/health/canary machinery
    pred.put_block_task(states, lambda a, v, lp: None)
    return pred


def another_ctor_shape(server, model, params):
    # dotted construction resolves the same way
    return server.BatchedPredictor(model, params)
