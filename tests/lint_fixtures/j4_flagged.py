"""J4 flagged: PRNGKey consumed repeatedly without split."""
import jax


def sample_twice(shape):
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)  # J4: identical randomness
    return a, b


def sample_loop(shapes):
    key = jax.random.PRNGKey(1)
    outs = []
    for s in shapes:
        outs.append(jax.random.normal(key, s))  # J4: same draw every iter
    return outs
