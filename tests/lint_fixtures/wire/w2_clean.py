"""W2 must stay quiet: the optional tail read is length-guarded, so old
senders' shorter frames keep parsing (append-only, positions pinned)."""

from distributed_ba3c_tpu.utils import serialize  # noqa: F401  wire-scope marker


def header_tail(meta):
    if len(meta) < 3:
        raise ValueError("short header")
    ident, step, b = meta[0], meta[1], meta[2]
    tele = meta[3] if len(meta) > 3 else None
    return ident, step, b, tele
