"""W2 must fire: a read past the validated base length with no covering
length guard — old senders' shorter frames would IndexError here."""

from distributed_ba3c_tpu.utils import serialize  # noqa: F401  wire-scope marker


def header_tail(meta):
    if len(meta) < 3:
        raise ValueError("short header")
    ident, step, b = meta[0], meta[1], meta[2]
    tele = meta[3]
    return ident, step, b, tele
