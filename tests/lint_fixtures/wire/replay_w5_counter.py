"""Historical replay: the PR 5 sign-mixed reward counter.

Before PR 5 raw Atari rewards (Pong: −1) were accumulated into ONE
counter-typed series; the decreasing value read as a counter reset to
Prometheus ``rate()``. The fix split the series by sign — W5 must flag
the unguarded negated increment that recreates the bug."""

from distributed_ba3c_tpu import telemetry

tele = telemetry.registry("simulator")
c_rew = tele.counter("reward_pos_sum")


def account(reward):
    c_rew.inc(-reward)
