"""Real W6 findings masked by a trailing and a standalone suppression —
the filtered run must be clean, the raw run must see both."""

import msgpack


def ship_trailing(sock, obj):
    sock.send(msgpack.packb(obj))  # ba3cwire: disable=W6 — fixture: trailing form


def ship_standalone(sock, obj):
    # ba3cwire: disable=W6 — fixture: standalone form covers next line
    sock.send(msgpack.packb(obj))
