"""W3 must fire twice: a bare decode straight off the socket, and a call
into a helper whose decode can raise back into the receive loop."""

from distributed_ba3c_tpu.utils.serialize import loads


def _decode(raw):
    return loads(raw)


def pump_bare(sock, out):
    while True:
        out.append(loads(sock.recv()))


def pump_chained(sock, out):
    while True:
        out.append(_decode(sock.recv()))
