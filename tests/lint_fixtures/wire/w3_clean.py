"""W3 must stay quiet: one loop wraps the decode so a corrupt frame
continues it, the other contains the decode (and its accounting) in the
callee — both count the reject, so W4 stays quiet too."""

from distributed_ba3c_tpu.utils.serialize import loads


def _decode_safe(raw, counter):
    try:
        return loads(raw)
    except ValueError:
        counter.inc()
        return None


def pump_wrapped(sock, out, counter):
    while True:
        raw = sock.recv()
        try:
            msg = loads(raw)
        except ValueError:
            counter.inc()
            continue
        out.append(msg)


def pump_contained(sock, out, counter):
    while True:
        out.append(_decode_safe(sock.recv(), counter))
