"""W4 must fire: the decode-failure handler discards the message without
incrementing any reject counter — drops vanish from /metrics."""

from distributed_ba3c_tpu.utils.serialize import loads


def handle_once(sock):
    raw = sock.recv()
    try:
        return loads(raw)
    except ValueError:
        return None
