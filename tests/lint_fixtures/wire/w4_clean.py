"""W4 must stay quiet: the reject is counted — once directly in the
handler, once through a callee (the interprocedural witness)."""

from distributed_ba3c_tpu.utils.serialize import loads


def _count_reject(counter):
    counter.inc()


def handle_direct(sock, counter):
    raw = sock.recv()
    try:
        return loads(raw)
    except ValueError:
        counter.inc()
        return None


def handle_via_callee(sock, counter):
    raw = sock.recv()
    try:
        return loads(raw)
    except ValueError:
        _count_reject(counter)
        return None
