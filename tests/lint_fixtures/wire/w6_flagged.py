"""W6 must fire twice: raw msgpack outside the codec layer, and an
explicit ``crc=False`` opt-out at a non-codec call site."""

import msgpack

from distributed_ba3c_tpu.utils.serialize import dumps


def ship_raw(sock, obj):
    sock.send(msgpack.packb(obj))


def ship_uncovered(sock, obj):
    sock.send(dumps(obj, crc=False))
