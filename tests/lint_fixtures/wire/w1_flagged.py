"""W1 must fire twice: an orphan packer, and a pack/unpack pair whose
frame counts disagree (the unpacker indexes past what the packer emits)."""

from distributed_ba3c_tpu.utils.serialize import dumps


def pack_orphan(meta):
    return [dumps(meta)]


def pack_pair(header, payload):
    return [dumps(header), payload]


def unpack_pair(frames):
    return frames[0], frames[1], frames[2]
