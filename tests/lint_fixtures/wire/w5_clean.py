"""W5 must stay quiet: documented series names, and the negated
increment sits under a ``< 0`` sign-split guard (the PR 5 idiom)."""

from distributed_ba3c_tpu import telemetry

tele = telemetry.registry("simulator")
c_pos = tele.counter("reward_pos_sum")
c_neg = tele.counter("reward_neg_sum")


def account(reward):
    if reward > 0:
        c_pos.inc(reward)
    elif reward < 0:
        c_neg.inc(-reward)
