"""W1 must stay quiet: both halves pair up and frame counts agree."""

from distributed_ba3c_tpu.utils.serialize import dumps


def pack_pair(header, payload):
    return [dumps(header), payload]


def unpack_pair(frames):
    return frames[0], frames[1]
