"""W6 must stay quiet: every send routes through the CRC-capable codec
layer with no opt-out."""

from distributed_ba3c_tpu.utils.serialize import dumps


def ship(sock, obj):
    sock.send(dumps(obj))
