"""W5 must fire five times: a gauge wearing the ``*_total`` counter
suffix (also undocumented), ``set()`` on a counter, a negative-literal
``inc``, and a negated ``inc`` with no dominating sign guard."""

from distributed_ba3c_tpu import telemetry

tele = telemetry.registry("fixture")
g_bad = tele.gauge("wire_fixture_widgets_total")
c_steps = tele.counter("env_steps_total")


def account(delta):
    c_steps.set(0)
    c_steps.inc(-5)
    c_steps.inc(-delta)
