"""A suppression that masks nothing — --check-suppressions must flag it."""


def harmless(meta):
    return meta[0]  # ba3cwire: disable=W2 — stale: nothing optional is read here
