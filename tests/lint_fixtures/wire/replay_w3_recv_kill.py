"""Historical replay: the PR 14 receive-loop kill.

Before PR 14 the master's experience pump decoded straight off the
socket inside its poller loop — one corrupt frame from ONE env server
raised out of the loop and silently starved EVERY peer (the fleet looked
alive; throughput went to zero). W3 must flag the bare decode."""

import zmq

from distributed_ba3c_tpu.utils.serialize import loads


def master_pump(sock, handle):
    poller = zmq.Poller()
    poller.register(sock, zmq.POLLIN)
    while True:
        if not poller.poll(100):
            continue
        frames = sock.recv_multipart()
        handle(loads(frames[0]))
