"""A15 flagged fixture: hand-rolled supervision loops outside orchestrate/."""
import subprocess
import time


def shadow_supervisor(worker_factory):
    # the closed observe+respawn cycle: polls liveness AND restarts in
    # the same loop — unbudgeted, uncounted, no decision trail
    worker = worker_factory()
    worker.start()
    while True:
        if not worker.is_alive():
            worker = worker_factory()
            worker.start()
        time.sleep(0.5)


def child_babysitter(argv, n):
    # subprocess flavor: .poll() liveness + fresh Popen respawn
    child = subprocess.Popen(argv)
    for _ in range(n):
        if child.poll() is not None:
            child = subprocess.Popen(argv)
        time.sleep(1)
