"""A10 clean fixture: the idioms the repo actually uses (must stay quiet)."""


def serve(predictor, states):
    # reads go through the serving surface, never the policy table
    actions, values, greedy = predictor.predict_batch(states)
    return actions


def snapshot(state):
    # train-state params access is not a predictor policy-table read
    return state.params


class Cache:
    """A non-predictor holder may keep a private _params of its own."""

    def __init__(self):
        self._params = None

    def apply(self, params):
        self._params = params
        return self._params


def tune(scheduler, params):
    # an unrelated update_params API (non-predictor receiver) is not a
    # params publish — the rule must not force a bogus suppression here
    scheduler.update_params(params)
