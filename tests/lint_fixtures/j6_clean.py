"""J6 clean: the overlap facade idiom — both dispatches async, sync after."""
import jax
import numpy as np


def actor_fn(params, astate):
    return astate, astate


def learner_fn(train, block):
    return train, {}


actor_jit = jax.jit(actor_fn, donate_argnums=(1,))
learner_jit = jax.jit(learner_fn, donate_argnums=(0,))


def overlap_loop(train, astate, block, n):
    """The clean schedule: actor and learner enqueued back-to-back, no
    host sync in between; the caller fetches metrics once per window."""
    for _ in range(n):
        astate, next_block = actor_jit(train, astate)
        train, m = learner_jit(train, block)
        block = next_block
    return train, astate, block, m


def window_fetch(train, astate, block, n):
    for _ in range(n):
        astate, next_block = actor_jit(train, astate)
        train, m = learner_jit(train, block)
        block = next_block
    # sync AFTER both dispatches is the contract (once per window)
    jax.block_until_ready(block)
    return np.asarray(block)


def actor_only_consumer(params, astate):
    # no learner in scope: a plain actor caller may inspect its output
    # (J1 still governs loops; J6 is about the two-program schedule)
    astate, block = actor_jit(params, astate)
    return jax.device_get(block)
