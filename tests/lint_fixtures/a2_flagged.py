"""A2 flagged: blocking queue ops with no timeout."""


class Pump:
    def __init__(self, in_queue, out_queue):
        self.in_queue = in_queue
        self.out_queue = out_queue

    def drain_forever(self):
        while True:
            item = self.in_queue.get()  # A2: never re-checks the stop flag
            self.out_queue.put(item)  # A2: wedges when the consumer dies
