"""A11 clean fixture: the sanctioned span / monotonic-pair shapes."""
import time

from distributed_ba3c_tpu.telemetry import tracing


def context_manager_span(trace_id, parent_id):
    with tracing.span(trace_id, "collate", "learner", parent=parent_id):
        return 1


def explicit_finish(trace_id):
    s = tracing.span(trace_id, "ingest", "learner")
    try:
        return 1
    finally:
        s.finish()


def monotonic_into_histogram(hist, t0):
    # the sanctioned in-place shape: the pair feeds the telemetry plane
    # in the same statement
    hist.observe(time.monotonic() - t0)


def monotonic_non_metric(t0, deadline_s):
    # duration math that is not metric accounting (timeouts, waits)
    # stays fine — A11 polices latency *reporting*, not arithmetic
    remaining = deadline_s - (time.monotonic() - t0)
    return remaining > 0
