"""J2 clean: jit constructed once, called in the loop."""
import jax


def make_step(fn):
    return jax.jit(fn)  # constructed once per factory call


def sweep(fn, xs):
    jitted = jax.jit(fn)  # hoisted out of the loop
    return [jitted(x) for x in xs]
