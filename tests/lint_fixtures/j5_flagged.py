"""J5 flagged: donated buffer read after the donating call."""
import jax


def train_step(state, batch):
    return state


jitted = jax.jit(train_step, donate_argnums=(0,))


def run(state, batch, predictor):
    new_state = jitted(state, batch)
    predictor.update(state)  # J5: `state` was donated — buffer is gone
    return new_state
