"""J5 flagged: donated buffer read after the donating call (2 findings)."""
import jax

from distributed_ba3c_tpu.audit import tripwire_jit


def train_step(state, batch):
    return state


jitted = jax.jit(train_step, donate_argnums=(0,))


def run(state, batch, predictor):
    new_state = jitted(state, batch)
    predictor.update(state)  # J5: `state` was donated — buffer is gone
    return new_state


# the hot-path sites jit through the audit tripwire — same donation rules
wired = tripwire_jit("fixture.step", train_step, donate_argnums=(0,))


def run_wired(state, batch, predictor):
    new_state = wired(state, batch)
    predictor.update(state)  # J5: donated through tripwire_jit
    return new_state
