"""A1 clean: stoppable wrappers and managed processes."""
from distributed_ba3c_tpu.utils.concurrency import (
    LoopThread,
    StoppableThread,
    ensure_proc_terminate,
    start_proc_mask_signal,
)


def start_worker(fn):
    t = StoppableThread(target=fn, daemon=True)
    t.start()
    return t


def start_pump(fn):
    return LoopThread(fn)


def start_children(procs):
    ensure_proc_terminate(procs)
    start_proc_mask_signal(procs)
