"""A7-clean: the idioms the real codebase uses — registry metrics, logger
output, and wall-clock timestamps only where they leave the process."""

import time

from distributed_ba3c_tpu import telemetry

_steps = telemetry.registry("master").counter("env_steps_total")
_wait = telemetry.registry("master").histogram("queue_put_wait_s", unit=1e-6)


def account(n: int, waited_s: float) -> None:
    # metric accounting through the registry: scrape/stat.json/fleet all
    # see it, and the internals are monotonic
    _steps.inc(n)
    _wait.observe(waited_s)


def export_record(channel: str, value: float) -> dict:
    # a wall timestamp that LEAVES the process (experiment log) is what
    # time.time() is for
    return {"channel": channel, "value": value, "ts": time.time()}


def console(logger, epoch: int, score: float) -> None:
    # logger output (not print), no hand-rolled rate math
    logger.info("epoch %d | score %.2f", epoch, score)
    print("episode finished with score", score)  # non-metric print is fine
