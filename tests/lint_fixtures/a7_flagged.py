"""A7 fixture: ad-hoc metric accounting that belongs in the telemetry
registry (docs/observability.md). Every pattern here is invisible to the
scrape endpoint, stat.json and the fleet series."""

import time


class Plane:
    def __init__(self, q):
        self.q = q
        self.n = 0
        self.started = time.monotonic()

    def report(self):
        # time.time()-based rate math (also wall-clock — A4's territory)
        fps = self.n / (time.time() - self.started)
        # print-based metric reporting: f-string fragment
        print(f"plane fps {fps:.1f}")
        # print-based metric reporting: plain-string fragment
        print("train queue qsize:", self.q.qsize())
        # print-based metric reporting: rate-unit fragment
        print("serving " + str(self.n) + " env-steps/sec")
