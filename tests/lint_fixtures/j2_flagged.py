"""J2 flagged: jax.jit constructed inside loop bodies."""
import jax


def sweep(fns, x):
    outs = []
    for fn in fns:
        jitted = jax.jit(fn)  # J2: fresh cache + retrace every iteration
        outs.append(jitted(x))
    return outs


def poll(fn, x):
    while True:
        y = jax.jit(fn)(x)  # J2
        if y is not None:
            return y
