"""A10 fixture: unversioned predictor params access (must be flagged)."""


def publish_sideways(predictor, params):
    # a stray publish outside the versioned plane: no version names these
    # weights, the pod's params_lag stamp becomes a lie
    predictor.update_params(params)


def fan_out(predictors, params):
    for pred in predictors:
        pred.update_params(params, policy="default")


def poke_policy_table(predictor):
    # reading the predictor's policy table directly bypasses the same
    # accounting on the read side
    stale = predictor._params
    predictor._policies["default"] = stale
    return stale
