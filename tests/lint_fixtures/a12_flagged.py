"""A12 fixture: blocking ZMQ waits with no bound."""
import zmq


def bare_recv_parks_forever(context, addr):
    dealer = context.socket(zmq.DEALER)
    dealer.connect(addr)
    return dealer.recv()  # no poller, no NOBLOCK, no RCVTIMEO


def bare_send_parks_on_full_peer(push_sock, frames):
    push_sock.send_multipart(frames)  # no bound: a partitioned PULL wedges this
