"""Historical replay: the admission-counter decrement race.

The shed callback decremented ``_admitting`` without the lock the
admit path guards it with, so a racing decrement could be lost and the
gate stuck counting phantom in-flight tasks. F1's guard-discipline
facet catches exactly this shape."""

import threading


class AdmissionGate:

    def __init__(self, cap):
        self._lock = threading.Lock()
        self._cap = cap
        self._admitting = 0

    def try_admit(self):
        with self._lock:
            if self._admitting >= self._cap:
                return False
            self._admitting += 1
        return True

    def on_shed(self):
        self._admitting -= 1
