"""Real F1 findings masked by a trailing and a standalone suppression —
the filtered run must be clean, the raw run must see both."""

import threading


class Admission:

    def __init__(self):
        self._lock = threading.Lock()
        self._admitting = 0

    def try_admit(self):
        with self._lock:
            if self._admitting >= 4:
                return False
            self._admitting += 1
        return True

    def on_shed(self):
        self._admitting -= 1  # ba3cflow: disable=F1 — fixture: trailing form

    def on_timeout(self):
        # ba3cflow: disable=F1 — fixture: standalone form covers next line
        self._admitting -= 1
