"""F2 must fire: one path takes _alock then _block, the other _block
then _alock — two threads can each hold one and wait forever."""

import threading


class Ledger:

    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.credits = 0
        self.debits = 0

    def credit(self):
        with self._alock:
            with self._block:
                self.credits += 1

    def debit(self):
        with self._block:
            with self._alock:
                self.debits += 1
