"""F4 must fire twice: an untimed .join() inside a lock-held region, and
a self.join() reachable from the thread's own run()."""

import threading


class Reaper(threading.Thread):

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self.workers = []

    def shutdown(self):
        with self._lock:
            for w in self.workers:
                w.join()

    def run(self):
        self._finish()

    def _finish(self):
        self.join()
