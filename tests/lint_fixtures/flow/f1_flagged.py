"""F1 must fire: blocking ops reachable while a lock is held, and an
attribute guarded in one method but written bare in another."""

import queue
import threading
import time


class Worker(threading.Thread):

    def __init__(self):
        super().__init__()
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self.inq = queue.Queue()
        self._depth = 0

    def run(self):
        while True:
            if self._stop_evt.is_set():
                return
            item = self.inq.get(timeout=0.2)
            with self._lock:
                # transitively blocking: _handle sleeps
                self._handle(item)

    def _handle(self, item):
        time.sleep(0.1)
        self._depth += 1

    def enqueue(self, item):
        with self._lock:
            # direct: untimed queue put under the lock
            self.inq.put(item)

    def drain(self):
        # guard discipline: _depth is written under _lock in _handle
        # (always called locked) but bare here
        self._depth = 0
