"""F5 must stay quiet: whoever starts the thread joins it."""

import threading


def _work():
    return None


class Owner:

    def __init__(self):
        self._t = threading.Thread(target=_work, daemon=True)

    def start(self):
        self._t.start()

    def stop(self):
        self._t.join(timeout=2.0)
