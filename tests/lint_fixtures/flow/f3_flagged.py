"""F3 must fire: the thread body spins forever with no stop-flag check,
break, or return — stop()/join() can never reclaim it."""

import threading


class Pump(threading.Thread):

    def __init__(self):
        super().__init__()
        self.backlog = []

    def run(self):
        while True:
            self._drain()

    def _drain(self):
        if self.backlog:
            self.backlog.pop()
