"""A suppression that masks nothing — --check-suppressions must flag it."""

import threading


class Quiet:

    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1  # ba3cflow: disable=F2 — stale: nothing inverts here
