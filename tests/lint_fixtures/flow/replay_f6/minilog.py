"""Mini logger module for the replay: info/warning/error exist,
``exception`` does not — same surface as utils/logger at the time."""


def info(msg, *args):
    return None


def warning(msg, *args):
    return None


def error(msg, *args):
    return None
