"""Historical replay: the ``logger.exception`` latent AttributeError.

The error path of a guarded tick called a logger function that the
project logger module never defined, so the handler that was supposed
to contain failures raised INSIDE the except block. It sat latent for
nine PRs because the happy path never entered the handler. F6 resolves
the call against the module's real top-level names."""

from tests.lint_fixtures.flow.replay_f6 import minilog


def guarded_tick(tick):
    try:
        tick()
    except Exception as e:
        minilog.exception("tick failed: %r", e)


def healthy_tick(tick):
    try:
        tick()
    except Exception as e:
        minilog.error("tick failed: %r", e)
