"""F6 must stay quiet: every attribute call resolves statically."""


class Task:

    def __init__(self):
        self.payload = None

    def cancel(self):
        self.payload = None


def handle(task: Task):
    task.cancel()
