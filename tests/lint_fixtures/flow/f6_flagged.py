"""F6 must fire: a method call on a project-typed object that no class in
the MRO defines — the call raises AttributeError at runtime."""


class Task:

    def __init__(self):
        self.payload = None

    def cancel(self):
        self.payload = None


def handle(task: Task):
    task.cancle("late")
