"""F4 must stay quiet: the worker list is snapshotted under the lock but
joined outside it, with a bound; run() never joins itself."""

import threading


class Reaper(threading.Thread):

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self.workers = []

    def shutdown(self):
        with self._lock:
            workers = list(self.workers)
        for w in workers:
            w.join(timeout=1.0)

    def run(self):
        self._finish()

    def _finish(self):
        return None
