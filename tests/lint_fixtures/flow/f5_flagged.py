"""F5 must fire: the owner constructs and starts a thread but neither
stops nor joins it — shutdown leaks the thread."""

import threading


def _work():
    return None


class Owner:

    def __init__(self):
        self._t = threading.Thread(target=_work, daemon=True)

    def start(self):
        self._t.start()

    def stop(self):
        return None
