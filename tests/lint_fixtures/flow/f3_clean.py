"""F3 must stay quiet: the while-True body observes the stop event."""

import threading


class Pump(threading.Thread):

    def __init__(self):
        super().__init__()
        self._stop_evt = threading.Event()
        self.backlog = []

    def run(self):
        while True:
            if self._stop_evt.is_set():
                break
            self._drain()

    def _drain(self):
        if self.backlog:
            self.backlog.pop()
