"""ba3cflow fixtures: each F-rule has a *_flagged.py / *_clean.py pair,
plus historical replays of bugs that shipped (and were later caught) in
this repo. Never imported — the analyzer parses them as source."""
