"""F1 must stay quiet: blocking work happens outside the lock, queue ops
are bounded, and the guarded counter is written under the lock everywhere."""

import queue
import threading
import time


class Worker(threading.Thread):

    def __init__(self):
        super().__init__()
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self.inq = queue.Queue()
        self._depth = 0

    def run(self):
        while True:
            if self._stop_evt.is_set():
                return
            item = self.inq.get(timeout=0.2)
            self._handle(item)
            with self._lock:
                self._depth += 1

    def _handle(self, item):
        time.sleep(0.01)

    def enqueue(self, item):
        self.inq.put(item, timeout=1.0)

    def drain(self):
        with self._lock:
            self._depth = 0
