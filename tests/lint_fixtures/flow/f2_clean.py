"""F2 must stay quiet: both paths honor the same acquisition order."""

import threading


class Ledger:

    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.credits = 0
        self.debits = 0

    def credit(self):
        with self._alock:
            with self._block:
                self.credits += 1

    def debit(self):
        with self._alock:
            with self._block:
                self.debits += 1
