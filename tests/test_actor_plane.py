"""Actor plane: n-step parse logic + live ZMQ simulator↔master integration."""

import functools
import queue
import time

import jax
import numpy as np
import pytest

from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
from distributed_ba3c_tpu.actors.simulator import (
    SimulatorProcess,
    TransitionExperience,
    default_pipes,
)
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs.fake import build_fake_player
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.returns import discounted_returns_np
from distributed_ba3c_tpu.predict.server import BatchedPredictor
from distributed_ba3c_tpu.utils.concurrency import ensure_proc_terminate


class _NullPredictor:
    """Predictor stub for parse-logic tests (never called)."""

    def put_task(self, state, cb, **kw):
        raise AssertionError("should not be called")


def _make_master(tmp_path, gamma=0.5, local_time_max=3):
    c2s = f"ipc://{tmp_path}/c2s"
    s2c = f"ipc://{tmp_path}/s2c"
    return BA3CSimulatorMaster(
        c2s,
        s2c,
        _NullPredictor(),
        gamma=gamma,
        local_time_max=local_time_max,
        score_queue=queue.Queue(),
    )


def test_parse_memory_episode_over(tmp_path):
    m = _make_master(tmp_path, gamma=0.5)
    ident = b"sim-0"
    client = m.clients[ident]
    rewards = [1.0, 0.0, 2.0]
    for t, r in enumerate(rewards):
        client.memory.append(
            TransitionExperience(np.full((4, 4), t, np.uint8), t % 2, value=9.9, reward=r)
        )
    m._parse_memory(0.0, ident, is_over=True)
    got = [m.queue.get_nowait() for _ in range(3)]
    # queue receives transitions newest-first; returns = discounted suffix sums
    expected_R = discounted_returns_np(np.array(rewards), 0.0, 0.5)
    states_t = [int(dp[0][0, 0]) for dp in got]
    assert states_t == [2, 1, 0]
    for dp in got:
        t = int(dp[0][0, 0])
        assert dp[2] == pytest.approx(expected_R[t])
    assert client.memory == []


def test_parse_memory_truncation_bootstraps_from_value(tmp_path):
    m = _make_master(tmp_path, gamma=0.5, local_time_max=2)
    ident = b"sim-1"
    client = m.clients[ident]
    # local_time_max+1 = 3 transitions; last one's VALUE bootstraps
    for t, (r, v) in enumerate([(1.0, 0.0), (0.0, 0.0), (0.5, 4.0)]):
        client.memory.append(
            TransitionExperience(np.full((2, 2), t, np.uint8), t, value=v, reward=r)
        )
    m._on_datapoint(ident)
    got = [m.queue.get_nowait() for _ in range(2)]
    # R(t=1) = 0.0 + 0.5*4.0 = 2.0 ; R(t=0) = 1.0 + 0.5*2.0 = 2.0
    assert got[0][2] == pytest.approx(2.0) and int(got[0][0][0, 0]) == 1
    assert got[1][2] == pytest.approx(2.0) and int(got[1][0][0, 0]) == 0
    # newest transition kept for the next window
    assert len(client.memory) == 1 and client.memory[0].value == 4.0


def test_reward_clip_applies_to_learning_not_scores(tmp_path):
    """reward_clip bounds the learner's rewards via the REAL message path;
    episode scores stay raw."""

    class _NoPredictMaster(BA3CSimulatorMaster):
        def _on_state(self, state, ident):  # skip the predictor round-trip
            pass

    score_q = queue.Queue()
    m = _NoPredictMaster(
        f"ipc://{tmp_path}/c",
        f"ipc://{tmp_path}/s",
        _NullPredictor(),
        gamma=0.0,
        local_time_max=3,
        score_queue=score_q,
        reward_clip=1.0,
    )
    ident = b"sim-9"
    client = m.clients[ident]
    client.ident = ident
    client.memory.append(
        TransitionExperience(np.zeros((2, 2), np.uint8), 0, value=0.0)
    )
    # a +25 reward arrives with episode end: the base _on_message attaches
    # the clipped learning reward and accumulates the raw score
    try:
        m._on_message(ident, np.zeros((2, 2), np.uint8), 25.0, True)
        _, _, R = m.queue.get_nowait()
        assert R == 1.0  # clipped learning signal
        assert score_q.get_nowait() == 25.0  # raw episode score
    finally:
        m.close()  # never leak the ZMQ context/threads into later tests


def test_zmq_actor_plane_end_to_end(tmp_path):
    """2 FakeEnv simulator processes stream through a real predictor; the
    train queue fills with well-formed n-step datapoints."""
    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=4)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=4, num_threads=1)

    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s,
        s2c,
        predictor,
        gamma=cfg.gamma,
        local_time_max=cfg.local_time_max,
        score_queue=queue.Queue(maxsize=100),
    )
    build = functools.partial(
        build_fake_player,
        image_size=cfg.image_size,
        frame_history=cfg.frame_history,
        num_actions=cfg.num_actions,
    )
    procs = [SimulatorProcess(i, c2s, s2c, build) for i in range(2)]
    ensure_proc_terminate(procs)

    predictor.start()
    master.start()
    for p in procs:
        p.start()

    try:
        datapoints = []
        deadline = time.time() + 120
        while len(datapoints) < 64 and time.time() < deadline:
            try:
                datapoints.append(master.queue.get(timeout=5))
            except queue.Empty:
                pass
        assert len(datapoints) >= 64, "actor plane produced too few datapoints"
        for state, action, ret in datapoints:
            assert state.shape == cfg.state_shape and state.dtype == np.uint8
            assert 0 <= action < cfg.num_actions
            # returns bounded: rewards in {0,1}, bootstrap values finite
            assert np.isfinite(ret)
        # episodes complete -> scores flow
        assert master.score_queue.qsize() >= 1
    finally:
        for p in procs:
            p.terminate()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)
