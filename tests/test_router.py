"""The replicated serving plane (predict/router.py + orchestrate/serving.py,
docs/serving.md, ISSUE 15).

Router mechanics are tested against deterministic fake replicas (manual
serve pumps, injectable health signals, a fake clock shared with the
router) so every state transition is driven explicitly; one integration
test runs REAL BatchedPredictor replicas and kills one scheduler thread
mid-load — the in-process analogue of a SIGKILLed replica process — to
prove the typed-shed rebalance end to end.
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.serving import (
    PromotionController,
    ReplicaAutoscaler,
    ReplicaSet,
    ServingScalerPolicy,
    welch_z,
)
from distributed_ba3c_tpu.predict.router import (
    DEAD,
    DRAINING,
    UP,
    ServingRouter,
    http_replica_signals,
    replica_role,
    replica_signals,
    signals_from_snapshot,
)
from distributed_ba3c_tpu.predict.server import BatchedPredictor, ShedReject

N_ACTIONS = 4
STATE = (4, 4, 2)


class _FakeClock:
    def __init__(self):
        self.t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        return self.t

    def advance(self, dt):
        with self._lock:
            self.t += dt


class FakeReplica:
    """Deterministic replica: tasks queue until the test pumps
    ``serve()``; a bounded cap fast-rejects like the real admission
    queue; health is whatever the test injects."""

    num_actions = N_ACTIONS

    def __init__(self, cap=64):
        self.cap = cap
        self.tasks = []  # (states, k, policy, cb, shed_cb)
        self.policies = {"default": None}
        self.published = []
        self.alive = True
        self.scrape_fails = False
        self.stopped = False
        self.rows = 0
        self.sheds = 0

    # -- the predictor caller surface -----------------------------------
    def put_block_task(self, states, cb, deadline=None, policy=None,
                       shed_callback=None, trace=None):
        return self._put(states, states.shape[0], policy, cb, shed_callback)

    def put_task(self, state, cb, deadline=None, policy=None,
                 shed_callback=None, trace=None):
        return self._put(state, 1, policy, cb, shed_callback)

    def _put(self, states, k, policy, cb, shed_cb):
        if policy is not None and policy not in self.policies:
            raise KeyError(f"unknown policy {policy!r}")
        if len(self.tasks) >= self.cap or self.stopped:
            if shed_cb is not None:
                shed_cb(ShedReject(
                    "shutdown" if self.stopped else "queue_full"
                ))
            self.sheds += k
            return False
        self.tasks.append((states, k, policy, cb, shed_cb))
        return True

    def add_policy(self, pid, params):
        self.policies[pid] = params

    def update_params(self, params, policy="default"):
        self.published.append((policy, params))
        self.policies[policy] = params

    def predict_batch(self, states):
        return "sync-answer"

    def start(self):
        pass

    def stop(self):
        self.stopped = True

    def join(self, timeout=None):
        pass

    # -- test controls ---------------------------------------------------
    def serve(self, n=None):
        """Resolve the oldest ``n`` queued tasks (all when None)."""
        n = len(self.tasks) if n is None else n
        for _ in range(min(n, len(self.tasks))):
            states, k, policy, cb, _ = self.tasks.pop(0)
            self.rows += k
            acts = np.zeros(k, np.int32)
            if k == 1:
                cb(0, 0.0, -1.0)
            else:
                cb(acts, np.zeros(k, np.float32), np.full(k, -1.0))

    def signals(self):
        if self.scrape_fails:
            raise ConnectionError("scrape target gone")
        return {
            "alive": 1.0 if self.alive else 0.0,
            "rows_total": float(self.rows),
            "sheds_total": float(self.sheds),
            "queue_depth": float(len(self.tasks)),
            "inflight": 0.0,
            "serve_p99_ms": 1.0,
        }


def _router(n_replicas=2, cap=64, **kw):
    telemetry.reset_all()
    clock = _FakeClock()
    kw.setdefault("health_interval_s", 3600.0)  # ticks driven manually
    router = ServingRouter(clock=clock, **kw)
    reps = [FakeReplica(cap=cap) for _ in range(n_replicas)]
    for i, rep in enumerate(reps):
        router.add_replica(f"r{i}", rep, signals=rep.signals)
    return router, reps, clock


def _block(k=4):
    return np.zeros((k, *STATE), np.uint8)


def _router_scalar(name):
    return telemetry.registry("router").scalars().get(name, 0.0)


def _flight_events(kind):
    return [
        ev for ev in telemetry.flight_recorder().snapshot()
        if ev.get("kind") == kind
    ]


# -- dispatch ---------------------------------------------------------------


def test_least_loaded_dispatch_balances_rows():
    router, (r0, r1), _ = _router()
    for _ in range(4):
        router.put_block_task(_block(4), lambda a, v, lp: None)
    # nothing served yet: outstanding rows steer each block to the
    # emptier replica — 2 blocks each, never 3/1
    assert len(r0.tasks) == 2 and len(r1.tasks) == 2
    assert router.outstanding_rows() == 16
    r0.serve()
    r1.serve()
    assert router.outstanding_rows() == 0
    assert _router_scalar("routed_rows_total") == 16
    assert _router_scalar("routed_r0_rows_total") == 8
    assert _router_scalar("routed_r1_rows_total") == 8


def test_slow_replica_gets_less_traffic():
    router, (r0, r1), _ = _router()
    served = []
    for i in range(8):
        router.put_block_task(_block(2), lambda a, v, lp: served.append(1))
        # r1 serves immediately; r0 never does — its backlog repels load
        r1.serve()
    assert len(r0.tasks) == 1  # only the very first block landed on r0
    assert _router_scalar("routed_r1_rows_total") == 14


def test_overflow_fails_over_before_shedding():
    router, (r0, r1), _ = _router(cap=1)
    sheds = []
    # two blocks fill both replicas (cap 1 each)
    assert router.put_block_task(_block(2), lambda *a: None,
                                 shed_callback=sheds.append)
    assert router.put_block_task(_block(2), lambda *a: None,
                                 shed_callback=sheds.append)
    assert not sheds
    # the third finds BOTH full: one typed reject after trying every
    # replica, exactly once
    ok = router.put_block_task(_block(2), lambda *a: None,
                               shed_callback=sheds.append)
    assert ok is False
    assert len(sheds) == 1
    assert sheds[0].reason == "queue_full"
    assert _router_scalar("overflow_retries_total") >= 2
    assert _router_scalar("overflow_exhausted_total") == 1
    # overflow earlier: fill ONLY the least-loaded candidate and prove
    # the task lands on the other instead of shedding
    r0.serve()
    r1.serve()
    r0.cap = 0  # r0 now always fast-rejects
    ok = router.put_block_task(_block(2), lambda *a: None,
                               shed_callback=sheds.append)
    assert ok is True
    assert len(sheds) == 1  # no new shed — the overflow path absorbed it
    assert len(r1.tasks) == 1


def test_no_replica_is_a_typed_shed():
    telemetry.reset_all()
    router = ServingRouter(clock=_FakeClock(), health_interval_s=3600.0)
    sheds = []
    ok = router.put_block_task(_block(2), lambda *a: None,
                               shed_callback=sheds.append)
    assert ok is False and sheds[0].reason == "no_replica"
    assert _router_scalar("no_replica_sheds_total") == 1


# -- health: drain / resume / dead ------------------------------------------


def test_stale_scrape_drains_then_resumes():
    router, (r0, r1), _ = _router()
    done = []
    # r0 takes one block, then its scrape goes stale
    router.put_block_task(_block(2), lambda a, v, lp: done.append(1))
    assert len(r0.tasks) == 1
    r0.scrape_fails = True
    for _ in range(router.drain_after):
        router.health_tick()
    assert router.replica_states()["r0"] == DRAINING
    assert _flight_events("replica_drain")
    # drained, NOT blackholed: new traffic avoids r0 ...
    for _ in range(3):
        router.put_block_task(_block(2), lambda a, v, lp: None)
    assert len(r0.tasks) == 1 and len(r1.tasks) == 3
    # ... while its in-flight task still resolves normally (through the
    # router's wrapper, so r0's outstanding accounting drains too)
    r0.serve()
    assert done == [1]
    assert router.outstanding_rows("r0") == 0
    # scrape recovers -> the replica resumes taking traffic
    r0.scrape_fails = False
    router.health_tick()
    assert router.replica_states()["r0"] == UP
    assert _flight_events("replica_resume")
    r1.serve()
    router.put_block_task(_block(2), lambda a, v, lp: None)
    assert len(r0.tasks) == 1


def test_dead_replica_resheds_outstanding_typed_and_rebalances():
    router, (r0, r1), _ = _router()
    sheds, served = [], []
    for _ in range(2):
        router.put_block_task(
            _block(4), lambda a, v, lp: served.append(1),
            shed_callback=sheds.append,
        )
    assert len(r0.tasks) == 1 and len(r1.tasks) == 1
    # r0's scheduler dies (the SIGKILL analogue): first health tick sees
    # alive=0 and re-sheds its outstanding task with the typed reject
    r0.alive = False
    router.health_tick()
    assert router.replica_states()["r0"] == DEAD
    assert len(sheds) == 1 and sheds[0].reason == "replica_lost"
    assert _router_scalar("replica_lost_sheds_total") == 4
    ev = _flight_events("replica_dead")
    assert ev and ev[0]["replica"] == "r0"
    # traffic rebalances to the survivor; nothing hangs
    for _ in range(3):
        router.put_block_task(
            _block(4), lambda a, v, lp: served.append(1),
            shed_callback=sheds.append,
        )
    assert len(r0.tasks) == 1  # the corpse's queue never grows
    r1.serve()
    assert len(served) == 4  # r1's original + the 3 rebalanced
    assert len(sheds) == 1


def test_canary_split_is_router_attributed():
    router, (r0, r1), _ = _router()
    router.add_policy("canary", {"w": "c"})
    # add_policy seeds EVERY replica synchronously
    assert r0.policies["canary"] == {"w": "c"}
    assert r1.policies["canary"] == {"w": "c"}
    router.set_canary("canary", 0.25)
    for _ in range(16):
        router.put_task(np.zeros(STATE, np.uint8), lambda a, v, lp: None)
    r0.serve()
    r1.serve()
    scal = telemetry.registry("router").scalars()
    assert scal["policy_canary_rows_total"] == 4
    assert scal["policy_default_rows_total"] == 12
    # the canary tasks were PINNED (the replicas saw the policy id), so
    # per-policy latency is router-attributed
    assert scal["policy_canary_serve_latency_s_count"] == 4
    router.set_canary(None)
    assert router.canary() is None
    with pytest.raises(KeyError):
        router.set_canary("ghost", 0.5)


def test_update_params_fans_out_async_and_promote_republishes():
    router, (r0, r1), _ = _router()
    router.add_policy("canary", {"v": "canary-params"})
    router.update_params({"v": 1})
    assert router.flush_params(10.0)
    assert ("default", {"v": 1}) in r0.published
    assert ("default", {"v": 1}) in r1.published
    router.promote("canary")
    assert router.flush_params(10.0)
    assert r0.published[-1] == ("default", {"v": "canary-params"})
    assert r1.published[-1] == ("default", {"v": "canary-params"})
    assert router.canary() is None
    router.stop()
    router.join(timeout=5)


# -- signal sources ----------------------------------------------------------


def test_signals_from_snapshot_and_http_source():
    telemetry.reset_all()
    reg = telemetry.registry("predictor")
    reg.counter("rows_total").inc(100)
    reg.counter("sheds_total").inc(7)
    h = reg.histogram("serve_latency_s", unit=1e-6)
    for _ in range(100):
        h.observe(0.004)
    s = signals_from_snapshot(reg.collect())
    assert s["rows_total"] == 100 and s["sheds_total"] == 7
    # log2 buckets: the p99 upper bound is within 2x of the true 4 ms
    assert 4.0 <= s["serve_p99_ms"] <= 8.2
    assert s["serve_hist"]["count"] == 100

    server = telemetry.TelemetryServer(port=0, host="127.0.0.1")
    server.start()
    try:
        src = http_replica_signals(
            f"http://127.0.0.1:{server.port}", role="predictor"
        )
        s2 = src()
        assert s2["rows_total"] == 100
        assert s2["serve_p99_ms"] == s["serve_p99_ms"]
        missing = http_replica_signals(
            f"http://127.0.0.1:{server.port}", role="predictor.r99"
        )
        with pytest.raises(KeyError, match="predictor.r99"):
            missing()
    finally:
        server.stop()
        server.join(timeout=5)
        server.close()


def test_replica_role_formula():
    assert replica_role("predictor", 3) == "predictor.r3"
    assert replica_role(telemetry.fleet_role("predictor", 1), 2) == \
        "predictor.f1.r2"


# -- the serving scaler ------------------------------------------------------


def test_serving_scaler_policy_decisions():
    pol = ServingScalerPolicy(
        slo_ms=50.0, patience=2, cooldown_ticks=2, step=1
    )
    breach = {"served_p99_ms": 49.0, "shed_rate": 0.0, "outstanding_rows": 10}
    ok = {"served_p99_ms": 5.0, "shed_rate": 0.0, "outstanding_rows": 10}
    mid = {"served_p99_ms": 30.0, "shed_rate": 0.0, "outstanding_rows": 10}
    unknown_busy = {"served_p99_ms": None, "shed_rate": 0.0,
                    "outstanding_rows": 10}
    idle = {"served_p99_ms": None, "shed_rate": 0.0, "outstanding_rows": 0}
    # pressure needs `patience` consecutive ticks
    assert pol.decide(breach) == (0, "")
    d, reason = pol.decide(breach)
    assert d == 1 and "SLO pressure" in reason
    # cooldown absorbs the next 2 ticks
    assert pol.decide(breach) == (0, "")
    assert pol.decide(breach) == (0, "")
    # shed-rate alone is a breach signal too
    shed = {"served_p99_ms": 5.0, "shed_rate": 0.5, "outstanding_rows": 0}
    pol.decide(shed)
    d, _ = pol.decide(shed)
    assert d == 1
    pol.decide(ok)
    pol.decide(ok)
    # relaxed after cooldown+patience -> scale down
    pol2 = ServingScalerPolicy(slo_ms=50.0, patience=2, cooldown_ticks=0)
    pol2.decide(ok)
    d, reason = pol2.decide(ok)
    assert d == -1 and "slack" in reason
    # the deadband holds still, and UNKNOWN p99 with work outstanding is
    # indeterminate (never reads as slack)
    pol3 = ServingScalerPolicy(slo_ms=50.0, patience=1, cooldown_ticks=0)
    assert pol3.decide(mid) == (0, "")
    assert pol3.decide(unknown_busy) == (0, "")
    # a provably idle window IS slack
    d, _ = pol3.decide(idle)
    assert d == -1
    with pytest.raises(ValueError):
        ServingScalerPolicy(slo_ms=0)


def test_replica_set_scales_and_autoscaler_records_decisions():
    telemetry.reset_all()
    clock = _FakeClock()
    router = ServingRouter(clock=clock, health_interval_s=3600.0)
    made = []

    def factory(idx):
        rep = FakeReplica()
        made.append(rep)
        return rep

    rs = ReplicaSet(
        router, factory, min_replicas=1, max_replicas=3,
        signals=lambda idx, pred: pred.signals, retire_grace_s=0.1,
    )
    rs.start(1)
    assert rs.target == 1 and router.live_count() == 1
    # SLO breach in the aggregate drives the autoscaler up
    auto = ReplicaAutoscaler(
        rs, ServingScalerPolicy(slo_ms=50.0, patience=1, cooldown_ticks=0),
        interval_s=3600.0,
    )
    router._agg = {"served_p99_ms": 49.0, "shed_rate": 0.0,
                   "replicas_live": 1.0, "outstanding_rows": 5.0}
    auto.tick()
    assert rs.target == 2 and router.live_count() == 2
    ev = _flight_events("serving_scale_decision")
    assert ev and ev[-1]["delta"] == 1 and ev[-1]["served_p99_ms"] == 49.0
    # incarnation ids are monotonic — the new replica is r1
    assert router.replica_ids() == ["r0", "r1"]
    # slack scales back down; the retired replica is stopped
    router._agg = {"served_p99_ms": 2.0, "shed_rate": 0.0,
                   "replicas_live": 2.0, "outstanding_rows": 0.0}
    auto.tick()
    assert rs.target == 1
    assert made[1].stopped
    # clamped at min_replicas: no decision recorded for a no-op
    n_dec = len(_flight_events("serving_scale_decision"))
    router._agg = {"served_p99_ms": 2.0, "shed_rate": 0.0,
                   "replicas_live": 1.0, "outstanding_rows": 0.0}
    auto.tick()
    assert rs.target == 1
    assert len(_flight_events("serving_scale_decision")) == n_dec
    rs.close()


def test_replica_set_reconcile_replaces_dead_replica():
    """A replica the router declares DEAD is swept out of the set and
    REPLACED by a fresh incarnation — a fixed-count deployment heals to
    its target without an autoscaler in the loop."""
    telemetry.reset_all()
    clock = _FakeClock()
    router = ServingRouter(clock=clock, health_interval_s=3600.0)
    made = []

    def factory(idx):
        rep = FakeReplica()
        made.append(rep)
        return rep

    rs = ReplicaSet(
        router, factory, min_replicas=2, max_replicas=4,
        signals=lambda idx, pred: pred.signals, retire_grace_s=0.1,
    )
    rs.start(2)
    assert router.replica_ids() == ["r0", "r1"]
    made[0].alive = False
    router.health_tick()
    assert router.replica_states()["r0"] == DEAD
    replaced = rs.reconcile()
    # the corpse is gone, a NEW incarnation (never a reused id) serves
    assert replaced == ["r2"]
    assert router.replica_ids() == ["r1", "r2"]
    assert rs.target == 2
    assert made[0].stopped
    ev = _flight_events("serving_replica_replace")
    assert ev and ev[-1]["dead"] == "r0" and ev[-1]["replacement"] == "r2"
    assert telemetry.registry("orchestrator").scalars()[
        "serving_replica_replacements_total"] == 1
    # traffic flows to the replacement
    served = []
    router.put_block_task(_block(2), lambda a, v, lp: served.append(1))
    router.put_block_task(_block(2), lambda a, v, lp: served.append(1))
    made[1].serve()
    made[2].serve()
    assert len(served) == 2
    rs.close()


def test_reconcile_retries_after_failed_respawn():
    """A RAISING respawn (factory/warmup failure) must not lose the slot
    forever: the corpse is already swept, so the next tick has no corpse
    to key off — reconcile heals to the pre-sweep count instead."""
    telemetry.reset_all()
    clock = _FakeClock()
    router = ServingRouter(clock=clock, health_interval_s=3600.0)
    made, fail = [], [False]

    def factory(idx):
        if fail[0]:
            raise RuntimeError("transient factory failure")
        rep = FakeReplica()
        made.append(rep)
        return rep

    rs = ReplicaSet(
        router, factory, min_replicas=2, max_replicas=4,
        signals=lambda idx, pred: pred.signals, retire_grace_s=0.1,
    )
    rs.start(2)
    made[0].alive = False
    router.health_tick()
    fail[0] = True
    assert rs.reconcile() == []  # respawn raised — no replacement yet
    assert rs.target == 1 and router.live_count() == 1
    # next tick: no corpse left, but the shortfall is retried and heals
    fail[0] = False
    replaced = rs.reconcile()
    assert replaced == ["r3"]
    assert rs.target == 2 and router.live_count() == 2
    rs.close()


def test_overflow_does_not_readmit_a_swept_task():
    """A death sweep racing a fast-reject resolves the task mid-overflow:
    the router must deliver that ONE typed outcome and stop — re-admitting
    the resolved task on a healthy replica would register rows that no
    resolution ever releases (the resolvers also deregister on the
    already-resolved branch for the same reason)."""
    telemetry.reset_all()
    clock = _FakeClock()
    router = ServingRouter(clock=clock, health_interval_s=3600.0)
    r0 = FakeReplica(cap=0)  # always fast-rejects
    r1 = FakeReplica()
    router.add_replica("r0", r0, signals=r0.signals)
    router.add_replica("r1", r1, signals=r1.signals)
    orig = r0._put

    def racing_put(states, k, policy, cb, shed_cb):
        ok = orig(states, k, policy, cb, shed_cb)  # sync fast-reject
        # the health loop declares r0 dead in the same instant — its
        # sweep finds the still-registered task and resolves it
        router._mark_dead(router._replicas["r0"], "raced sweep")
        return ok

    r0._put = racing_put
    sheds = []
    ok = router.put_task(
        np.zeros(STATE, np.uint8), lambda *a: None,
        shed_callback=sheds.append,
    )
    assert ok is False
    # exactly ONE typed outcome, delivered by the sweep
    assert len(sheds) == 1 and sheds[0].reason == "replica_lost"
    # the healthy replica never saw the already-resolved task
    assert r1.tasks == []
    assert router._replicas["r1"].outstanding_rows == 0
    assert not router._replicas["r1"].outstanding
    router.stop()


def test_replica_set_refuses_spawn_after_close():
    """A scale-up tick racing teardown must not register a replica that
    nothing will ever stop: after close(), scale_to is a no-op and
    _spawn refuses (a replica built mid-close is torn down, not leaked)."""
    telemetry.reset_all()
    clock = _FakeClock()
    router = ServingRouter(clock=clock, health_interval_s=3600.0)
    made = []

    def factory(idx):
        rep = FakeReplica()
        made.append(rep)
        return rep

    rs = ReplicaSet(
        router, factory, min_replicas=1, max_replicas=4,
        signals=lambda idx, pred: pred.signals,
    )
    rs.start(1)
    rs.close()
    assert made[0].stopped
    assert rs.scale_to(3) == 0  # no-op: teardown won
    with pytest.raises(RuntimeError):
        rs._spawn()
    assert len(made) == 1 and router.live_count() == 0
    router.stop()


def test_control_loops_survive_raising_tick():
    """One raising tick must not kill the ReplicaAutoscaler or
    PromotionController thread for the rest of the run."""
    telemetry.reset_all()
    clock = _FakeClock()
    router = ServingRouter(clock=clock, health_interval_s=3600.0)
    rep = FakeReplica()
    router.add_replica("r0", rep, signals=rep.signals)
    rs = ReplicaSet(
        router, lambda idx: FakeReplica(), min_replicas=1, max_replicas=2,
        signals=lambda idx, pred: pred.signals,
    )
    for ctor in (
        lambda: ReplicaAutoscaler(
            rs, ServingScalerPolicy(slo_ms=50.0), interval_s=0.01
        ),
        lambda: PromotionController(router, interval_s=0.01),
    ):
        loop = ctor()
        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("tick blew up")

        loop.tick = boom
        loop.start()
        deadline = time.monotonic() + 5
        while len(calls) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(calls) >= 3, "loop died after the first raising tick"
        assert loop.is_alive()
        loop.stop()
        loop.join(2)
    router.stop()


def test_raising_put_rolls_back_registration():
    """A put that RAISES (unknown policy, oversize block) propagates to
    the caller — but the router-side registration must roll back, or the
    phantom outstanding rows repel least-loaded dispatch forever and a
    later death sweep double-delivers a shed."""
    router, (r0,), clock = _router(n_replicas=1)
    with pytest.raises(KeyError):
        router.put_task(
            np.zeros(STATE, np.uint8), lambda *a: None, policy="nope"
        )
    assert router._replicas["r0"].outstanding_rows == 0
    assert not router._replicas["r0"].outstanding
    # the replica still serves normal traffic
    served = []
    assert router.put_task(
        np.zeros(STATE, np.uint8), lambda *a: served.append(1)
    )
    r0.serve()
    assert served == [1]
    # a death sweep re-sheds only the live registrations — never the
    # raised task (its caller already saw the exception)
    sheds = []
    router.put_task(
        np.zeros(STATE, np.uint8), lambda *a: None,
        shed_callback=sheds.append,
    )
    r0.alive = False
    router.health_tick()
    clock.advance(1e9)
    router.health_tick()
    assert len(sheds) == 1 and sheds[0].reason == "replica_lost"
    router.stop()


# -- the promotion controller ------------------------------------------------


def _promotion_rig(**kw):
    router, reps, clock = _router()
    kw.setdefault("min_samples", 5)
    kw.setdefault("min_decide_tasks", 4)
    kw.setdefault("fraction", 0.5)
    kw.setdefault("slo_ms", 50.0)
    ctrl = PromotionController(router, **kw)
    return router, reps, clock, ctrl


def test_promotion_on_statistical_win_with_flight_snapshot():
    router, (r0, r1), clock, ctrl = _promotion_rig()
    ctrl.start_canary({"v": "candidate"})
    assert router.canary() == ("canary", 0.5)
    # serve canary traffic inside the SLO (fake clock never advances ->
    # latency 0)
    for _ in range(8):
        router.put_task(np.zeros(STATE, np.uint8), lambda a, v, lp: None)
    r0.serve()
    r1.serve()
    # the canary's reward stream clearly beats the default's
    for i in range(8):
        ctrl.observe_reward("canary", 10.0 + 0.1 * i)
        ctrl.observe_reward("default", 1.0 + 0.1 * i)
    ctrl.tick()
    assert ctrl.state == PromotionController.PROMOTED
    assert router.canary() is None
    assert router.flush_params(10.0)
    # every replica now serves the candidate as DEFAULT
    assert r0.published[-1] == ("default", {"v": "candidate"})
    assert r1.published[-1] == ("default", {"v": "candidate"})
    ev = _flight_events("canary_promote")
    assert len(ev) == 1
    # the decision carries its input snapshot
    assert ev[0]["reward_n_canary"] == 8 and ev[0]["welch_z"] > 1.96
    assert ev[0]["canary_p99_ms"] is not None
    assert telemetry.registry("orchestrator").scalars()[
        "canary_promotions_total"] == 1


def test_rollback_on_slo_breach_with_flight_snapshot():
    router, (r0, r1), clock, ctrl = _promotion_rig()
    ctrl.start_canary({"v": "bad"})
    # canary traffic breaches the SLO: 200 ms between admit and serve
    for _ in range(8):
        router.put_task(np.zeros(STATE, np.uint8), lambda a, v, lp: None)
    clock.advance(0.2)
    r0.serve()
    r1.serve()
    ctrl.tick()
    assert ctrl.state == PromotionController.ROLLED_BACK
    assert router.canary() is None  # the split cleared, default serves on
    ev = _flight_events("canary_rollback")
    assert len(ev) == 1 and ev[0]["why"] == "slo_breach"
    assert ev[0]["canary_p99_ms"] > 50.0
    assert telemetry.registry("orchestrator").scalars()[
        "canary_rollbacks_total"] == 1
    # default keeps serving after the rollback
    served = []
    router.put_task(np.zeros(STATE, np.uint8), lambda a, v, lp: served.append(1))
    r0.serve()
    r1.serve()
    assert served == [1]


def test_rollback_on_reward_loss():
    router, (r0, r1), clock, ctrl = _promotion_rig()
    ctrl.start_canary({"v": "worse"})
    for _ in range(8):
        router.put_task(np.zeros(STATE, np.uint8), lambda a, v, lp: None)
    r0.serve()
    r1.serve()
    for i in range(8):
        ctrl.observe_reward("canary", 1.0 + 0.1 * i)
        ctrl.observe_reward("default", 10.0 + 0.1 * i)
    ctrl.tick()
    assert ctrl.state == PromotionController.ROLLED_BACK
    assert _flight_events("canary_rollback")[-1]["why"] == "reward_loss"


def test_insufficient_evidence_keeps_watching():
    router, (r0, r1), clock, ctrl = _promotion_rig(min_samples=50)
    ctrl.start_canary({"v": "x"})
    for i in range(4):
        ctrl.observe_reward("canary", 10.0 + i)
        ctrl.observe_reward("default", 1.0 + i)
    ctrl.tick()
    assert ctrl.state == PromotionController.WATCHING
    assert router.canary() is not None


def test_reward_win_without_serving_evidence_does_not_promote():
    """An external reward feed can outrun routed canary traffic; below
    min_decide_tasks the SLO-breach check never runs, so a reward win
    with no serving evidence must KEEP WATCHING — not republish an
    un-latency-tested candidate as default everywhere."""
    router, (r0, r1), clock, ctrl = _promotion_rig()
    n0 = len(_flight_events("canary_promote"))
    ctrl.start_canary({"v": "candidate"})
    # decisive reward win arrives before the canary served ANY traffic
    for i in range(8):
        ctrl.observe_reward("canary", 10.0 + 0.1 * i)
        ctrl.observe_reward("default", 1.0 + 0.1 * i)
    ctrl.tick()
    assert ctrl.state == PromotionController.WATCHING
    assert router.canary() is not None
    assert len(_flight_events("canary_promote")) == n0
    # once the canary carries real traffic inside the SLO, the same
    # reward evidence promotes
    for _ in range(8):
        router.put_task(np.zeros(STATE, np.uint8), lambda a, v, lp: None)
    r0.serve()
    r1.serve()
    ctrl.tick()
    assert ctrl.state == PromotionController.PROMOTED
    ev = _flight_events("canary_promote")
    assert len(ev) == n0 + 1 and ev[-1]["canary_tasks"] >= 4


def test_welch_z():
    import collections

    a = collections.deque([10.0, 10.1, 10.2, 9.9])
    b = collections.deque([1.0, 1.1, 0.9, 1.05])
    assert welch_z(a, b) > 10
    assert welch_z(b, a) < -10
    assert welch_z(collections.deque([1.0]), b) is None
    same = collections.deque([2.0, 2.0, 2.0])
    assert welch_z(same, collections.deque([2.0, 2.0])) is None
    assert welch_z(
        collections.deque([3.0, 3.0]), collections.deque([2.0, 2.0])
    ) == float("inf")


# -- integration: real replicas, one killed mid-load -------------------------


class _NullServingPred(BatchedPredictor):
    """Real scheduler machinery over a host-side null device (the
    test_serving pattern); ``die=True`` makes the next dispatch raise —
    killing the scheduler thread exactly like a SIGKILL leaves a replica:
    queue intact, nobody serving it."""

    service_s = 0.0
    die = False

    def _dispatch(self, params, batch):
        if self.die:
            raise RuntimeError("injected replica death")
        b = np.asarray(batch)
        k = b.shape[0]
        acts = (np.arange(k) % N_ACTIONS).astype(np.int32)
        return k, (
            acts, np.zeros(k, np.float32), np.full(k, -1.0, np.float32), acts
        )

    def _collect(self, handle):
        if self.service_s:
            time.sleep(self.service_s)
        return handle[1]


@pytest.mark.slow
def test_killed_real_replica_traffic_rebalances_without_wedging():
    """ISSUE 15 acceptance: a replica whose scheduler dies mid-load is
    detected via its thread liveness, its outstanding tasks come back as
    TYPED replica_lost sheds (the masters' uniform-fallback path — no
    lockstep server ever wedges waiting on a corpse), and the surviving
    replica absorbs the traffic."""
    telemetry.reset_all()
    model = SimpleNamespace(num_actions=N_ACTIONS, apply=None)
    preds = [
        _NullServingPred(
            model, {}, batch_size=8, coalesce_ms=0.0, queue_depth=64,
            slo_ms=1000.0, tele_role=replica_role("predictor", i),
        )
        for i in range(2)
    ]
    router = ServingRouter(health_interval_s=0.05)
    for i, p in enumerate(preds):
        p.start()
        router.add_replica(f"r{i}", p)
    router.start()
    served, sheds = [], []
    lock = threading.Lock()

    def cb(a, v, lp):
        with lock:
            served.append(1)

    def shed_cb(rej):
        with lock:
            sheds.append(rej.reason)

    try:
        # healthy baseline over both replicas
        for _ in range(6):
            router.put_block_task(_block(4), cb, shed_callback=shed_cb)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            with lock:
                if len(served) == 6:
                    break
            time.sleep(0.01)
        assert len(served) == 6
        # kill r0's scheduler mid-load: stuff its queue while it dies
        preds[0].die = True
        for _ in range(20):
            router.put_block_task(_block(4), cb, shed_callback=shed_cb)
        # every task RESOLVES — served by r1, or typed replica_lost from
        # the dead r0 — nobody hangs
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(served) + len(sheds) == 26:
                    break
            time.sleep(0.01)
        with lock:
            assert len(served) + len(sheds) == 26, (
                f"{len(served)} served + {len(sheds)} sheds — a caller "
                "is hung on the dead replica"
            )
            assert all(r == "replica_lost" for r in sheds)
        assert router.replica_states()["r0"] == DEAD
        # the plane keeps serving on the survivor
        n0 = len(served)
        router.put_block_task(_block(4), cb, shed_callback=shed_cb)
        deadline = time.monotonic() + 10
        while len(served) == n0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(served) == n0 + 1
    finally:
        router.stop()
        router.join(timeout=5)
        for p in preds:
            p.stop()
            p.join(timeout=5)


# -- lifecycle/locking regressions (found by ba3cflow) ----------------------


def test_add_replica_seeds_policies_outside_the_router_lock():
    """add_policy reaches jax.device_put on a real predictor; seeding a
    grown replica must not happen under the router-wide lock (F1: a slow
    device would wedge every dispatch and the health loop)."""
    router, reps, clock = _router(n_replicas=1)
    try:
        router.update_params("v1", policy="canary")
        lock_held = []

        class _Seeded(FakeReplica):
            def add_policy(self, pid, params):
                lock_held.append(router._lock._is_owned())
                super().add_policy(pid, params)

        rep = _Seeded()
        router.add_replica("r9", rep, signals=rep.signals)
        assert lock_held, "the grown replica was never seeded"
        assert not any(lock_held), (
            "add_policy ran while the router lock was held"
        )
        assert rep.policies["canary"] == "v1"
    finally:
        router.stop()


def test_add_replica_catches_up_on_params_published_during_seed():
    """A publish that lands between the seed snapshot and the table
    insert must still reach the new replica (via its pump), or it serves
    a stale table until the next publish."""
    router, reps, clock = _router(n_replicas=1)
    try:
        router.update_params("v1", policy="default")

        class _Racy(FakeReplica):
            def add_policy(self, pid, params):
                # a promotion fires mid-registration, after this
                # replica's seed snapshot was taken
                if not self.policies.get("default"):
                    router.update_params("v2", policy="default")
                super().add_policy(pid, params)

        rep = _Racy()
        router.add_replica("r9", rep, signals=rep.signals)
        deadline = time.monotonic() + 5
        while rep.policies["default"] != "v2" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rep.policies["default"] == "v2", (
            "replica kept the stale seed-time params"
        )
    finally:
        router.stop()


def test_stop_joins_every_pump_thread():
    """The router starts one publisher thread per replica; stop() must
    join them, not orphan them (F5) — a wedged daemon thread otherwise
    outlives the router and races interpreter teardown."""
    router, reps, clock = _router(n_replicas=3)
    pumps = [r.pump for r in router._replicas.values()]
    router.stop()
    for p in pumps:
        assert not p.is_alive(), f"{p.name} still running after stop()"


def test_remove_replica_joins_its_pump_thread():
    router, reps, clock = _router(n_replicas=2)
    try:
        pump = router._replicas["r0"].pump
        router.remove_replica("r0")
        assert not pump.is_alive(), (
            "pump thread survived remove_replica — a late publish can "
            "race the owner's drain/stop of the predictor"
        )
    finally:
        router.stop()


def test_stale_health_tick_cannot_resurrect_removed_replica_state():
    """The health loop snapshots the replica list, then recomputes the
    aggregate lock-free. A removal that lands mid-tick must win: the
    tick's writeback may not re-create the removed replica's histogram
    state (the _agg_last entry remove_replica just popped)."""
    router, reps, clock = _router(n_replicas=2)
    try:
        stale = list(router._replicas.values())  # health thread's snapshot
        hist = {"buckets": [5, 3, 1], "count": 9, "unit": 1e-6}
        for r in stale:
            r.last_health = {
                "rows_total": 10.0, "sheds_total": 0.0,
                "serve_hist": hist,
            }
        router.remove_replica("r0")  # races the tick below
        router._recompute_aggregate(stale)
        with router._lock:
            assert "r0" not in router._agg_last, (
                "stale tick resurrected the removed replica's entry"
            )
            assert "r1" in router._agg_last
    finally:
        router.stop()
