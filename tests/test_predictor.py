"""BatchedPredictor: batching, padding buckets, async callbacks, param swap."""

import threading

import jax
import numpy as np

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.predict.server import BatchedPredictor, _next_pow2


def _make(greedy=False, num_threads=1):
    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=4)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8))[
        "params"
    ]
    pred = BatchedPredictor(
        model, params, batch_size=8, num_threads=num_threads, greedy=greedy
    )
    return cfg, model, pred


def test_next_pow2():
    assert [_next_pow2(n) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 16]


def test_sync_predict_shapes_and_padding():
    cfg, _, pred = _make()
    states = np.zeros((5, *cfg.state_shape), np.uint8)  # pads to 8
    actions, values, greedy = pred.predict_batch(states)
    assert actions.shape == (5,) and values.shape == (5,)
    assert greedy.shape == (5,)
    assert ((actions >= 0) & (actions < cfg.num_actions)).all()
    assert ((greedy >= 0) & (greedy < cfg.num_actions)).all()


def test_greedy_matches_argmax():
    cfg, model, pred = _make(greedy=True)
    rng = np.random.default_rng(0)
    states = rng.integers(0, 255, (4, *cfg.state_shape), np.uint8)
    actions, _, greedy = pred.predict_batch(states)
    # with greedy=True the serving actions ARE the argmax channel
    np.testing.assert_array_equal(actions, greedy)


def test_async_callbacks_all_fire():
    cfg, _, pred = _make(num_threads=2)
    pred.start()
    try:
        n = 100
        done = threading.Event()
        results = {}
        lock = threading.Lock()
        rng = np.random.default_rng(1)

        def make_cb(i):
            def cb(action, value, logp):
                with lock:
                    results[i] = (action, value, logp)
                    if len(results) == n:
                        done.set()

            return cb

        for i in range(n):
            pred.put_task(
                rng.integers(0, 255, cfg.state_shape, np.uint8), make_cb(i)
            )
        assert done.wait(timeout=60), f"only {len(results)}/{n} callbacks fired"
        for a, v, lp in results.values():
            assert 0 <= a < cfg.num_actions
            assert np.isfinite(v)
            assert lp <= 0.0  # a log-probability
    finally:
        pred.stop()


def test_update_params_changes_output():
    cfg, model, pred = _make(greedy=True)
    states = np.full((2, *cfg.state_shape), 128, np.uint8)
    _, values_before, _ = pred.predict_batch(states)
    new_params = model.init(
        jax.random.PRNGKey(7), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    pred.update_params(new_params)
    _, values_after, _ = pred.predict_batch(states)
    assert not np.allclose(values_before, values_after)
