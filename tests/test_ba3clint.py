"""tools/ba3clint: per-rule fixtures, suppression semantics, CLI contract.

Every rule must (a) fire on its ``*_flagged.py`` fixture and (b) stay quiet
on its ``*_clean.py`` fixture — the clean fixtures encode the idioms the
real codebase uses, so a rule regression that would spam the repo fails
here first. The CLI tests pin the exit-status contract CI gates on.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.ba3clint import all_rules, lint_file, lint_paths
from tools.ba3clint.engine import suppressions

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULE_IDS = ["J1", "J2", "J3", "J4", "J5", "J6", "A1", "A2", "A3", "A4", "A5", "A6", "A7", "A8", "A9", "A10", "A11", "A12", "A13", "A14", "A15", "A16"]


def _fixture(name):
    p = os.path.join(FIXTURES, name)
    if not os.path.exists(p):
        # path-gated rules keep their fixtures under the directory that
        # activates them (A9 lives in lint_fixtures/predict/)
        p = os.path.join(FIXTURES, "predict", name)
    return p


def _findings(name, rule_id=None):
    out = lint_file(_fixture(name), all_rules())
    if rule_id is not None:
        out = [f for f in out if f.rule == rule_id]
    return out


def test_rule_registry_complete():
    assert [r.id for r in all_rules()] == RULE_IDS
    for r in all_rules():
        assert r.name and r.summary and r.__doc__


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_flagged_fixture_fires(rule_id):
    name = f"{rule_id.lower()}_flagged.py"
    hits = _findings(name, rule_id)
    assert hits, f"{rule_id} produced no findings on {name}"


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_is_silent(rule_id):
    name = f"{rule_id.lower()}_clean.py"
    hits = _findings(name, rule_id)
    assert not hits, f"{rule_id} false-positives on {name}: {hits}"


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixtures_clean_under_every_rule(rule_id):
    """A clean fixture must not trade one rule's silence for another's noise."""
    hits = _findings(f"{rule_id.lower()}_clean.py")
    assert not hits, hits


def test_expected_flag_counts():
    """Pin a few exact counts so rules don't silently widen or narrow."""
    assert len(_findings("a4_flagged.py", "A4")) == 5
    assert len(_findings("a3_flagged.py", "A3")) == 3
    assert len(_findings("j3_flagged.py", "J3")) == 3
    assert len(_findings("a2_flagged.py", "A2")) == 2
    assert len(_findings("a6_flagged.py", "A6")) == 3
    assert len(_findings("a7_flagged.py", "A7")) == 4
    assert len(_findings("j6_flagged.py", "J6")) == 4
    assert len(_findings("a9_flagged.py", "A9")) == 5
    assert len(_findings("a11_flagged.py", "A11")) == 4
    assert len(_findings("a12_flagged.py", "A12")) == 2
    assert len(_findings("a16_flagged.py", "A16")) == 4


def test_a12_file_level_sockopt_timeout_sanctions(tmp_path):
    """RCVTIMEO/SNDTIMEO anywhere in the file bounds its blocking ops."""
    p = tmp_path / "timeo.py"
    p.write_text(
        "import zmq\n"
        "def make(context, addr):\n"
        "    dealer = context.socket(zmq.DEALER)\n"
        "    dealer.setsockopt(zmq.RCVTIMEO, 2000)\n"
        "    dealer.connect(addr)\n"
        "    return dealer.recv()\n"
    )
    hits = [f for f in lint_file(str(p), all_rules()) if f.rule == "A12"]
    assert not hits, hits


def test_a7_exempts_telemetry_package(tmp_path):
    """The registry's own implementation may use print/time.time freely."""
    d = tmp_path / "telemetry"
    d.mkdir()
    f = d / "exporters.py"
    f.write_text("import time\nfps = 3 / (time.time() - 1)\nprint('fps', fps)\n")
    assert [x for x in lint_file(str(f), all_rules()) if x.rule == "A7"] == []
    g = tmp_path / "loop.py"
    g.write_text("import time\nfps = 3 / (time.time() - 1)\n")
    assert [x for x in lint_file(str(g), all_rules()) if x.rule == "A7"]


def test_a9_applies_only_under_predict(tmp_path):
    """The same unbounded queue outside predict/ is A9-silent (A2/A7 own
    the neighboring hazards elsewhere)."""
    src = "import queue\ntasks = queue.Queue()\n"
    outside = tmp_path / "dataflow.py"
    outside.write_text(src)
    assert [f for f in lint_file(str(outside), all_rules()) if f.rule == "A9"] == []
    d = tmp_path / "predict"
    d.mkdir()
    inside = d / "server2.py"
    inside.write_text(src)
    assert [f for f in lint_file(str(inside), all_rules()) if f.rule == "A9"]


def test_suppressions_silence_real_violations():
    assert _findings("suppressed.py") == []
    # ...and the suppression parser sees all three comment forms
    with open(_fixture("suppressed.py")) as fh:
        sup = suppressions(fh.read())
    assert any("A1" in s for s in sup.values())
    assert any("A2" in s for s in sup.values())
    assert any("ALL" in s for s in sup.values())


def test_standalone_comment_suppresses_next_line():
    sup = suppressions("# ba3clint: disable=A2\nx = q.get()\n")
    assert "A2" in sup.get(1, set()) and "A2" in sup.get(2, set())


def test_syntax_error_is_reported_not_raised(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    out = lint_file(str(bad), all_rules())
    assert [f.rule for f in out] == ["E001"]


def test_submodule_import_does_not_shadow_package_resolution(tmp_path):
    """`import jax.numpy` binds the name `jax`, not `jax.numpy` — J-rules
    must still resolve jax.jit/jax.device_get in such files."""
    f = tmp_path / "sub.py"
    f.write_text(
        "import jax.numpy\n"
        "def run(fns, xs):\n"
        "    for fn in fns:\n"
        "        y = jax.jit(fn)(xs)\n"
        "        print(jax.device_get(y))\n"
    )
    rules = {fi.rule for fi in lint_file(str(f), all_rules())}
    assert {"J1", "J2"} <= rules, rules


def test_missing_lint_path_fails_loudly(tmp_path):
    """A mistyped gate target must error, not pass green over zero files."""
    with pytest.raises(FileNotFoundError):
        lint_paths([str(tmp_path / "no_such_dir")], all_rules())
    r = _run_cli(str(tmp_path / "no_such_dir"))
    assert r.returncode == 2
    assert "does not exist" in r.stderr


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.ba3clint", *args],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )


def test_cli_nonzero_on_flagged_fixture():
    r = _run_cli(_fixture("a4_flagged.py"))
    assert r.returncode == 1
    assert "[A4]" in r.stdout


def test_cli_zero_on_clean_fixture_and_list_rules():
    r = _run_cli(_fixture("a4_clean.py"))
    assert r.returncode == 0
    assert "0 findings" in r.stdout
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid in RULE_IDS:
        assert rid in r.stdout


def test_cli_json_output_and_select():
    r = _run_cli("--format", "json", "--select", "A4", _fixture("a4_flagged.py"))
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload and all(f["rule"] == "A4" for f in payload)
    assert {"path", "line", "col", "rule", "message"} <= set(payload[0])
    r = _run_cli("--select", "NOPE", _fixture("a4_flagged.py"))
    assert r.returncode == 2


def test_repo_tree_is_lint_clean():
    """The acceptance gate: the shipped tree has no unsuppressed findings."""
    findings = lint_paths(
        [
            os.path.join(REPO_ROOT, "distributed_ba3c_tpu"),
            os.path.join(REPO_ROOT, "scripts"),
            os.path.join(REPO_ROOT, "train.py"),
            os.path.join(REPO_ROOT, "bench.py"),
        ],
        all_rules(),
    )
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in findings
    )


def test_cli_sarif_output(tmp_path):
    sarif_path = tmp_path / "lint.sarif"
    r = _run_cli("--sarif", str(sarif_path), _fixture("a4_flagged.py"))
    assert r.returncode == 1
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ba3clint"
    assert {rd["id"] for rd in run["tool"]["driver"]["rules"]} >= set(RULE_IDS)
    assert run["results"] and run["results"][0]["ruleId"]


def test_cli_check_suppressions(tmp_path):
    live = tmp_path / "live.py"
    live.write_text(
        "import queue\n"
        "def pull(q: 'queue.Queue'):\n"
        "    return q.get()  # ba3clint: disable=A2 — fixture\n"
    )
    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # ba3clint: disable=A2 — nothing here\n")
    assert _run_cli("--check-suppressions", str(live)).returncode == 0
    r = _run_cli("--check-suppressions", str(stale))
    assert r.returncode == 1
    assert "[S001]" in r.stdout and "A2" in r.stdout
