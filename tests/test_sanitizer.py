"""utils/sanitizer.py: the BA3C_SANITIZE=1 actor-plane race sanitizer.

Negative tests prove violations are caught (cross-thread structural table
writes, second live queue consumer); the integration test proves the real
actor plane produces NO findings under sanitization — the conventions the
suppressed ba3clint-A3 sites claim actually hold at runtime.
"""

import functools
import queue
import threading
import time

import pytest

from distributed_ba3c_tpu.utils import sanitizer


@pytest.fixture(autouse=True)
def _clean_registry():
    sanitizer.reset()
    yield
    sanitizer.reset()


def test_disabled_by_default_returns_plain_objects(monkeypatch):
    monkeypatch.delenv("BA3C_SANITIZE", raising=False)
    table = sanitizer.wrap_client_table(dict, name="t")
    assert not isinstance(table, sanitizer.SanitizedClientTable)
    table["k"]  # defaultdict behavior preserved
    q = queue.Queue()
    assert sanitizer.wrap_queue(q, name="q") is q
    sanitizer.claim_owner(q)  # no-op on unwrapped objects


def test_client_table_cross_thread_structural_write_fails(monkeypatch):
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    table = sanitizer.wrap_client_table(dict, name="master.clients")
    assert isinstance(table, sanitizer.SanitizedClientTable)
    table[b"pre-claim"]  # unclaimed: setup-phase creation is unrestricted

    errors = []

    def owner_loop(claimed):
        table.claim_owner()
        claimed.set()
        table[b"owned"] = {}
        del table[b"owned"]

    claimed = threading.Event()
    t = threading.Thread(target=owner_loop, args=(claimed,), daemon=True)
    t.start()
    assert claimed.wait(5)
    t.join(timeout=5)

    # reads from a foreign thread are fine
    assert b"pre-claim" in table
    # structural create from a foreign thread (the defaultdict-resurrection
    # race) must fail loudly and be recorded
    with pytest.raises(sanitizer.SanitizerError):
        table[b"resurrected"]
    with pytest.raises(sanitizer.SanitizerError):
        del table[b"pre-claim"]
    # every structural-mutation spelling is covered, not just []/del
    with pytest.raises(sanitizer.SanitizerError):
        table.setdefault(b"via-setdefault", {})
    with pytest.raises(sanitizer.SanitizerError):
        table.update({b"via-update": {}})
    with pytest.raises(sanitizer.SanitizerError):
        table.popitem()
    assert b"via-setdefault" not in table and b"via-update" not in table
    assert len(sanitizer.findings()) == 5
    assert "cross-thread mutation" in sanitizer.findings()[0]


def test_queue_second_live_consumer_fails(monkeypatch):
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    inner = queue.Queue()
    q = sanitizer.wrap_queue(inner, name="send_queue")
    assert isinstance(q, sanitizer.SanitizedQueue)
    assert q.maxsize == inner.maxsize

    stop = threading.Event()

    def consumer():
        while not stop.is_set():
            try:
                q.get(timeout=0.05)
            except queue.Empty:
                pass

    t = threading.Thread(target=consumer, daemon=True, name="drain")
    t.start()
    try:
        q.put("item")  # producers are unrestricted
        time.sleep(0.1)
        with pytest.raises(sanitizer.SanitizerError):
            q.get_nowait()  # main thread becomes a SECOND live consumer
        assert sanitizer.findings()
    finally:
        stop.set()
        t.join(timeout=5)
    # after the consumer thread exits, the slot re-arms: sequential
    # ownership across tests is not a race
    sanitizer.reset()
    q.put("later")
    assert q.get(timeout=1) == "later"
    assert sanitizer.findings() == []


def test_sanitized_actor_plane_has_no_findings(tmp_path, monkeypatch):
    """The real ZMQ actor plane (simulator procs -> master -> predictor ->
    train queue) runs clean under BA3C_SANITIZE=1: the client table is only
    structurally mutated by the master loop and each queue has one drain
    thread — the runtime half of the suppressed ba3clint-A3 justifications."""
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    import jax
    import numpy as np

    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.actors.simulator import (
        SimulatorProcess,
        default_pipes,
    )
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.fake import build_fake_player
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.predict.server import BatchedPredictor
    from distributed_ba3c_tpu.utils.concurrency import ensure_proc_terminate

    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=4)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=4, num_threads=1)

    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s, s2c, predictor, gamma=cfg.gamma,
        local_time_max=cfg.local_time_max,
    )
    assert isinstance(master.clients, sanitizer.SanitizedClientTable)
    assert isinstance(master.send_queue, sanitizer.SanitizedQueue)
    assert isinstance(master.queue, sanitizer.SanitizedQueue)

    build = functools.partial(
        build_fake_player,
        image_size=cfg.image_size,
        frame_history=cfg.frame_history,
        num_actions=cfg.num_actions,
    )
    procs = [SimulatorProcess(i, c2s, s2c, build) for i in range(2)]
    ensure_proc_terminate(procs)
    predictor.start()
    master.start()
    for p in procs:
        p.start()
    try:
        got = 0
        deadline = time.monotonic() + 120
        while got < 32 and time.monotonic() < deadline:
            try:
                master.queue.get(timeout=5)
                got += 1
            except queue.Empty:
                pass
        assert got >= 32, "sanitized actor plane produced too few datapoints"
    finally:
        for p in procs:
            p.terminate()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)
    assert sanitizer.findings() == [], sanitizer.findings()
