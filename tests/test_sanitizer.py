"""utils/sanitizer.py: the BA3C_SANITIZE=1 actor-plane race sanitizer.

Negative tests prove violations are caught (cross-thread structural table
writes, second live queue consumer); the integration test proves the real
actor plane produces NO findings under sanitization — the conventions the
suppressed ba3clint-A3 sites claim actually hold at runtime.
"""

import functools
import queue
import threading
import time

import pytest

from distributed_ba3c_tpu.utils import sanitizer


@pytest.fixture(autouse=True)
def _clean_registry():
    sanitizer.reset()
    yield
    sanitizer.reset()


def test_disabled_by_default_returns_plain_objects(monkeypatch):
    monkeypatch.delenv("BA3C_SANITIZE", raising=False)
    table = sanitizer.wrap_client_table(dict, name="t")
    assert not isinstance(table, sanitizer.SanitizedClientTable)
    table["k"]  # defaultdict behavior preserved
    q = queue.Queue()
    assert sanitizer.wrap_queue(q, name="q") is q
    sanitizer.claim_owner(q)  # no-op on unwrapped objects


def test_client_table_cross_thread_structural_write_fails(monkeypatch):
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    table = sanitizer.wrap_client_table(dict, name="master.clients")
    assert isinstance(table, sanitizer.SanitizedClientTable)
    table[b"pre-claim"]  # unclaimed: setup-phase creation is unrestricted

    errors = []

    def owner_loop(claimed):
        table.claim_owner()
        claimed.set()
        table[b"owned"] = {}
        del table[b"owned"]

    claimed = threading.Event()
    t = threading.Thread(target=owner_loop, args=(claimed,), daemon=True)
    t.start()
    assert claimed.wait(5)
    t.join(timeout=5)

    # reads from a foreign thread are fine
    assert b"pre-claim" in table
    # structural create from a foreign thread (the defaultdict-resurrection
    # race) must fail loudly and be recorded
    with pytest.raises(sanitizer.SanitizerError):
        table[b"resurrected"]
    with pytest.raises(sanitizer.SanitizerError):
        del table[b"pre-claim"]
    # every structural-mutation spelling is covered, not just []/del
    with pytest.raises(sanitizer.SanitizerError):
        table.setdefault(b"via-setdefault", {})
    with pytest.raises(sanitizer.SanitizerError):
        table.update({b"via-update": {}})
    with pytest.raises(sanitizer.SanitizerError):
        table.popitem()
    assert b"via-setdefault" not in table and b"via-update" not in table
    assert len(sanitizer.findings()) == 5
    assert "cross-thread mutation" in sanitizer.findings()[0]


def test_queue_second_live_consumer_fails(monkeypatch):
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    inner = queue.Queue()
    q = sanitizer.wrap_queue(inner, name="send_queue")
    assert isinstance(q, sanitizer.SanitizedQueue)
    assert q.maxsize == inner.maxsize

    stop = threading.Event()

    def consumer():
        while not stop.is_set():
            try:
                q.get(timeout=0.05)
            except queue.Empty:
                pass

    t = threading.Thread(target=consumer, daemon=True, name="drain")
    t.start()
    try:
        q.put("item")  # producers are unrestricted
        time.sleep(0.1)
        with pytest.raises(sanitizer.SanitizerError):
            q.get_nowait()  # main thread becomes a SECOND live consumer
        assert sanitizer.findings()
    finally:
        stop.set()
        t.join(timeout=5)
    # after the consumer thread exits, the slot re-arms: sequential
    # ownership across tests is not a race
    sanitizer.reset()
    q.put("later")
    assert q.get(timeout=1) == "later"
    assert sanitizer.findings() == []


def test_sanitized_actor_plane_has_no_findings(tmp_path, monkeypatch):
    """The real ZMQ actor plane (simulator procs -> master -> predictor ->
    train queue) runs clean under BA3C_SANITIZE=1: the client table is only
    structurally mutated by the master loop and each queue has one drain
    thread — the runtime half of the suppressed ba3clint-A3 justifications."""
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    import jax
    import numpy as np

    from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster
    from distributed_ba3c_tpu.actors.simulator import (
        SimulatorProcess,
        default_pipes,
    )
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.envs.fake import build_fake_player
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.predict.server import BatchedPredictor
    from distributed_ba3c_tpu.utils.concurrency import ensure_proc_terminate

    cfg = BA3CConfig(image_size=(16, 16), fc_units=16, num_actions=4)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, *cfg.state_shape), np.uint8)
    )["params"]
    predictor = BatchedPredictor(model, params, batch_size=4, num_threads=1)

    c2s, s2c = f"ipc://{tmp_path}/c2s", f"ipc://{tmp_path}/s2c"
    master = BA3CSimulatorMaster(
        c2s, s2c, predictor, gamma=cfg.gamma,
        local_time_max=cfg.local_time_max,
    )
    assert isinstance(master.clients, sanitizer.SanitizedClientTable)
    assert isinstance(master.send_queue, sanitizer.SanitizedQueue)
    assert isinstance(master.queue, sanitizer.SanitizedQueue)

    build = functools.partial(
        build_fake_player,
        image_size=cfg.image_size,
        frame_history=cfg.frame_history,
        num_actions=cfg.num_actions,
    )
    procs = [SimulatorProcess(i, c2s, s2c, build) for i in range(2)]
    ensure_proc_terminate(procs)
    predictor.start()
    master.start()
    for p in procs:
        p.start()
    try:
        got = 0
        deadline = time.monotonic() + 120
        while got < 32 and time.monotonic() < deadline:
            try:
                master.queue.get(timeout=5)
                got += 1
            except queue.Empty:
                pass
        assert got >= 32, "sanitized actor plane produced too few datapoints"
    finally:
        for p in procs:
            p.terminate()
        master.close()
        predictor.stop()
        predictor.join(timeout=5)
        for p in procs:
            p.join(timeout=5)
    assert sanitizer.findings() == [], sanitizer.findings()


# -- lock-guarded structures (the serving plane's tables) -------------------


def test_guarded_wrappers_disabled_return_plain(monkeypatch):
    monkeypatch.delenv("BA3C_SANITIZE", raising=False)
    lock = threading.RLock()
    assert type(sanitizer.wrap_guarded_dict(lock, "t")) is dict
    assert type(sanitizer.wrap_guarded_list(lock, "l")) is list


def test_guarded_dict_requires_lock_for_structural_writes(monkeypatch):
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    lock = threading.RLock()
    table = sanitizer.wrap_guarded_dict(lock, "router.replicas")
    assert isinstance(table, sanitizer.SanitizedGuardedDict)
    with lock:
        table["r0"] = "rep"
    assert "r0" in table and table["r0"] == "rep"  # lock-free reads are fine
    with pytest.raises(sanitizer.SanitizerError):
        table["r1"] = "rep"
    with pytest.raises(sanitizer.SanitizerError):
        table.pop("r0")
    with pytest.raises(sanitizer.SanitizerError):
        table.update({"r2": "rep"})
    with lock:
        assert table.pop("r0") == "rep"
    assert len(sanitizer.findings()) == 3


def test_guarded_dict_ignores_another_threads_hold(monkeypatch):
    """RLock ownership is per-thread: someone ELSE holding the lock does
    not license this thread's write."""
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    lock = threading.RLock()
    table = sanitizer.wrap_guarded_dict(lock, "t")
    held, release = threading.Event(), threading.Event()

    def holder():
        with lock:
            held.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert held.wait(5)
    try:
        with pytest.raises(sanitizer.SanitizerError):
            table["k"] = 1
    finally:
        release.set()
        t.join(timeout=5)


def test_guarded_list_requires_lock_for_structural_writes(monkeypatch):
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    lock = threading.RLock()
    roster = sanitizer.wrap_guarded_list(lock, "replica_set.live")
    assert isinstance(roster, sanitizer.SanitizedGuardedList)
    with lock:
        roster.append("r0")
        roster.append("r1")
    assert list(roster) == ["r0", "r1"] and "r0" in roster
    with pytest.raises(sanitizer.SanitizerError):
        roster.remove("r0")
    with pytest.raises(sanitizer.SanitizerError):
        roster.pop()
    with pytest.raises(sanitizer.SanitizerError):
        del roster[:]
    with lock:
        del roster[:]  # the close() idiom: clear in place, under the lock
    assert list(roster) == []
    assert len(sanitizer.findings()) == 3


def test_sanitized_routed_serving_plane_has_no_findings(monkeypatch):
    """The routed serving plane (ServingRouter + ReplicaSet) runs clean
    under BA3C_SANITIZE=1 through its full lifecycle — spawn, traffic,
    replica death, reconcile-replace, scale up/down, teardown. Every
    structural write to the router's replica table and the set's roster
    is lock-serialized; the sanitizer proves it at runtime."""
    monkeypatch.setenv("BA3C_SANITIZE", "1")
    import numpy as np

    from distributed_ba3c_tpu import telemetry
    from distributed_ba3c_tpu.orchestrate.serving import ReplicaSet
    from distributed_ba3c_tpu.predict.router import ServingRouter

    telemetry.reset_all()

    class _Fake:
        num_actions = 4

        def __init__(self):
            self.tasks = []
            self.policies = {"default": None}
            self.alive = True
            self.stopped = False

        def put_block_task(self, states, cb, deadline=None, policy=None,
                           shed_callback=None, trace=None):
            self.tasks.append((states, cb))
            return True

        def add_policy(self, pid, params):
            self.policies[pid] = params

        def update_params(self, params, policy="default"):
            self.policies[policy] = params

        def start(self):
            pass

        def stop(self):
            self.stopped = True

        def join(self, timeout=None):
            pass

        def serve(self):
            while self.tasks:
                states, cb = self.tasks.pop(0)
                k = states.shape[0]
                cb(np.zeros(k, np.int32), np.zeros(k, np.float32),
                   np.full(k, -1.0, np.float32))

        def signals(self):
            return {
                "alive": 1.0 if self.alive else 0.0, "rows_total": 0.0,
                "sheds_total": 0.0, "queue_depth": 0.0, "inflight": 0.0,
                "serve_p99_ms": 1.0,
            }

    router = ServingRouter(health_interval_s=3600.0)
    assert isinstance(router._replicas, sanitizer.SanitizedGuardedDict)
    made = []

    def factory(idx):
        rep = _Fake()
        made.append(rep)
        return rep

    rs = ReplicaSet(
        router, factory, min_replicas=2, max_replicas=4,
        signals=lambda idx, pred: pred.signals, retire_grace_s=0.05,
    )
    assert isinstance(rs._live, sanitizer.SanitizedGuardedList)
    router.replica_set = rs
    rs.start(2)
    router.start()
    try:
        served = []
        for _ in range(4):
            router.put_block_task(
                np.zeros((4, 8, 8, 1), np.uint8),
                lambda a, v, lp: served.append(1),
            )
        for rep in made:
            rep.serve()
        assert len(served) == 4
        # replica death -> reconcile replacement exercises every
        # structural-write path: router pop/insert, roster remove/append
        made[0].alive = False
        router.health_tick()
        assert rs.reconcile()
        rs.scale_to(3, reason="test-up")
        rs.scale_to(2, reason="test-down")
        assert router.live_count() == 2
    finally:
        router.stop()  # closes the ReplicaSet via router.replica_set
        router.join(timeout=5)
    assert sanitizer.findings() == [], sanitizer.findings()
