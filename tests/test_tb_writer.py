"""TensorBoard scalar plane: events written by StatHolder are readable back."""

import glob
import os

import pytest


def _read_scalars(log_dir):
    """Parse tfevents files back into {tag: [(step, value)]}."""
    tbrl = pytest.importorskip("tensorboard.backend.event_processing.event_accumulator")
    files = glob.glob(os.path.join(log_dir, "events.out.tfevents.*"))
    assert files, f"no event files in {log_dir}"
    acc = tbrl.EventAccumulator(log_dir)
    acc.Reload()
    return {
        tag: [(s.step, s.value) for s in acc.Scalars(tag)]
        for tag in acc.Tags()["scalars"]
    }


def test_stat_holder_emits_tb_events(tmp_path):
    from distributed_ba3c_tpu.utils.stats import StatHolder

    holder = StatHolder(str(tmp_path))
    holder.add_stat("mean_score", 12.5)
    holder.add_stat("loss", 0.25)
    holder.add_stat("global_step", 100)
    holder.finalize()
    holder.add_stat("mean_score", 15.0)
    holder.add_stat("global_step", 200)
    holder.finalize()
    holder.close()

    scalars = _read_scalars(str(tmp_path))
    assert scalars["mean_score"] == [(100, 12.5), (200, 15.0)]
    assert scalars["loss"] == [(100, 0.25)]
    # stat.json still written alongside (same metric names)
    import json

    stats = json.load(open(tmp_path / "stat.json"))
    assert stats[0]["mean_score"] == 12.5


def test_tb_writer_direct(tmp_path):
    from distributed_ba3c_tpu.utils.tb_writer import TBScalarWriter

    w = TBScalarWriter(str(tmp_path))
    for i in range(5):
        w.add_scalar("fps", 1000.0 + i, i)
    w.close()
    scalars = _read_scalars(str(tmp_path))
    assert [v for _, v in scalars["fps"]] == [1000.0, 1001.0, 1002.0, 1003.0, 1004.0]
