"""bench.py's measurement surface: K validation + the K-sweep JSON contract.

The bench number is the driver's round metric, and round 4's contaminated
K-sweep showed what an untested measurement path costs — these tests pin
the parts that don't need a chip: the steps_per_dispatch contract on
``bench_fused`` and ``scripts/ksweep_bench.py``'s one-JSON-line stdout
(diagnostics on stderr) including the windows_by_K provenance field the
committed artifact (runs/ksweep_r5.json) carries.
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


@pytest.mark.parametrize("bad_k", [0, -1, 3])
def test_bench_fused_rejects_bad_steps_per_dispatch(bad_k):
    # K=3 does not divide iters=8; 0/-1 are out of range. All must raise
    # the designed ValueError BEFORE any compile/dispatch work.
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        bench.bench_fused(n_envs=8, rollout_len=4, iters=8,
                          steps_per_dispatch=bad_k)


def _load_ksweep_module():
    spec = importlib.util.spec_from_file_location(
        "ksweep_bench", REPO / "scripts" / "ksweep_bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ksweep_stdout_is_one_json_line_with_windows(monkeypatch, capsys):
    mod = _load_ksweep_module()

    def fake_bench_fused(n_envs, rollout_len, iters, steps_per_dispatch):
        assert iters % steps_per_dispatch == 0
        return {
            "value": 100.0 + steps_per_dispatch,
            "window_rates": [90.0, 100.0 + steps_per_dispatch, 95.0],
        }

    monkeypatch.setattr(bench, "bench_fused", fake_bench_fused)
    monkeypatch.setattr(mod, "guard_tpu", lambda *a, **kw: None)
    monkeypatch.setattr(
        sys, "argv",
        ["ksweep_bench.py", "--ks", "1,4", "--total", "8", "--tpu_lock", "off"],
    )
    mod.main()

    captured = capsys.readouterr()
    lines = [ln for ln in captured.out.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {lines}"
    payload = json.loads(lines[0])
    assert payload["per_chip_by_K"] == {"1": 101.0, "4": 104.0}
    assert payload["windows_by_K"]["1"] == [90.0, 101.0, 95.0]
    assert payload["windows_by_K"]["4"] == [90.0, 104.0, 95.0]
    # the shape-keyed rows are always present alongside the legacy keys
    assert payload["rows"]["128x20"]["per_chip_by_K"] == {"1": 101.0, "4": 104.0}
    # per-K progress goes to stderr, never stdout
    assert "env-steps/s/chip" in captured.err


def test_ksweep_shard_shape_rows(monkeypatch, capsys):
    # --n_envs 8,16: the shard-shape capture (VERDICT r5 Next #1) emits one
    # row per shape; the legacy single-shape keys are NOT emitted (no one
    # shape is "the" sweep)
    mod = _load_ksweep_module()

    def fake_bench_fused(n_envs, rollout_len, iters, steps_per_dispatch):
        return {
            "value": 1000.0 * n_envs + steps_per_dispatch,
            "window_rates": [1000.0 * n_envs + steps_per_dispatch],
        }

    monkeypatch.setattr(bench, "bench_fused", fake_bench_fused)
    monkeypatch.setattr(mod, "guard_tpu", lambda *a, **kw: None)
    monkeypatch.setattr(
        sys, "argv",
        ["ksweep_bench.py", "--n_envs", "8,16", "--ks", "1,4", "--total", "8",
         "--tpu_lock", "off"],
    )
    mod.main()

    captured = capsys.readouterr()
    lines = [ln for ln in captured.out.splitlines() if ln.strip()]
    assert len(lines) == 1
    payload = json.loads(lines[0])
    assert payload["shape"] == "8x20,16x20"
    assert payload["rows"]["8x20"]["per_chip_by_K"] == {"1": 8001.0, "4": 8004.0}
    assert payload["rows"]["16x20"]["per_chip_by_K"] == {"1": 16001.0, "4": 16004.0}
    assert "per_chip_by_K" not in payload  # legacy keys absent on multi-shape
    assert "windows_by_K" not in payload
