"""Test harness: fake 8-device CPU mesh.

The reference could only validate distributed behavior on a live cluster
(SURVEY.md §4). We do better: XLA's host-platform device-count flag gives an
8-device CPU mesh, so every psum/sharding code path is unit-testable with zero
TPU hardware. Must run before jax is first imported.
"""

import os

# The container's axon sitecustomize force-registers the TPU backend and sets
# JAX_PLATFORMS=axon; a plain setdefault is not enough. Assign the env var AND
# override jax.config right after import (register() re-appends the plugin).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
