"""Test harness: fake 8-device CPU mesh.

The reference could only validate distributed behavior on a live cluster
(SURVEY.md §4). We do better: XLA's host-platform device-count flag gives an
8-device CPU mesh, so every psum/sharding code path is unit-testable with zero
TPU hardware. Must run before jax is first imported.
"""

import os

# The container's axon sitecustomize force-registers the TPU backend and sets
# JAX_PLATFORMS=axon; a plain setdefault is not enough. Assign the env var AND
# override jax.config right after import (register() re-appends the plugin).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import signal
import threading

import numpy as np
import pytest

# Per-test watchdog (round-1 CI hung forever on a wedged jit dispatch; a
# hang must become a failing test, not an eternal run).
_DEFAULT_TIMEOUT = 300
_SLOW_TIMEOUT = 900


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end test")
    config.addinivalue_line(
        "markers", "timeout(seconds): override the per-test SIGALRM watchdog"
    )


@pytest.fixture(autouse=True)
def _watchdog(request):
    marker = request.node.get_closest_marker("timeout")
    if marker:
        seconds = int(marker.args[0])
    elif request.node.get_closest_marker("slow"):
        seconds = _SLOW_TIMEOUT
    else:
        seconds = _DEFAULT_TIMEOUT
    if (
        not hasattr(signal, "SIGALRM")
        or threading.current_thread() is not threading.main_thread()
    ):
        yield
        return

    def _alarm(signum, frame):
        raise TimeoutError(
            f"{request.node.nodeid} exceeded the {seconds}s watchdog"
        )

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
