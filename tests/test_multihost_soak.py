"""Multi-host soak: sustained lockstep over epochs, not just one update.

Two real processes run the fused trainer for many epochs with LR/β schedules
active, per-epoch collective checkpoint saves, and a mid-soak resume from
the shared checkpoint — while ``BA3C_PARAM_DIGEST=1`` makes every rank log a
param digest each epoch. The digest sequences must be IDENTICAL across
ranks for the whole run (the divergence modes a chief/shared-dir setup
worries about: schedule drift, hyper.txt read races, restore mismatch).

Phase B also proves the fused trainer honors live hyper.txt edits: with
``learning_rate: 0`` written to the chief's dir before the resume, params
must FREEZE — every phase-B digest equals the phase-A final digest.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(_WORKER))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["BA3C_PARAM_DIGEST"] = "1"
    return env


def _run_pair(logdir: str, max_epoch: int, load: bool, n_ranks: int = 2) -> list:
    coord = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [
                sys.executable, _WORKER, str(r), str(n_ranks), coord, "soak",
                logdir, str(max_epoch), "load" if load else "fresh",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=_env(),
            cwd=os.path.dirname(os.path.dirname(_WORKER)),
        )
        for r in range(n_ranks)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        assert "CLI_RC 0" in out, out
    return outs


def _digests(out: str) -> list:
    return [
        l.split("param_digest ", 1)[1]
        for l in out.splitlines()
        if "param_digest " in l
    ]


@pytest.mark.slow
def test_soak_lockstep_with_schedules_hyper_and_resume(tmp_path):
    logdir = str(tmp_path / "soak")

    # phase A: 6 epochs with exp schedules + evals + collective ckpt saves
    outs = _run_pair(logdir, max_epoch=6, load=False)
    d0, d1 = (_digests(o) for o in outs)
    assert len(d0) == 6, outs[0]
    assert d0 == d1, "ranks diverged during the schedule soak"

    # live-knob edit between phases: freeze the learner via hyper.txt
    with open(os.path.join(logdir, "hyper.txt"), "w") as f:
        f.write("learning_rate: 0.0\n")

    # phase B: resume mid-soak from the SHARED checkpoint, 4 more epochs
    outs = _run_pair(logdir, max_epoch=10, load=True)
    b0, b1 = (_digests(o) for o in outs)
    assert len(b0) == 4, outs[0]
    assert b0 == b1, "ranks diverged after the mid-soak resume"
    # hyper.txt took effect in the fused trainer: lr=0 froze the params,
    # so every post-resume digest equals the pre-resume final digest
    assert all(d == d0[-1] for d in b0), (d0[-1], b0)


@pytest.mark.slow
def test_soak_lockstep_4_ranks(tmp_path):
    """The >2-rank evidence, in-suite: 4 real jax.distributed processes run
    the fused trainer for 8 epochs with schedules + collective saves; every
    rank's per-epoch digest sequence must be identical (README's manual
    4-rank soak, promoted from prose to a reproducible test)."""
    logdir = str(tmp_path / "soak4")
    outs = _run_pair(logdir, max_epoch=8, load=False, n_ranks=4)
    ds = [_digests(o) for o in outs]
    assert len(ds[0]) == 8, outs[0]
    for r in range(1, 4):
        assert ds[r] == ds[0], f"rank {r} diverged:\n{ds[r]}\nvs\n{ds[0]}"
